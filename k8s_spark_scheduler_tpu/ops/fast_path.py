"""Driver-path fast lane: TensorSnapshot → solver tensors with no
Quantity arithmetic.

Replicates, in vectorized integer math, exactly what the slow path
derives from Quantity metadata:

- the AZ-aware node priority order (nodesorting.go:95-122): zones
  ascending by total (memory, cpu) of *available* resources, nodes by
  (zone priority, memory, cpu, name) — int64 lexsorts, name ties via a
  precomputed rank;
- driver candidates = priority ∩ kube-scheduler's list; executor
  candidates = ready ∧ ¬unschedulable (nodesorting.go:41-64);
- the per-role label-priority stable re-sort
  (nodesorting.go:161-180): configured label values map to ascending
  ranks, any other/missing value sorts last, ties keep the base order —
  a stable integer argsort over precomputed rank arrays;
- the required-node-affinity filter over snapshot label dicts
  (resource.go:292-295).

Only usable when the snapshot is exact; callers fall back to the
Quantity path otherwise.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..state.tensor_snapshot import TensorSnapshot
from .nodesort import LabelPriorityOrder
from .tensorize import INT32_SAFE, ClusterTensor


def _label_ranks(labels_list, order: LabelPriorityOrder) -> np.ndarray:
    """Integer sort keys replicating _label_less_than: configured values
    get their list position, anything else (including a missing label)
    a rank past the end so it sorts last; stability preserves the base
    priority order within equal ranks."""
    value_ranks = {v: i for i, v in enumerate(order.descending_priority_values)}
    big = len(order.descending_priority_values)
    return np.fromiter(
        (value_ranks.get(labels.get(order.name), big) for labels in labels_list),
        dtype=np.int64,
        count=len(labels_list),
    )


def _base_priority_order(
    snap: TensorSnapshot, idx: np.ndarray, avail: np.ndarray
) -> np.ndarray:
    """AZ-aware base node priority over the selected rows
    (nodesorting.go:95-122), shared by the driver and executor fast
    lanes: zones ascending by total (memory, cpu, name) of the selected
    availability; nodes by (zone priority, memory, cpu, name).  Returns
    positions into `idx`."""
    zone_id = snap.zone_id[idx]
    n_zones = len(snap.zone_names)
    zone_mem = np.zeros(n_zones, dtype=np.int64)
    zone_cpu = np.zeros(n_zones, dtype=np.int64)
    np.add.at(zone_mem, zone_id, avail[:, 1])
    np.add.at(zone_cpu, zone_id, avail[:, 0])
    zone_name_rank = np.argsort(np.argsort(np.array(snap.zone_names, dtype=object)))
    zone_order = np.lexsort((zone_name_rank, zone_cpu, zone_mem))
    zone_priority = np.empty(n_zones, dtype=np.int64)
    zone_priority[zone_order] = np.arange(n_zones)

    # snapshot-maintained integer name ranks order exactly like the
    # names; lexsort needs only the ordering, not dense subset ranks
    return np.lexsort(
        (snap.name_rank[idx], avail[:, 0], avail[:, 1], zone_priority[zone_id])
    )


def executor_reschedule_order(
    snap: TensorSnapshot,
    candidate_names: List[str],
    executor_label_priority: Optional[LabelPriorityOrder] = None,
    zone: Optional[str] = None,
) -> Optional[Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]]:
    """Executor priority order + exact availability for the executor
    reschedule path (resource.go:594-663): metadata restricted to the
    kube-scheduler candidate list (optionally one zone for single-AZ
    dynamic allocation), AZ-aware sort keyed on
    avail = allocatable − usage − overhead, executor candidates
    ready ∧ ¬unschedulable, then the label-priority stable re-sort.

    Returns (names_in_order, avail_rows [M,3] int64, overhead_rows
    [M,3] int64, reservation_entry_mask [M] bool) or None when the
    snapshot is inexact.  Zone totals for the AZ sort are computed over
    ALL candidate nodes (including not-ready ones), exactly like the
    slow path's metadata."""
    if not snap.exact:
        return None
    nidx = snap.name_index
    rows = np.fromiter(
        (nidx.get(nm, -1) for nm in candidate_names),
        dtype=np.int64,
        count=len(candidate_names),
    )
    idx = np.unique(rows[rows >= 0])  # dedupe like the slow path's metadata dict
    if zone is not None:
        try:
            zi = snap.zone_names.index(zone)
        except ValueError:
            idx = idx[:0]
        else:
            idx = idx[snap.zone_id[idx] == zi]
    if len(idx) == 0:
        return [], np.zeros((0, 3), np.int64), np.zeros((0, 3), np.int64), np.zeros(0, bool)

    avail = snap.avail[idx]
    order = _base_priority_order(snap, idx, avail)

    exec_ok = snap.ready[idx] & ~snap.unschedulable[idx]
    order = order[exec_ok[order]]
    if executor_label_priority is not None:
        keys = _label_ranks([snap.labels[i] for i in idx], executor_label_priority)
        order = order[np.argsort(keys[order], kind="stable")]

    sel = idx[order]
    return (
        [snap.names[i] for i in sel],
        avail[order],  # == snap.avail[sel] without re-materializing the property
        snap.overhead[sel],
        snap.res_entries[sel],
    )


@dataclass
class _BuildPrep:
    """Avail-independent prework of build_cluster_tensor — everything
    derivable from the node TABLE (names/labels/zones/flags) and the
    request's candidate list, cacheable across Filter requests keyed by
    the snapshot's structure revision (the FIFO hot path rebuilds the
    same structures per request; at 10k nodes this was ~20ms of the
    ~24ms build cost)."""

    idx: np.ndarray            # eligible rows into the snapshot
    names: List[str]
    names_arr: np.ndarray      # object array of names (for permuting)
    is_cand: np.ndarray        # [len(idx)] bool — in the candidate list
    exec_ok_base: np.ndarray   # [len(idx)] bool — ready ∧ ¬unschedulable
    d_keys: Optional[np.ndarray]
    e_keys: Optional[np.ndarray]
    zones: Dict[str, str]      # eligible node → zone name


_PREP_CACHE: OrderedDict = OrderedDict()
_PREP_CACHE_MAX = 32
_prep_lock = threading.Lock()


def _single_in_sig(driver_pod):
    """Hashable signature of the dominant affinity shape (one In
    constraint); None = uncacheable shape."""
    if (
        not driver_pod.node_selector
        and not driver_pod.affinity_terms
        and len(driver_pod.node_affinity) == 1
    ):
        ((key, values),) = driver_pod.node_affinity.items()
        return (key, tuple(sorted(values)))
    return None


def _lp_sig(lp: Optional[LabelPriorityOrder]):
    return None if lp is None else (lp.name, tuple(lp.descending_priority_values))


def _compute_prep(snap, driver_pod, candidate_names, dlp, elp) -> _BuildPrep:
    n = len(snap.names)
    # required node affinity + nodeSelector filter (metadata membership),
    # via the same matcher the slow path uses.  The dominant real-world
    # shape — a single In-constraint on one label (the instance group) —
    # is vectorized; anything else falls back to the general matcher.
    single_in = _single_in_sig(driver_pod)
    if single_in is not None:
        key, values = single_in
        allowed = set(values)
        eligible = np.fromiter(
            (labels.get(key) in allowed for labels in snap.labels),
            dtype=bool,
            count=n,
        )
    else:
        eligible = np.fromiter(
            (driver_pod.matches_labels(labels) for labels in snap.labels),
            dtype=bool,
            count=n,
        )
    idx = np.flatnonzero(eligible)
    if len(idx) == 0:
        idx = np.zeros(0, dtype=np.int64)
    names = [snap.names[i] for i in idx]
    candidate_set = set(candidate_names)
    is_cand = np.fromiter(
        (nm in candidate_set for nm in names), dtype=bool, count=len(names)
    )
    need_labels = dlp is not None or elp is not None
    labels_sel = [snap.labels[i] for i in idx] if need_labels else None
    zone_sel = snap.zone_id[idx]
    return _BuildPrep(
        idx=idx,
        names=names,
        names_arr=np.array(names, dtype=object),
        is_cand=is_cand,
        exec_ok_base=snap.ready[idx] & ~snap.unschedulable[idx],
        d_keys=_label_ranks(labels_sel, dlp) if dlp is not None else None,
        e_keys=_label_ranks(labels_sel, elp) if elp is not None else None,
        zones={
            nm: snap.zone_names[zone_sel[i]] for i, nm in enumerate(names)
        },
    )


def build_prep_keyed(snap, driver_pod, candidate_names, dlp, elp):
    """(prep, key): the avail-independent prework plus the exact cache
    key it lives under — (structure revision, affinity signature,
    candidate tuple, label-priority signatures) — or key=None when the
    affinity shape is uncacheable.  The delta-solve engine keys its
    native solver sessions by the same identity, so a session can only
    ever be consulted for the cluster/candidate shape it was built for."""
    from ..tracing import add_tag

    aff = _single_in_sig(driver_pod)
    key = None
    if aff is not None and snap.structure_key[0] >= 0:
        key = (
            snap.structure_key,
            aff,
            # the tuple itself, not its hash: a hash collision would
            # silently reuse another request's candidate mask
            tuple(candidate_names),
            _lp_sig(dlp),
            _lp_sig(elp),
        )
        with _prep_lock:
            hit = _PREP_CACHE.get(key)
            if hit is not None:
                _PREP_CACHE.move_to_end(key)
                add_tag("prepCache", "hit")
                return hit, key
    # a miss at 10k nodes is ~20ms of the request — worth seeing on the
    # span when hunting a latency outlier
    add_tag("prepCache", "miss" if key is not None else "uncacheable")
    prep = _compute_prep(snap, driver_pod, candidate_names, dlp, elp)
    if key is not None:
        with _prep_lock:
            _PREP_CACHE[key] = prep
            while len(_PREP_CACHE) > _PREP_CACHE_MAX:
                _PREP_CACHE.popitem(last=False)
    return prep, key


def _build_prep(snap, driver_pod, candidate_names, dlp, elp) -> _BuildPrep:
    return build_prep_keyed(snap, driver_pod, candidate_names, dlp, elp)[0]


def build_cluster_tensor(
    snap: TensorSnapshot,
    driver_pod,
    candidate_names: List[str],
    driver_label_priority: Optional[LabelPriorityOrder] = None,
    executor_label_priority: Optional[LabelPriorityOrder] = None,
) -> Optional[Tuple[ClusterTensor, Dict[str, str]]]:
    """(cluster tensor, node→zone map) or None when the fast path can't
    represent the snapshot exactly."""
    if not snap.exact:
        return None
    n = len(snap.names)
    if n == 0:
        # no eligible nodes: an empty tensor is still valid input
        empty = ClusterTensor(
            node_names=[],
            avail=np.zeros((0, 3), np.int64),
            sched=np.zeros((0, 3), np.int64),
            driver_rank=np.zeros(0, np.int32),
            exec_ok=np.zeros(0, bool),
            zone_id=np.zeros(0, np.int32),
            zone_names=[],
            valid=np.zeros(0, bool),
            exact=True,
        )
        return empty, {}

    prep = _build_prep(
        snap, driver_pod, candidate_names, driver_label_priority,
        executor_label_priority,
    )
    idx = prep.idx
    avail = snap.avail[idx]
    sched = snap.schedulable[idx]
    zone_id = snap.zone_id[idx]

    # AZ-aware base priority (shared with the executor lane)
    order = _base_priority_order(snap, idx, avail)

    # per-role label-priority re-sort on top of the base order
    # (nodesorting.go:161-180).  The array order is the EXECUTOR priority
    # order (the solver packs executors in array order); the driver order
    # lives in driver_rank, so the two roles can be re-sorted
    # independently, exactly like the slow path's two stable sorts.
    perm = order
    if prep.e_keys is not None:
        perm = perm[np.argsort(prep.e_keys[perm], kind="stable")]

    # driver order = BASE order ∩ candidates (never the executor-resorted
    # order), stable-sorted by the driver label rank when configured;
    # ranks are then scattered into final array positions
    cand_base_positions = order[np.flatnonzero(prep.is_cand[order])]
    if prep.d_keys is not None:
        cand_base_positions = cand_base_positions[
            np.argsort(prep.d_keys[cand_base_positions], kind="stable")
        ]
    pos_in_array = np.empty(len(perm), dtype=np.int64)
    pos_in_array[perm] = np.arange(len(perm))
    driver_rank = np.full(len(perm), INT32_SAFE, dtype=np.int64)
    driver_rank[pos_in_array[cand_base_positions]] = np.arange(
        len(cand_base_positions)
    )
    ordered_names = list(prep.names_arr[perm])

    cluster = ClusterTensor(
        node_names=ordered_names,
        avail=avail[perm],
        sched=sched[perm],
        driver_rank=driver_rank.astype(np.int32),
        exec_ok=prep.exec_ok_base[perm],
        zone_id=zone_id[perm].astype(np.int32),
        zone_names=list(snap.zone_names),
        valid=np.ones(len(ordered_names), dtype=bool),
        exact=True,
    )
    return cluster, prep.zones
