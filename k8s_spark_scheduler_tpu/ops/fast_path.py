"""Driver-path fast lane: TensorSnapshot → solver tensors with no
Quantity arithmetic.

Replicates, in vectorized integer math, exactly what the slow path
derives from Quantity metadata:

- the AZ-aware node priority order (nodesorting.go:95-122): zones
  ascending by total (memory, cpu) of *available* resources, nodes by
  (zone priority, memory, cpu, name) — int64 lexsorts, name ties via a
  precomputed rank;
- driver candidates = priority ∩ kube-scheduler's list; executor
  candidates = ready ∧ ¬unschedulable (nodesorting.go:41-64);
- the per-role label-priority stable re-sort
  (nodesorting.go:161-180): configured label values map to ascending
  ranks, any other/missing value sorts last, ties keep the base order —
  a stable integer argsort over precomputed rank arrays;
- the required-node-affinity filter over snapshot label dicts
  (resource.go:292-295).

Only usable when the snapshot is exact; callers fall back to the
Quantity path otherwise.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..state.tensor_snapshot import TensorSnapshot
from .nodesort import LabelPriorityOrder
from .tensorize import INT32_SAFE, ClusterTensor


def _label_ranks(labels_list, order: LabelPriorityOrder) -> np.ndarray:
    """Integer sort keys replicating _label_less_than: configured values
    get their list position, anything else (including a missing label)
    a rank past the end so it sorts last; stability preserves the base
    priority order within equal ranks."""
    value_ranks = {v: i for i, v in enumerate(order.descending_priority_values)}
    big = len(order.descending_priority_values)
    return np.fromiter(
        (value_ranks.get(labels.get(order.name), big) for labels in labels_list),
        dtype=np.int64,
        count=len(labels_list),
    )


def _base_priority_order(
    snap: TensorSnapshot, idx: np.ndarray, avail: np.ndarray
) -> np.ndarray:
    """AZ-aware base node priority over the selected rows
    (nodesorting.go:95-122), shared by the driver and executor fast
    lanes: zones ascending by total (memory, cpu, name) of the selected
    availability; nodes by (zone priority, memory, cpu, name).  Returns
    positions into `idx`."""
    zone_id = snap.zone_id[idx]
    n_zones = len(snap.zone_names)
    zone_mem = np.zeros(n_zones, dtype=np.int64)
    zone_cpu = np.zeros(n_zones, dtype=np.int64)
    np.add.at(zone_mem, zone_id, avail[:, 1])
    np.add.at(zone_cpu, zone_id, avail[:, 0])
    zone_name_rank = np.argsort(np.argsort(np.array(snap.zone_names, dtype=object)))
    zone_order = np.lexsort((zone_name_rank, zone_cpu, zone_mem))
    zone_priority = np.empty(n_zones, dtype=np.int64)
    zone_priority[zone_order] = np.arange(n_zones)

    # snapshot-maintained integer name ranks order exactly like the
    # names; lexsort needs only the ordering, not dense subset ranks
    return np.lexsort(
        (snap.name_rank[idx], avail[:, 0], avail[:, 1], zone_priority[zone_id])
    )


def executor_reschedule_order(
    snap: TensorSnapshot,
    candidate_names: List[str],
    executor_label_priority: Optional[LabelPriorityOrder] = None,
    zone: Optional[str] = None,
) -> Optional[Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]]:
    """Executor priority order + exact availability for the executor
    reschedule path (resource.go:594-663): metadata restricted to the
    kube-scheduler candidate list (optionally one zone for single-AZ
    dynamic allocation), AZ-aware sort keyed on
    avail = allocatable − usage − overhead, executor candidates
    ready ∧ ¬unschedulable, then the label-priority stable re-sort.

    Returns (names_in_order, avail_rows [M,3] int64, overhead_rows
    [M,3] int64, reservation_entry_mask [M] bool) or None when the
    snapshot is inexact.  Zone totals for the AZ sort are computed over
    ALL candidate nodes (including not-ready ones), exactly like the
    slow path's metadata."""
    if not snap.exact:
        return None
    nidx = snap.name_index
    rows = np.fromiter(
        (nidx.get(nm, -1) for nm in candidate_names),
        dtype=np.int64,
        count=len(candidate_names),
    )
    idx = np.unique(rows[rows >= 0])  # dedupe like the slow path's metadata dict
    if zone is not None:
        try:
            zi = snap.zone_names.index(zone)
        except ValueError:
            idx = idx[:0]
        else:
            idx = idx[snap.zone_id[idx] == zi]
    if len(idx) == 0:
        return [], np.zeros((0, 3), np.int64), np.zeros((0, 3), np.int64), np.zeros(0, bool)

    avail = snap.avail[idx]
    order = _base_priority_order(snap, idx, avail)

    exec_ok = snap.ready[idx] & ~snap.unschedulable[idx]
    order = order[exec_ok[order]]
    if executor_label_priority is not None:
        keys = _label_ranks([snap.labels[i] for i in idx], executor_label_priority)
        order = order[np.argsort(keys[order], kind="stable")]

    sel = idx[order]
    return (
        [snap.names[i] for i in sel],
        avail[order],  # == snap.avail[sel] without re-materializing the property
        snap.overhead[sel],
        snap.res_entries[sel],
    )


def build_cluster_tensor(
    snap: TensorSnapshot,
    driver_pod,
    candidate_names: List[str],
    driver_label_priority: Optional[LabelPriorityOrder] = None,
    executor_label_priority: Optional[LabelPriorityOrder] = None,
) -> Optional[Tuple[ClusterTensor, Dict[str, str]]]:
    """(cluster tensor, node→zone map) or None when the fast path can't
    represent the snapshot exactly."""
    if not snap.exact:
        return None
    n = len(snap.names)
    if n == 0:
        # no eligible nodes: an empty tensor is still valid input
        empty = ClusterTensor(
            node_names=[],
            avail=np.zeros((0, 3), np.int64),
            sched=np.zeros((0, 3), np.int64),
            driver_rank=np.zeros(0, np.int32),
            exec_ok=np.zeros(0, bool),
            zone_id=np.zeros(0, np.int32),
            zone_names=[],
            valid=np.zeros(0, bool),
            exact=True,
        )
        return empty, {}

    # required node affinity + nodeSelector filter (metadata membership),
    # via the same matcher the slow path uses.  The dominant real-world
    # shape — a single In-constraint on one label (the instance group) —
    # is vectorized; anything else falls back to the general matcher.
    single_in = (
        not driver_pod.node_selector
        and not driver_pod.affinity_terms
        and len(driver_pod.node_affinity) == 1
    )
    if single_in:
        ((key, values),) = driver_pod.node_affinity.items()
        allowed = set(values)
        eligible = np.fromiter(
            (labels.get(key) in allowed for labels in snap.labels),
            dtype=bool,
            count=n,
        )
    else:
        eligible = np.fromiter(
            (driver_pod.matches_labels(labels) for labels in snap.labels),
            dtype=bool,
            count=n,
        )
    idx = np.flatnonzero(eligible)
    if len(idx) == 0:
        idx = np.zeros(0, dtype=np.int64)

    names = [snap.names[i] for i in idx]
    avail = snap.avail[idx]
    sched = snap.schedulable[idx]
    zone_id = snap.zone_id[idx]
    ready = snap.ready[idx]
    unsched = snap.unschedulable[idx]

    # AZ-aware base priority (shared with the executor lane)
    order = _base_priority_order(snap, idx, avail)

    # per-role label-priority re-sort on top of the base order
    # (nodesorting.go:161-180).  The array order is the EXECUTOR priority
    # order (the solver packs executors in array order); the driver order
    # lives in driver_rank, so the two roles can be re-sorted
    # independently, exactly like the slow path's two stable sorts.
    need_labels = driver_label_priority is not None or executor_label_priority is not None
    labels_sel = [snap.labels[i] for i in idx] if need_labels else None
    perm = order
    if executor_label_priority is not None:
        exec_keys = _label_ranks(labels_sel, executor_label_priority)
        perm = perm[np.argsort(exec_keys[perm], kind="stable")]

    names_arr = np.array(names, dtype=object)[perm]
    candidate_set = set(candidate_names)
    # driver order = BASE order ∩ candidates (never the executor-resorted
    # order), stable-sorted by the driver label rank when configured;
    # ranks are then scattered into final array positions
    cand_in_base = np.fromiter(
        (names[i] in candidate_set for i in order), dtype=bool, count=len(order)
    )
    cand_base_positions = order[np.flatnonzero(cand_in_base)]
    if driver_label_priority is not None:
        d_keys = _label_ranks(labels_sel, driver_label_priority)
        cand_base_positions = cand_base_positions[
            np.argsort(d_keys[cand_base_positions], kind="stable")
        ]
    pos_in_array = np.empty(len(perm), dtype=np.int64)
    pos_in_array[perm] = np.arange(len(perm))
    driver_rank = np.full(len(names_arr), INT32_SAFE, dtype=np.int64)
    driver_rank[pos_in_array[cand_base_positions]] = np.arange(
        len(cand_base_positions)
    )
    exec_ok = ready[perm] & ~unsched[perm]
    ordered_names = list(names_arr)

    cluster = ClusterTensor(
        node_names=ordered_names,
        avail=avail[perm],
        sched=sched[perm],
        driver_rank=driver_rank.astype(np.int32),
        exec_ok=exec_ok,
        zone_id=zone_id[perm].astype(np.int32),
        zone_names=list(snap.zone_names),
        valid=np.ones(len(ordered_names), dtype=bool),
        exact=True,
    )
    zone_ordered = zone_id[perm]
    zones = {
        name: snap.zone_names[zone_ordered[i]] for i, name in enumerate(ordered_names)
    }
    return cluster, zones
