"""FIFO queue solver: the extender's earlier-drivers pass on device.

Replaces the host loop of resource.go:224-262 (binpack every earlier
driver, subtract its usage, fail if an enforced driver doesn't fit) with
ONE whole-queue device solve (batch_solver.solve_queue), then packs the
current driver against the resulting availability.  Decisions are
bit-identical to the oracle loop (tests/test_fifo_solver.py); problems
that can't be exactly tensorized fall back to the host path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from .. import compat
from ..tracing import spans as tracing
from ..tracing.profiling import default_profiler
from ..types.resources import NodeGroupSchedulingMetadata
from .batch_adapter import (
    build_reserved,
    candidate_zone_masks,
    counts_to_evenly_list,
    counts_to_tightly_list,
    evenly_counts,
    min_frag_unclamped_caps,
    min_frag_zone_decode,
    minimal_fragmentation_assignment,
)
from .efficiency import compute_packing_efficiencies
from .packers import PackingResult, empty_packing_result
from .sparkapp import AppDemand
from .tensorize import _resources_to_base as _res_rows
from .tensorize import scale_problem, tensorize_apps, tensorize_cluster

logger = logging.getLogger(__name__)


def _ceil_div(v: int, d: int) -> int:
    return -((-v) // d)


def _pallas_selected(backend: str) -> bool:
    """Shared backend choice: 'pallas' forces the kernel, 'auto' uses it
    exactly when the default backend is a TPU."""
    if backend == "pallas":
        return True
    if backend == "auto":
        import jax

        return jax.default_backend() == "tpu"
    return False


def _native_selected(backend: str) -> bool:
    """Host lane choice: 'native' forces the C++ queue solver; 'auto'
    uses it exactly when no accelerator backs jax (CPU deployments —
    the XLA scan costs ~280ms/queue at 10k×1k on one host core vs ~35ms
    native, decision-identical per tests/test_native_fifo.py).  A FORCED
    'native' with no working toolchain raises — a silent 8× degrade to
    the XLA scan must never hide behind an explicit backend choice
    (mirrors how a forced 'pallas' fails loudly off-TPU)."""
    if backend not in ("native", "auto"):
        return False
    from ..native.fifo import native_fifo_available

    if backend == "native":
        if not native_fifo_available():
            raise RuntimeError(
                "backend='native' was forced but the C++ fifo solver could "
                "not be built/loaded (see native.fifo build log); use "
                "backend='auto' for graceful degradation"
            )
        return True
    import jax

    if jax.default_backend() != "cpu":
        return False
    return native_fifo_available()


class LazyEfficiencies(dict):
    """Per-node PackingEfficiency mapping backed by vectorized float64
    columns.  The zone choice reads only the placement nodes' entries
    and the metrics path needs only the average of per-node maxes, so
    building 10k dataclasses per Filter request (the dominant host cost
    of the driver fast lane) is deferred: [] / .get materialize single
    entries; values()/items() materialize everything (only the exact
    Quantity-parity consumers do that)."""

    def __init__(self, names, cpu, mem, gpu):
        super().__init__()
        self._names = list(names)
        # name → column dict built on first materialization: most
        # requests only read the scalar average (seq_max_avg), and a
        # 10k-entry dict per Filter is measurable on the request path
        self._col_idx_lazy = None
        self._cpu = cpu
        self._mem = mem
        self._gpu = gpu

    @property
    def _col_idx(self):
        if self._col_idx_lazy is None:
            self._col_idx_lazy = dict(
                zip(self._names, range(len(self._names)))
            )
        return self._col_idx_lazy

    def __missing__(self, name):
        from .efficiency import PackingEfficiency

        i = self._col_idx[name]
        e = PackingEfficiency(
            node_name=name,
            cpu=float(self._cpu[i]),
            memory=float(self._mem[i]),
            gpu=float(self._gpu[i]),
        )
        self[name] = e
        return e

    def get(self, name, default=None):
        try:
            return self[name]
        except KeyError:
            return default

    # the full dict read protocol must reflect ALL nodes (not just the
    # materialized subset), and iteration must stay in node order so
    # order-sensitive float accumulations (compute_avg_packing_
    # efficiency) see exactly the sequence the eager dict produced
    def __iter__(self):
        return iter(self._names)

    def __len__(self):
        return len(self._names)

    def __contains__(self, name):
        return name in self._col_idx

    def keys(self):
        return list(self._names)

    def values(self):
        return [self[n] for n in self._names]

    def items(self):
        return [(n, self[n]) for n in self._names]

    def seq_max_avg(self) -> float:
        """sum(max(gpu, cpu, memory)) / n for the extender's
        packing-efficiency gauge, Neumaier-compensated: the gauge's
        cross-lane bit-equality contract (test_extender_efficiency_
        gauge_matches_host_lane) sums the same per-node maxes in
        different orders on different lanes, and compensation makes the
        rounded result order-robust — exact whenever the true sum is
        representable, which plain left-to-right addition is not (the
        host lane's uncompensated loop can land an ulp off in ITS order;
        compensation recovers the representable value either way)."""
        if not self._names:
            return 0.0
        maxes = np.maximum(np.maximum(self._cpu, self._mem), self._gpu)
        try:
            from ..native.fifo import neumaier_sum_f64_native

            total = neumaier_sum_f64_native(maxes)
        except Exception:
            total = None
        if total is None:
            # same algorithm at Python speed (native lane unavailable)
            s = 0.0
            c = 0.0
            for x in maxes.tolist():
                t = s + x
                if abs(s) >= abs(x):
                    c += (s - t) + x
                else:
                    c += (x - t) + s
                s = t
            total = s + c
        return total / float(len(self._names))


def efficiencies_from_rows(names, sched_rows, avail_rows, reserved_rows):
    """compute_packing_efficiencies from exact base-unit int rows —
    bit-identical floats to the Quantity path (efficiency.go:80-105):
    per-dim reserved = schedulable − available + newly_reserved, then
    Quantity.value() semantics (ceil to canonical units) and ratio —
    computed as vectorized int64/float64 columns (identical IEEE results
    to the scalar loop) behind a lazily-materialized mapping."""
    n = len(names)
    s = np.asarray(sched_rows)[:n].astype(np.int64)
    r = (
        s
        - np.asarray(avail_rows)[:n].astype(np.int64)
        + np.asarray(reserved_rows)[:n].astype(np.int64)
    )
    s_cpu = _ceil_div(s[:, 0], 1000)
    s_gpu = _ceil_div(s[:, 2], 1000)
    r_cpu = _ceil_div(r[:, 0], 1000)
    r_gpu = _ceil_div(r[:, 2], 1000)
    # Go divides by normalize(schedulable)=1 when schedulable is 0
    cpu = r_cpu / np.maximum(s_cpu, 1)
    mem = r[:, 1] / np.maximum(s[:, 1], 1)
    gpu = np.where(s_gpu != 0, r_gpu / np.maximum(s_gpu, 1), 0.0)
    return LazyEfficiencies(names, cpu, mem, gpu)


def _patch_available(metadata, names, avail_rows):
    """Metadata view whose candidate-node availability is replaced by the
    post-queue scan carry (exact base-unit ints → exact Quantities):
    host-lane parity for efficiency metrics, which the reference computes
    against the metadata mutated by fitEarlierDrivers
    (resource.go:255-259)."""
    from dataclasses import replace
    from fractions import Fraction

    from ..types.resources import Resources
    from ..utils.quantity import Quantity

    patched = dict(metadata)
    for i, name in enumerate(names):
        patched[name] = replace(
            metadata[name],
            available=Resources(
                Quantity(Fraction(int(avail_rows[i, 0]), 1000)),
                Quantity(int(avail_rows[i, 1])),
                Quantity(Fraction(int(avail_rows[i, 2]), 1000)),
            ),
        )
    return patched


@dataclass
class FifoOutcome:
    """Result of the combined earlier-drivers + current-driver solve."""

    supported: bool  # False → caller must use the host oracle path
    earlier_ok: bool = True  # False → an enforced earlier driver doesn't fit
    result: Optional[PackingResult] = None  # current driver's packing


class TpuFifoSolver:
    """One device round for the whole FIFO queue + the current driver.

    backend: "auto" (pallas kernel on TPU, native C++ solver on CPU
    hosts, XLA scan otherwise), "xla", "pallas", or "native".  The
    pallas queue kernel (ops/pallas_queue) keeps the availability carry
    VMEM-resident across the whole queue — it is the program the
    headline bench measures, so production Filter requests pay exactly
    the benched cost (queue pass + one O(N) decode solve for the
    current driver's placements).  The native lane
    (native/fifo_solver.cpp) serves accelerator-less deployments with
    the same decisions at ~8× the XLA-scan speed for every policy
    (tightly/evenly via fifo_solve_queue, minimal-fragmentation via
    fifo_solve_queue_minfrag)."""

    def __init__(
        self,
        assignment_policy: str = "tightly-pack",
        backend: str = "auto",
        strict_reference_parity: bool = compat.DEFAULT_STRICT,
    ):
        self.assignment_policy = assignment_policy
        self.backend = backend
        # min-frag only: whether the reference's no-efficiency-write-back
        # quirk applies to the current driver's reported efficiencies
        self.strict_reference_parity = strict_reference_parity
        # which lane served the last queue pass — one of "native",
        # "native-minfrag", "pallas", "pallas-minfrag", "xla",
        # "minfrag-xla"; None = no queue pass ran — observable for tests
        # and the tpu.fastpath lane counters
        self.last_queue_lane: Optional[str] = None
        # (ids, strong refs, AppTensor) of the last earlier-apps list:
        # consecutive Filters tensorize the same pending queue, and the
        # per-request Python loop over ~1k apps is measurable.  The
        # cached list holds strong references, so an id can never be
        # reused while the entry lives — id-tuple equality therefore
        # proves the SAME AppDemand objects (stable per pod version via
        # sparkpods._cached_entry), making the hit exact.
        self._earlier_tensor_cache = None
        # decision provenance (provenance/tracker.py): wiring points
        # this at ProvenanceTracker.capture when provenance is enabled;
        # None (the default) keeps solve_tensor capture-free.
        self.capture_sink = None

    def _use_pallas(self) -> bool:
        return _pallas_selected(self.backend)

    def _use_native(self) -> bool:
        return not self._use_pallas() and _native_selected(self.backend)

    def solve(
        self,
        metadata: NodeGroupSchedulingMetadata,
        driver_order: Sequence[str],
        executor_order: Sequence[str],
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
    ) -> FifoOutcome:
        cluster = tensorize_cluster(metadata, driver_order, executor_order)
        return self.solve_tensor(
            cluster, earlier_apps, earlier_skip_allowed, current_app, metadata=metadata
        )

    def _tensorize_with_cache(self, earlier, current_app):
        """AppTensor for earlier + [current]: the earlier block is
        cached by object identity (see _earlier_tensor_cache) and the
        current app's rows are appended."""
        from .tensorize import AppTensor, _app_base_rows

        key = tuple(map(id, earlier))
        cached = self._earlier_tensor_cache
        if cached is not None and cached[0] == key:
            base = cached[2]
        else:
            base = tensorize_apps(earlier)
            self._earlier_tensor_cache = (key, earlier, base)
        drow, erow, exact = _app_base_rows(current_app)
        a = base.driver.shape[0]
        driver = np.empty((a + 1, 3), dtype=np.int64)
        driver[:a] = base.driver
        driver[a] = drow
        executor = np.empty((a + 1, 3), dtype=np.int64)
        executor[:a] = base.executor
        executor[a] = erow
        count = np.empty(a + 1, dtype=np.int64)
        count[:a] = base.count
        count[a] = current_app.min_executor_count
        return AppTensor(
            driver=driver,
            executor=executor,
            count=count,
            valid=np.ones(a + 1, dtype=bool),
            exact=base.exact and exact,
        )

    def feasible_tensor(self, cluster, app: AppDemand) -> Optional[bool]:
        """Feasibility of one app against a prebuilt ClusterTensor with
        no placement decode and no efficiency math — the
        unschedulable-marker's empty-cluster verdict (its scan runs
        every interval over the whole pending backlog, so the full
        solve_tensor cost per pod was pure waste).  Feasibility is
        policy-invariant across tightly/evenly/min-frag (the
        work-conserving drain rule, batch_solver docstring), identical
        to binpack_func's has_capacity.  None = not exactly
        tensorizable (caller uses the host path)."""
        apps = tensorize_apps([app])
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            return None
        if self._use_native():
            from ..native.fifo import solve_app_native

            feas, _, _, _ = solve_app_native(
                problem.avail, problem.driver_rank, problem.exec_ok,
                problem.driver[0], problem.executor[0], int(problem.count[0]),
            )
            return bool(feas)
        import jax.numpy as jnp

        from .batch_solver import solve_single

        solve = solve_single(
            jnp.asarray(problem.avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver[0]),
            jnp.asarray(problem.executor[0]),
            jnp.asarray(problem.count[0]),
        )
        return bool(solve.feasible)

    def solve_tensor(
        self,
        cluster,
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
        metadata: Optional[NodeGroupSchedulingMetadata] = None,
    ) -> FifoOutcome:
        """Solve from a prebuilt ClusterTensor (the tensor-snapshot fast
        path passes one directly; `metadata` is only used for the
        Quantity-based efficiency computation when provided)."""
        import jax.numpy as jnp

        from .batch_solver import solve_queue, solve_queue_min_frag

        apps = self._tensorize_with_cache(list(earlier_apps), current_app)
        self.last_queue_lane = None
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            return FifoOutcome(supported=False)

        evenly = self.assignment_policy == "distribute-evenly"
        minfrag = self.assignment_policy == "minimal-fragmentation"
        if minfrag:
            from .batch_solver import mf_sentinel_safe

            if not mf_sentinel_safe(problem.avail):
                # a real capacity could collide with the device kernel's
                # unbounded-capacity sentinel (batch_solver.MF_SENT)
                return FifoOutcome(supported=False)
        n_earlier = len(earlier_apps)
        # the native C++ lane serves every policy; decisions are
        # differential-tested bit-identical to the device scans
        use_native = self._use_native()

        shape_key = (problem.avail.shape, problem.driver.shape)
        didx_all = None  # native lanes keep per-position driver indices
        if n_earlier > 0:
            # whole-queue pass over the earlier drivers only.  The
            # fifo_gate span is the request's "earlier drivers fit?"
            # phase; the kernel profiles inside it split the dispatch
            # into jit-compile vs execute time (tracing/profiling.py).
            with tracing.child_span(
                "fifo_gate", {"earlierApps": n_earlier}
            ) as gate_span:
                queue_valid = problem.app_valid.copy()
                queue_valid[n_earlier:] = False
                if use_native and minfrag:
                    from ..native.fifo import solve_queue_min_frag_native

                    self.last_queue_lane = "native-minfrag"
                    with default_profiler.profile(
                        "fifo_queue", lane="native-minfrag", jit=False
                    ):
                        feasible_all, didx_all, avail_after = solve_queue_min_frag_native(
                            problem.avail, problem.driver_rank, problem.exec_ok,
                            problem.driver, problem.executor, problem.count,
                            queue_valid,
                        )
                    feasible = feasible_all[:n_earlier]
                elif use_native:
                    from ..native.fifo import solve_queue_native

                    self.last_queue_lane = "native"
                    with default_profiler.profile(
                        "fifo_queue", lane="native", jit=False
                    ):
                        feasible_all, didx_all, avail_after = solve_queue_native(
                            problem.avail, problem.driver_rank, problem.exec_ok,
                            problem.driver, problem.executor, problem.count,
                            queue_valid, evenly=evenly,
                        )
                    feasible = feasible_all[:n_earlier]
                else:
                    queue_args = (
                        jnp.asarray(problem.avail),
                        jnp.asarray(problem.driver_rank),
                        jnp.asarray(problem.exec_ok),
                        jnp.asarray(problem.driver),
                        jnp.asarray(problem.executor),
                        jnp.asarray(problem.count),
                        jnp.asarray(queue_valid),
                    )
                    if minfrag and self._use_pallas():
                        from .pallas_queue import pallas_solve_queue_min_frag

                        self.last_queue_lane = "pallas-minfrag"
                        with default_profiler.profile(
                            "fifo_queue", lane="pallas-minfrag",
                            shape_key=shape_key,
                        ) as rec:
                            feasible_dev, _, avail_after = pallas_solve_queue_min_frag(
                                *queue_args
                            )
                            rec.sync(avail_after)
                        feasible = np.asarray(feasible_dev)[:n_earlier]
                    elif minfrag:
                        self.last_queue_lane = "minfrag-xla"
                        with default_profiler.profile(
                            "fifo_queue", lane="minfrag-xla",
                            fn=solve_queue_min_frag,
                        ) as rec:
                            out = solve_queue_min_frag(*queue_args, with_placements=False)
                            rec.sync(out.avail_after)
                        feasible = np.asarray(out.feasible)[:n_earlier]
                        avail_after = out.avail_after
                    elif self._use_pallas():
                        from .pallas_queue import pallas_solve_queue

                        self.last_queue_lane = "pallas"
                        with default_profiler.profile(
                            "fifo_queue", lane="pallas", shape_key=shape_key
                        ) as rec:
                            feasible_dev, _, avail_after = pallas_solve_queue(
                                *queue_args, evenly=evenly
                            )
                            rec.sync(avail_after)
                        feasible = np.asarray(feasible_dev)[:n_earlier]
                    else:
                        self.last_queue_lane = "xla"
                        with default_profiler.profile(
                            "fifo_queue", lane="xla", fn=solve_queue
                        ) as rec:
                            out = solve_queue(*queue_args, evenly=evenly, with_placements=False)
                            rec.sync(out.avail_after)
                        feasible = np.asarray(out.feasible)[:n_earlier]
                        avail_after = out.avail_after
                gate_span.tag("lane", self.last_queue_lane)
                # capture BEFORE the blocked-earlier verdict below: a
                # FAILURE_EARLIER_DRIVER refusal is exactly the decision
                # the provenance explainer must be able to decompose
                if self.capture_sink is not None:
                    self._capture_solve(
                        cluster, problem, earlier_skip_allowed, n_earlier,
                        feasible, didx_all, avail_after,
                    )
                # an enforced (old-enough) earlier driver that doesn't fit
                # fails the whole request (resource.go:244-253)
                for i in range(n_earlier):
                    if not feasible[i] and not earlier_skip_allowed[i]:
                        gate_span.tag("earlierOk", False)
                        return FifoOutcome(supported=True, earlier_ok=False)
                gate_span.tag("earlierOk", True)
        else:
            with tracing.child_span("fifo_gate", {"earlierApps": 0, "earlierOk": True}):
                avail_after = problem.avail if use_native else jnp.asarray(problem.avail)
            feasible = np.zeros(0, dtype=bool)
            if self.capture_sink is not None:
                self._capture_solve(
                    cluster, problem, earlier_skip_allowed, n_earlier,
                    feasible, didx_all, avail_after,
                )

        return self._pack_current(
            cluster, problem, avail_after, n_earlier, current_app,
            metadata=metadata, use_native=use_native,
        )

    def _capture_solve(
        self, cluster, problem, earlier_skip_allowed, n_earlier,
        feasible, didx_all, avail_after,
    ) -> None:
        """Hand the queue solve's inputs + verdicts to the provenance
        sink (provenance/tracker.py).  Array references, no copies; only
        runs when wiring installed a sink."""
        try:
            from .batch_solver import queue_policy_code
            from ..provenance.tracker import SolveArtifacts

            policy_code = queue_policy_code(self.assignment_policy)
            if policy_code is None:
                return
            na = n_earlier + 1
            packed = np.empty((na, 8), dtype=np.int32)
            packed[:, 0:3] = problem.driver[:na]
            packed[:, 3:6] = problem.executor[:na]
            packed[:, 6] = problem.count[:na]
            packed[:, 7] = problem.app_valid[:na]
            self.capture_sink(SolveArtifacts(
                policy_code=int(policy_code),
                lane=self.last_queue_lane or "none",
                basis=problem.avail,
                driver_rank=problem.driver_rank,
                exec_ok=problem.exec_ok,
                packed=packed,
                n_earlier=n_earlier,
                feasible=np.asarray(feasible, dtype=bool),
                didx=(
                    np.asarray(didx_all, dtype=np.int32)
                    if didx_all is not None
                    else None
                ),
                resume=0,
                avail_after=np.asarray(avail_after, dtype=np.int32),
                scale=problem.scale,
                node_names=cluster.node_names,
                zone_names=cluster.zone_names,
                zone_id=cluster.zone_id,
                skip_allowed=list(earlier_skip_allowed),
            ))
        except Exception:
            logger.exception("provenance capture failed (diagnostic only)")

    def _pack_current(
        self,
        cluster,
        problem,
        avail_after,
        n_earlier: int,
        current_app: AppDemand,
        metadata: Optional[NodeGroupSchedulingMetadata] = None,
        use_native: bool = False,
    ) -> FifoOutcome:
        """The current driver's gang pack against the post-queue
        availability carry: solve + placement decode + efficiency rows.
        Shared tail of solve_tensor and the delta-solve engine
        (ops/deltasolve.py), which substitutes its session's warm carry
        for the cold queue pass and hands the identical arguments here."""
        import jax.numpy as jnp

        from .batch_solver import solve_single

        evenly = self.assignment_policy == "distribute-evenly"
        minfrag = self.assignment_policy == "minimal-fragmentation"
        with tracing.child_span(
            "binpack", {"policy": self.assignment_policy}
        ) as binpack_span:
            if use_native:
                from ..native.fifo import solve_app_native

                binpack_span.tag("lane", "native")
                with default_profiler.profile(
                    "solve_app", lane="native", jit=False
                ):
                    nat_feas, nat_didx, nat_counts, nat_caps = solve_app_native(
                        np.asarray(avail_after), problem.driver_rank, problem.exec_ok,
                        problem.driver[n_earlier], problem.executor[n_earlier],
                        int(problem.count[n_earlier]),
                    )
                from .batch_solver import AppSolve

                solve = AppSolve(
                    feasible=np.bool_(nat_feas),
                    driver_idx=np.int32(nat_didx),
                    exec_counts=nat_counts,
                    exec_capacity=nat_caps,
                )
            else:
                binpack_span.tag("lane", "xla")
                with default_profiler.profile(
                    "solve_single", lane="xla", fn=solve_single
                ) as rec:
                    solve = solve_single(
                        avail_after,
                        jnp.asarray(problem.driver_rank),
                        jnp.asarray(problem.exec_ok),
                        jnp.asarray(problem.driver[n_earlier]),
                        jnp.asarray(problem.executor[n_earlier]),
                        jnp.asarray(problem.count[n_earlier]),
                    )
                    rec.sync(solve.exec_counts)
            binpack_span.tag("feasible", bool(solve.feasible))
        if not bool(solve.feasible):
            return FifoOutcome(supported=True, earlier_ok=True, result=empty_packing_result())

        names = cluster.node_names
        driver_node = names[int(solve.driver_idx)]
        k = current_app.min_executor_count
        if evenly:
            cap = np.asarray(solve.exec_capacity)[: len(names)]
            counts = evenly_counts(cap, k)
            executor_nodes = counts_to_evenly_list(names, counts)
        elif minfrag:
            cap = min_frag_unclamped_caps(
                np.asarray(avail_after)[: len(names)],
                problem.executor[n_earlier],
                np.asarray(problem.exec_ok[: len(names)]),
                int(solve.driver_idx),
                problem.driver[n_earlier],
            )
            executor_nodes = minimal_fragmentation_assignment(names, cap, k)
            if executor_nodes is None:  # unreachable: feasibility proven above
                return FifoOutcome(
                    supported=True, earlier_ok=True, result=empty_packing_result()
                )
            # reference quirk: min-frag reports only the driver in
            # reserved/efficiencies under strict parity (packers.
            # make_minimal_fragmentation QUIRK, switchable)
            counts = np.zeros(len(names), dtype=np.int64)
            if not self.strict_reference_parity:
                pos = {name: i for i, name in enumerate(names)}
                for node in executor_nodes:
                    counts[pos[node]] += 1
        else:
            counts = np.asarray(solve.exec_counts)[: len(names)]
            executor_nodes = counts_to_tightly_list(names, counts)

        # efficiencies feed metrics only on this path (non-single-AZ
        # policies); the host lane computes them against the metadata
        # MUTATED by the earlier-drivers pass (resource.go:255-259 then
        # binpack on the same map), so both branches use the post-queue
        # availability carried out of the device scan.  Domain contract:
        # the rows branch averages over cluster.node_names, which the
        # production caller (build_cluster_tensor) populates with EVERY
        # affinity-matching node — the same domain as the host lane's
        # metadata — not just schedulable candidates.
        def post_queue_avail_rows():
            if n_earlier == 0:
                # no queue pass ran: skip the device→host sync + multiply
                return cluster.avail[: len(names)]
            scale = problem.scale.astype(np.int64)
            return (
                np.asarray(avail_after)[: len(names)].astype(np.int64)
                * scale[None, :]
            )

        if metadata is not None:
            reserved = build_reserved(
                names, counts, driver_node, current_app.driver_resources,
                current_app.executor_resources,
            )
            eff_meta = metadata
            if n_earlier > 0:
                eff_meta = _patch_available(metadata, names, post_queue_avail_rows())
            efficiencies = compute_packing_efficiencies(eff_meta, reserved)
        else:
            # per-node reserved = count × executor (+ driver on its node)
            reserved_rows = np.zeros_like(cluster.avail)
            drv_row, _ = _res_rows(current_app.driver_resources)
            exec_row, _ = _res_rows(current_app.executor_resources)
            reserved_rows[int(solve.driver_idx)] += np.array(drv_row, np.int64)
            reserved_rows[: len(names)] += (
                counts.astype(np.int64)[:, None] * np.array(exec_row, np.int64)[None, :]
            )
            efficiencies = efficiencies_from_rows(
                names, cluster.sched, post_queue_avail_rows(), reserved_rows
            )
        result = PackingResult(
            driver_node=driver_node,
            executor_nodes=executor_nodes,
            has_capacity=True,
            packing_efficiencies=efficiencies,
            max_avg_efficiency=(
                efficiencies.seq_max_avg()
                if isinstance(efficiencies, LazyEfficiencies)
                else None
            ),
        )
        return FifoOutcome(supported=True, earlier_ok=True, result=result)


def _fused_efficiency_inputs(cluster, problem):
    """Device inputs + numeric-range guards for the on-device zone-
    efficiency score (batch_solver.solve_queue_single_az).  Returns None
    when any bound fails and the host zone-choice loop must take over.
    The bounds guarantee: int32 exactness of every reserved numerator
    (r_base = sched_base − m·scale), f32 exactness of all ratio operands
    (ints ≤ 2^24), ratios ≤ 1 (avail ≤ schedulable), and an int32-safe
    score accumulator ((k+1)·2^EFF_SHIFT < 2^31)."""
    n = len(cluster.node_names)
    nb = problem.avail.shape[0]
    sched = cluster.sched[:n]  # int64 base units (milli-cpu, bytes, milli-gpu)
    avail_base = cluster.avail[:n]
    scale = problem.scale.astype(np.int64)
    k_max = int(problem.count.max()) if problem.count.size else 0
    if k_max + 1 > 4096:
        return None
    if n == 0:
        return None
    if (sched[:, 0] <= 0).any() or (sched[:, 1] <= 0).any():
        # zero-schedulable dims hit the normalize(0)→1 divisor and can
        # produce efficiencies ≫ 1 — exact f64 host path handles those
        return None
    if (sched[:, 0] > 2**31 - 1024).any() or (sched[:, 2] > 2**31 - 1024).any():
        return None
    if (avail_base > sched).any():
        return None
    if int(scale[0]) > 2**31 - 1 or int(scale[2]) > 2**31 - 1:
        return None
    th_mem = _ceil_div(sched[:, 1], int(scale[1]))
    den_c = _ceil_div(sched[:, 0], 1000)
    den_g = _ceil_div(sched[:, 2], 1000)
    if (th_mem > 2**24).any() or (den_c > 2**24).any() or (den_g > 2**24).any():
        return None

    s_cpu = np.zeros(nb, np.int32)
    s_cpu[:n] = sched[:, 0]
    s_gpu = np.zeros(nb, np.int32)
    s_gpu[:n] = sched[:, 2]
    inv_m = np.zeros(nb, np.float32)
    inv_m[:n] = (float(scale[1]) / sched[:, 1].astype(np.float64)).astype(np.float32)
    th = np.zeros(nb, np.int32)
    th[:n] = th_mem
    return s_cpu, s_gpu, inv_m, th, int(scale[0]), int(scale[2])


class TpuSingleAzFifoSolver:
    """FIFO pass for the single-AZ policies.

    Fast lane (one dispatch): batch_solver.solve_queue_single_az scans
    the whole earlier-driver queue on device — per-zone tightly-pack
    solves, the zone-efficiency choice in certified fixed point
    (batch_solver.EFF_SHIFT), the az-aware cross-zone fallback, and the
    carried usage subtraction all fused into a single XLA program.  On
    accelerator-less hosts (backend "auto" on CPU, or "native") the C++
    lane (native/fifo_solver.cpp::fifo_solve_queue_single_az) runs the
    same per-zone solves with the zone chosen by EXACT float64
    efficiency math — host-lane decisions with no uncertainty valve, at
    native speed.

    Exactness valve: any app whose zone scores land inside the
    fixed-point margin is flagged `uncertain`, and the whole queue is
    re-solved on the host lane — per-driver vmapped zone solves
    (solve_zones) with the zone choice in the oracle's float64
    efficiency math — restoring bit-exact reference parity.  Snapshots
    outside the fused lane's numeric bounds (_fused_efficiency_inputs)
    go straight to the host lane.  The current app's packing is always
    chosen with the exact host math.  `last_path` records which lane ran
    ("fused" / "native" / "host") for tests and diagnostics."""

    def __init__(
        self,
        az_aware: bool = False,
        backend: str = "auto",
        interpret: bool = False,
        inner_policy: str = "tightly-pack",
        strict_reference_parity: bool = compat.DEFAULT_STRICT,
    ):
        # inner_policy "minimal-fragmentation" gives the
        # single-az-minimal-fragmentation semantics: zone feasibility and
        # driver choice are shared with tightly (work-conserving drain),
        # placements come from the min-frag kernel / host bisect, and the
        # zone choice sees driver-only reserved under strict parity (the
        # reference's no-write-back quirk).  Both fused one-dispatch
        # lanes serve it (XLA scan with minfrag=True; pallas kernel with
        # the min-frag drain per zone); az_aware has no min-frag variant
        # in the reference.
        assert not (az_aware and inner_policy == "minimal-fragmentation")
        self.az_aware = az_aware
        self.backend = backend
        self.inner_policy = inner_policy
        self.strict_reference_parity = strict_reference_parity
        # interpret=True runs the pallas kernel in interpreter mode so the
        # solver-side pallas wiring is testable on CPU
        self.interpret = interpret
        self.last_path: Optional[str] = None

    def _use_pallas(self) -> bool:
        return _pallas_selected(self.backend)

    def solve(
        self,
        metadata: NodeGroupSchedulingMetadata,
        driver_order: Sequence[str],
        executor_order: Sequence[str],
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
    ) -> FifoOutcome:
        import jax.numpy as jnp

        from . import packers
        from .batch_solver import solve_queue_single_az, solve_zones_jit

        cluster = tensorize_cluster(metadata, driver_order, executor_order)
        all_apps = list(earlier_apps) + [current_app]
        apps = tensorize_apps(all_apps)
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            self.last_path = None
            return FifoOutcome(supported=False)

        names = cluster.node_names
        n = len(names)
        nb = problem.avail.shape[0]
        scale = problem.scale.astype(np.int64)

        candidate_zones, zone_masks = candidate_zone_masks(
            driver_order, executor_order, metadata, names, nb
        )
        zone_masks_dev = jnp.asarray(zone_masks)
        rank_dev = jnp.asarray(problem.driver_rank)
        exec_dev = jnp.asarray(problem.exec_ok)

        avail = problem.avail.astype(np.int32).copy()  # scaled, mutated per driver

        minfrag_inner = self.inner_policy == "minimal-fragmentation"
        exec_ok_arr = np.asarray(problem.exec_ok[:n])

        def pack_one(app_idx: int):
            """Device zone solves + host zone choice for one app.
            Returns (driver_idx, counts) or None when infeasible."""
            if not candidate_zones:
                return None  # no zone has both driver and executor candidates
            solves = solve_zones_jit(
                jnp.asarray(avail),
                rank_dev,
                exec_dev,
                zone_masks_dev,
                jnp.asarray(problem.driver[app_idx]),
                jnp.asarray(problem.executor[app_idx]),
                jnp.asarray(problem.count[app_idx]),
            )
            feasible = np.asarray(solves.feasible)
            driver_idx = np.asarray(solves.driver_idx)
            counts_all = np.asarray(solves.exec_counts)

            results = []
            per_zone = []
            for zi, zone in enumerate(candidate_zones):
                if not feasible[zi]:
                    continue
                d_idx = int(driver_idx[zi])
                if minfrag_inner:
                    # exact host bisect on the carried scaled availability
                    # (capacities are scale-invariant); placement order is
                    # the drain order, not priority order
                    decoded = min_frag_zone_decode(
                        names,
                        avail.astype(np.int64)[:n],
                        problem.executor[app_idx],
                        exec_ok_arr & zone_masks[zi][:n],
                        d_idx,
                        problem.driver[app_idx],
                        int(problem.count[app_idx]),
                        self.strict_reference_parity,
                    )
                    if decoded is None:  # unreachable: zone feasible
                        continue
                    executor_nodes, zone_counts, eff_counts = decoded
                    eff_rows = _reserved_rows(n, d_idx, eff_counts, problem, app_idx)
                else:
                    zone_counts = counts_all[zi][:n]
                    executor_nodes = counts_to_tightly_list(names, zone_counts)
                    eff_rows = _reserved_rows(n, d_idx, zone_counts, problem, app_idx)
                results.append(
                    PackingResult(
                        driver_node=names[d_idx],
                        executor_nodes=executor_nodes,
                        has_capacity=True,
                        packing_efficiencies=efficiencies_from_rows(
                            names,
                            cluster.sched,
                            avail.astype(np.int64) * scale[None, :],
                            eff_rows * scale[None, :],
                        ),
                    )
                )
                per_zone.append((d_idx, zone_counts))
            if not results:
                return None
            best = packers._choose_best_result(metadata, results)
            if not best.has_capacity:
                # the all-zero-efficiency quirk: single-az yields nothing;
                # the caller's az_aware fallback handles the cross-zone pack
                return None
            choice = results.index(best)
            d_idx, counts = per_zone[choice]
            return d_idx, counts, best

        def plain_fallback(app_idx):
            return self._plain_pack(app_idx, avail, problem, n)

        n_earlier = len(earlier_apps)
        fused_done = False
        # None = no queue pass ran (empty queue); "fused"/"native"/"host"
        # report which lane actually processed earlier drivers
        self.last_path = None
        # min-frag inner: all fast lanes (native, XLA scan, pallas
        # kernel) run the min-frag drain with the int32 MF_SENT
        # sentinel, so the sentinel-collision guard gates every one of
        # them; pathological snapshots take the exact host lane (its
        # decode uses a 2^62 sentinel no int32 capacity can reach).
        from .batch_solver import mf_sentinel_safe

        mf_fused_ok = not minfrag_inner or mf_sentinel_safe(problem.avail)
        # shared by the native and pallas lanes: disjoint zone masks →
        # one zone index per node (-1 = in no candidate zone), and the
        # queue-only validity mask
        zone_vec = np.full(avail.shape[0], -1, np.int32)
        for zi in range(len(candidate_zones)):
            zone_vec[zone_masks[zi]] = zi
        queue_valid = problem.app_valid.copy()
        queue_valid[n_earlier:] = False

        if (
            n_earlier > 0
            and mf_fused_ok
            and not self._use_pallas()
            and _native_selected(self.backend)
        ):
            # native C++ lane: per-zone solves with the zone chosen by
            # EXACT float64 efficiency math — same decisions as the host
            # lane with no uncertainty valve, at native speed
            from ..native.fifo import solve_queue_single_az_native

            with tracing.child_span(
                "fifo_gate", {"lane": "native", "earlierApps": n_earlier}
            ) as gate_span:
                with default_profiler.profile(
                    "fifo_queue_single_az", lane="native", jit=False
                ):
                    feas_n, _zone_n, _didx_n, avail_after_n = solve_queue_single_az_native(
                        avail, problem.driver_rank, np.asarray(problem.exec_ok),
                        zone_vec, problem.driver, problem.executor, problem.count,
                        queue_valid, cluster.sched, scale,
                        n_zones=len(candidate_zones), az_aware=self.az_aware,
                        minfrag=minfrag_inner, strict=self.strict_reference_parity,
                    )
                self.last_path = "native"
                for i in range(n_earlier):
                    if not feas_n[i] and not earlier_skip_allowed[i]:
                        gate_span.tag("earlierOk", False)
                        return FifoOutcome(supported=True, earlier_ok=False)
                gate_span.tag("earlierOk", True)
                avail[:] = avail_after_n
                fused_done = True

        if not fused_done and n_earlier > 0 and mf_fused_ok:
            eff_inputs = _fused_efficiency_inputs(cluster, problem)
            if eff_inputs is not None:
                s_cpu, s_gpu, inv_m, th_m, scale_c, scale_g = eff_inputs
                if self._use_pallas():
                    from .pallas_queue import pallas_solve_queue_single_az

                    from .batch_solver import ZoneQueueSolve

                    with default_profiler.profile(
                        "fifo_queue_single_az", lane="pallas",
                        shape_key=(avail.shape, problem.driver.shape),
                    ) as rec:
                        feas_d, zone_d, didx_d, uncertain_d, avail_after_d = (
                            pallas_solve_queue_single_az(
                                jnp.asarray(avail),
                                rank_dev,
                                exec_dev,
                                jnp.asarray(zone_vec),
                                jnp.asarray(problem.driver),
                                jnp.asarray(problem.executor),
                                jnp.asarray(problem.count),
                                jnp.asarray(queue_valid),
                                jnp.asarray(s_cpu),
                                jnp.asarray(s_gpu),
                                jnp.asarray(inv_m),
                                jnp.asarray(th_m),
                                jnp.asarray(np.array([scale_c], np.int32)),
                                jnp.asarray(np.array([scale_g], np.int32)),
                                n_zones=len(candidate_zones),
                                az_aware=self.az_aware,
                                interpret=self.interpret,
                                minfrag=minfrag_inner,
                                strict=self.strict_reference_parity,
                            )
                        )
                        rec.sync(avail_after_d)
                    out = ZoneQueueSolve(
                        feasible=feas_d,
                        zone_idx=zone_d,
                        driver_idx=didx_d,
                        uncertain=uncertain_d,
                        avail_after=avail_after_d,
                    )
                else:
                    with default_profiler.profile(
                        "fifo_queue_single_az", lane="xla",
                        fn=solve_queue_single_az,
                    ) as rec:
                        out = solve_queue_single_az(
                            jnp.asarray(avail),
                            rank_dev,
                            exec_dev,
                            zone_masks_dev,
                            jnp.asarray(problem.driver),
                            jnp.asarray(problem.executor),
                            jnp.asarray(problem.count),
                            jnp.asarray(queue_valid),
                            jnp.asarray(s_cpu),
                            jnp.asarray(s_gpu),
                            jnp.asarray(inv_m),
                            jnp.asarray(th_m),
                            jnp.int32(scale_c),
                            jnp.int32(scale_g),
                            az_aware=self.az_aware,
                            minfrag=minfrag_inner,
                            strict=self.strict_reference_parity,
                        )
                        rec.sync(out.avail_after)
                if not bool(np.asarray(out.uncertain)[:n_earlier].any()):
                    # the one-dispatch lane's answer is certain — it is
                    # the lane that served this request, whatever the
                    # FIFO verdict
                    self.last_path = "fused"
                    feasible = np.asarray(out.feasible)[:n_earlier]
                    with tracing.child_span(
                        "fifo_gate", {"lane": "fused", "earlierApps": n_earlier}
                    ) as gate_span:
                        for i in range(n_earlier):
                            if not feasible[i] and not earlier_skip_allowed[i]:
                                gate_span.tag("earlierOk", False)
                                return FifoOutcome(supported=True, earlier_ok=False)
                        gate_span.tag("earlierOk", True)
                    # keep the closure binding: copy the carried result
                    # into the same array pack_one reads
                    avail[:] = np.asarray(out.avail_after)
                    fused_done = True

        if not fused_done and n_earlier > 0:
            # host lane: per-driver vmapped zone solves with the exact
            # float64 zone choice (the uncertainty/guard fallback)
            self.last_path = "host"
            with tracing.child_span(
                "fifo_gate", {"lane": "host", "earlierApps": n_earlier}
            ) as gate_span:
                for i, app in enumerate(earlier_apps):
                    packed = pack_one(i)
                    if packed is None and self.az_aware:
                        fallback = plain_fallback(i)
                        packed = fallback if fallback is None else (*fallback, None)
                    if packed is None:
                        if earlier_skip_allowed[i]:
                            continue
                        gate_span.tag("earlierOk", False)
                        return FifoOutcome(supported=True, earlier_ok=False)
                    d_idx, counts = packed[0], packed[1]
                    self._subtract(avail, d_idx, counts, problem, i, n)
                gate_span.tag("earlierOk", True)

        with tracing.child_span(
            "binpack", {"policy": self.inner_policy, "azAware": self.az_aware}
        ) as bp_span:
            packed = pack_one(len(earlier_apps))
            if packed is None and self.az_aware:
                fallback = plain_fallback(len(earlier_apps))
                packed = fallback if fallback is None else (*fallback, None)
            bp_span.tag("feasible", packed is not None)
        if packed is None:
            return FifoOutcome(supported=True, earlier_ok=True, result=empty_packing_result())
        d_idx, counts, chosen = packed
        if chosen is None:
            # cross-zone fallback path: build the result from counts
            chosen = PackingResult(
                driver_node=names[d_idx],
                executor_nodes=counts_to_tightly_list(names, counts),
                has_capacity=True,
                packing_efficiencies=efficiencies_from_rows(
                    names,
                    cluster.sched,
                    avail.astype(np.int64) * scale[None, :],
                    _reserved_rows(n, d_idx, counts, problem, len(earlier_apps))
                    * scale[None, :],
                ),
            )
        return FifoOutcome(supported=True, earlier_ok=True, result=chosen)

    @staticmethod
    def _plain_pack(app_idx, avail, problem, n):
        """Cross-zone tightly-pack (the az-aware fallback)."""
        import jax.numpy as jnp

        from .batch_solver import solve_single

        solve = solve_single(
            jnp.asarray(avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver[app_idx]),
            jnp.asarray(problem.executor[app_idx]),
            jnp.asarray(problem.count[app_idx]),
        )
        if not bool(solve.feasible):
            return None
        return int(solve.driver_idx), np.asarray(solve.exec_counts)[:n]

    @staticmethod
    def _subtract(avail, d_idx, counts, problem, app_idx, n):
        """The reference's usage-overwrite quirk in scaled int space."""
        exec_mask = counts > 0
        delta = np.zeros((avail.shape[0], 3), np.int32)
        delta[:n][exec_mask] = problem.executor[app_idx]
        if not exec_mask[d_idx]:
            delta[d_idx] = problem.driver[app_idx]
        avail -= delta


def _reserved_rows(n, d_idx, counts, problem, app_idx):
    rows = np.zeros((n, 3), np.int64)
    rows += counts.astype(np.int64)[:, None] * problem.executor[app_idx].astype(np.int64)[None, :]
    rows[d_idx] += problem.driver[app_idx].astype(np.int64)
    return rows
