"""FIFO queue solver: the extender's earlier-drivers pass on device.

Replaces the host loop of resource.go:224-262 (binpack every earlier
driver, subtract its usage, fail if an enforced driver doesn't fit) with
ONE whole-queue device solve (batch_solver.solve_queue), then packs the
current driver against the resulting availability.  Decisions are
bit-identical to the oracle loop (tests/test_fifo_solver.py); problems
that can't be exactly tensorized fall back to the host path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..types.resources import NodeGroupSchedulingMetadata
from .batch_adapter import (
    build_reserved,
    candidate_zone_masks,
    counts_to_evenly_list,
    counts_to_tightly_list,
    evenly_counts,
)
from .efficiency import compute_packing_efficiencies
from .packers import PackingResult, empty_packing_result
from .sparkapp import AppDemand
from .tensorize import _resources_to_base as _res_rows
from .tensorize import scale_problem, tensorize_apps, tensorize_cluster

logger = logging.getLogger(__name__)


def _ceil_div(v: int, d: int) -> int:
    return -((-v) // d)


def efficiencies_from_rows(names, sched_rows, avail_rows, reserved_rows):
    """compute_packing_efficiencies from exact base-unit int rows —
    bit-identical floats to the Quantity path (efficiency.go:80-105):
    per-dim reserved = schedulable − available + newly_reserved, then
    Quantity.value() semantics (ceil to canonical units) and ratio."""
    from .efficiency import PackingEfficiency

    out = {}
    for i, name in enumerate(names):
        s_cpu = _ceil_div(int(sched_rows[i, 0]), 1000)
        s_mem = int(sched_rows[i, 1])
        s_gpu = _ceil_div(int(sched_rows[i, 2]), 1000)
        r = sched_rows[i] - avail_rows[i] + reserved_rows[i]
        r_cpu = _ceil_div(int(r[0]), 1000)
        r_mem = int(r[1])
        r_gpu = _ceil_div(int(r[2]), 1000)
        gpu_eff = 0.0
        if s_gpu != 0:
            gpu_eff = float(r_gpu) / float(s_gpu if s_gpu != 0 else 1)
        out[name] = PackingEfficiency(
            node_name=name,
            cpu=float(r_cpu) / float(s_cpu if s_cpu != 0 else 1),
            memory=float(r_mem) / float(s_mem if s_mem != 0 else 1),
            gpu=gpu_eff,
        )
    return out


@dataclass
class FifoOutcome:
    """Result of the combined earlier-drivers + current-driver solve."""

    supported: bool  # False → caller must use the host oracle path
    earlier_ok: bool = True  # False → an enforced earlier driver doesn't fit
    result: Optional[PackingResult] = None  # current driver's packing


class TpuFifoSolver:
    """One device round for the whole FIFO queue + the current driver."""

    def __init__(self, assignment_policy: str = "tightly-pack"):
        self.assignment_policy = assignment_policy

    def solve(
        self,
        metadata: NodeGroupSchedulingMetadata,
        driver_order: Sequence[str],
        executor_order: Sequence[str],
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
    ) -> FifoOutcome:
        cluster = tensorize_cluster(metadata, driver_order, executor_order)
        return self.solve_tensor(
            cluster, earlier_apps, earlier_skip_allowed, current_app, metadata=metadata
        )

    def solve_tensor(
        self,
        cluster,
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
        metadata: Optional[NodeGroupSchedulingMetadata] = None,
    ) -> FifoOutcome:
        """Solve from a prebuilt ClusterTensor (the tensor-snapshot fast
        path passes one directly; `metadata` is only used for the
        Quantity-based efficiency computation when provided)."""
        import jax.numpy as jnp

        from .batch_solver import solve_queue, solve_single

        apps = tensorize_apps(list(earlier_apps) + [current_app])
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            return FifoOutcome(supported=False)

        evenly = self.assignment_policy == "distribute-evenly"
        n_earlier = len(earlier_apps)

        if n_earlier > 0:
            # whole-queue pass over the earlier drivers only
            queue_valid = problem.app_valid.copy()
            queue_valid[n_earlier:] = False
            out = solve_queue(
                jnp.asarray(problem.avail),
                jnp.asarray(problem.driver_rank),
                jnp.asarray(problem.exec_ok),
                jnp.asarray(problem.driver),
                jnp.asarray(problem.executor),
                jnp.asarray(problem.count),
                jnp.asarray(queue_valid),
                evenly=evenly,
                with_placements=False,
            )
            feasible = np.asarray(out.feasible)[:n_earlier]
            # an enforced (old-enough) earlier driver that doesn't fit
            # fails the whole request (resource.go:244-253)
            for i in range(n_earlier):
                if not feasible[i] and not earlier_skip_allowed[i]:
                    return FifoOutcome(supported=True, earlier_ok=False)
            avail_after = out.avail_after
        else:
            avail_after = jnp.asarray(problem.avail)

        solve = solve_single(
            avail_after,
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver[n_earlier]),
            jnp.asarray(problem.executor[n_earlier]),
            jnp.asarray(problem.count[n_earlier]),
        )
        if not bool(solve.feasible):
            return FifoOutcome(supported=True, earlier_ok=True, result=empty_packing_result())

        names = cluster.node_names
        driver_node = names[int(solve.driver_idx)]
        k = current_app.min_executor_count
        if evenly:
            cap = np.asarray(solve.exec_capacity)[: len(names)]
            counts = evenly_counts(cap, k)
            executor_nodes = counts_to_evenly_list(names, counts)
        else:
            counts = np.asarray(solve.exec_counts)[: len(names)]
            executor_nodes = counts_to_tightly_list(names, counts)

        # efficiencies feed metrics only on this path (non-single-AZ
        # policies); computed vs the original snapshot like the oracle
        if metadata is not None:
            reserved = build_reserved(
                names, counts, driver_node, current_app.driver_resources,
                current_app.executor_resources,
            )
            efficiencies = compute_packing_efficiencies(metadata, reserved)
        else:
            # per-node reserved = count × executor (+ driver on its node)
            reserved_rows = np.zeros_like(cluster.avail)
            drv_row, _ = _res_rows(current_app.driver_resources)
            exec_row, _ = _res_rows(current_app.executor_resources)
            reserved_rows[int(solve.driver_idx)] += np.array(drv_row, np.int64)
            reserved_rows[: len(names)] += (
                counts.astype(np.int64)[:, None] * np.array(exec_row, np.int64)[None, :]
            )
            efficiencies = efficiencies_from_rows(
                names, cluster.sched, cluster.avail, reserved_rows
            )
        result = PackingResult(
            driver_node=driver_node,
            executor_nodes=executor_nodes,
            has_capacity=True,
            packing_efficiencies=efficiencies,
        )
        return FifoOutcome(supported=True, earlier_ok=True, result=result)


class TpuSingleAzFifoSolver:
    """FIFO pass for the single-AZ policies: each earlier driver's
    per-zone gang solves run in ONE vmapped device call (solve_zones);
    the zone choice (float64 efficiency, oracle functions) and the
    carried usage subtraction (exact scaled ints with the reference's
    overwrite quirk) run on host.  az_aware adds the cross-zone fallback
    for each driver (az_aware_pack_tightly.go:27-38)."""

    def __init__(self, az_aware: bool = False):
        self.az_aware = az_aware

    def solve(
        self,
        metadata: NodeGroupSchedulingMetadata,
        driver_order: Sequence[str],
        executor_order: Sequence[str],
        earlier_apps: List[AppDemand],
        earlier_skip_allowed: List[bool],
        current_app: AppDemand,
    ) -> FifoOutcome:
        import jax.numpy as jnp

        from . import packers
        from .batch_solver import solve_zones_jit

        cluster = tensorize_cluster(metadata, driver_order, executor_order)
        all_apps = list(earlier_apps) + [current_app]
        apps = tensorize_apps(all_apps)
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            return FifoOutcome(supported=False)

        names = cluster.node_names
        n = len(names)
        nb = problem.avail.shape[0]
        scale = problem.scale.astype(np.int64)

        candidate_zones, zone_masks = candidate_zone_masks(
            driver_order, executor_order, metadata, names, nb
        )
        zone_masks_dev = jnp.asarray(zone_masks)
        rank_dev = jnp.asarray(problem.driver_rank)
        exec_dev = jnp.asarray(problem.exec_ok)

        avail = problem.avail.astype(np.int32).copy()  # scaled, mutated per driver

        def pack_one(app_idx: int):
            """Device zone solves + host zone choice for one app.
            Returns (driver_idx, counts) or None when infeasible."""
            if not candidate_zones:
                return None  # no zone has both driver and executor candidates
            solves = solve_zones_jit(
                jnp.asarray(avail),
                rank_dev,
                exec_dev,
                zone_masks_dev,
                jnp.asarray(problem.driver[app_idx]),
                jnp.asarray(problem.executor[app_idx]),
                jnp.asarray(problem.count[app_idx]),
            )
            feasible = np.asarray(solves.feasible)
            driver_idx = np.asarray(solves.driver_idx)
            counts_all = np.asarray(solves.exec_counts)

            results = []
            per_zone = []
            for zi, zone in enumerate(candidate_zones):
                if not feasible[zi]:
                    continue
                d_idx = int(driver_idx[zi])
                zone_counts = counts_all[zi][:n]
                results.append(
                    PackingResult(
                        driver_node=names[d_idx],
                        executor_nodes=counts_to_tightly_list(names, zone_counts),
                        has_capacity=True,
                        packing_efficiencies=efficiencies_from_rows(
                            names,
                            cluster.sched,
                            avail.astype(np.int64) * scale[None, :],
                            _reserved_rows(
                                n, d_idx, zone_counts, problem, app_idx
                            ) * scale[None, :],
                        ),
                    )
                )
                per_zone.append((d_idx, zone_counts))
            if not results:
                return None
            best = packers._choose_best_result(metadata, results)
            if not best.has_capacity:
                # the all-zero-efficiency quirk: single-az yields nothing;
                # the caller's az_aware fallback handles the cross-zone pack
                return None
            choice = results.index(best)
            d_idx, counts = per_zone[choice]
            return d_idx, counts, best

        def plain_fallback(app_idx):
            return self._plain_pack(app_idx, avail, problem, n)

        for i, app in enumerate(earlier_apps):
            packed = pack_one(i)
            if packed is None and self.az_aware:
                fallback = plain_fallback(i)
                packed = fallback if fallback is None else (*fallback, None)
            if packed is None:
                if earlier_skip_allowed[i]:
                    continue
                return FifoOutcome(supported=True, earlier_ok=False)
            d_idx, counts = packed[0], packed[1]
            self._subtract(avail, d_idx, counts, problem, i, n)

        packed = pack_one(len(earlier_apps))
        if packed is None and self.az_aware:
            fallback = plain_fallback(len(earlier_apps))
            packed = fallback if fallback is None else (*fallback, None)
        if packed is None:
            return FifoOutcome(supported=True, earlier_ok=True, result=empty_packing_result())
        d_idx, counts, chosen = packed
        if chosen is None:
            # cross-zone fallback path: build the result from counts
            chosen = PackingResult(
                driver_node=names[d_idx],
                executor_nodes=counts_to_tightly_list(names, counts),
                has_capacity=True,
                packing_efficiencies=efficiencies_from_rows(
                    names,
                    cluster.sched,
                    avail.astype(np.int64) * scale[None, :],
                    _reserved_rows(n, d_idx, counts, problem, len(earlier_apps))
                    * scale[None, :],
                ),
            )
        return FifoOutcome(supported=True, earlier_ok=True, result=chosen)

    @staticmethod
    def _plain_pack(app_idx, avail, problem, n):
        """Cross-zone tightly-pack (the az-aware fallback)."""
        import jax.numpy as jnp

        from .batch_solver import solve_single

        solve = solve_single(
            jnp.asarray(avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver[app_idx]),
            jnp.asarray(problem.executor[app_idx]),
            jnp.asarray(problem.count[app_idx]),
        )
        if not bool(solve.feasible):
            return None
        return int(solve.driver_idx), np.asarray(solve.exec_counts)[:n]

    @staticmethod
    def _subtract(avail, d_idx, counts, problem, app_idx, n):
        """The reference's usage-overwrite quirk in scaled int space."""
        exec_mask = counts > 0
        delta = np.zeros((avail.shape[0], 3), np.int32)
        delta[:n][exec_mask] = problem.executor[app_idx]
        if not exec_mask[d_idx]:
            delta[d_idx] = problem.driver[app_idx]
        avail -= delta


def _reserved_rows(n, d_idx, counts, problem, app_idx):
    rows = np.zeros((n, 3), np.int64)
    rows += counts.astype(np.int64)[:, None] * problem.executor[app_idx].astype(np.int64)[None, :]
    rows[d_idx] += problem.driver[app_idx].astype(np.int64)
    return rows
