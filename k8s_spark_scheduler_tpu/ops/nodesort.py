"""AZ-aware node priority ordering (reference ``internal/sort/nodesorting.go``).

Priority: AZs ascending by total available resources (memory before CPU),
nodes within an AZ ascending by (memory, cpu), then name.  Driver
candidates are the intersection with kube-scheduler's candidate list;
executor candidates are all schedulable+ready nodes.  Optional per-role
label-priority stable re-sort (nodesorting.go:161-180).

The reference's Go map iteration makes AZ/node ties nondeterministic; we
break ties deterministically (zone name, node name) which stays inside the
reference's behavior envelope.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..types.resources import (
    NodeGroupSchedulingMetadata,
    Resources,
)


@dataclass
class LabelPriorityOrder:
    """config.LabelPriorityOrder (config/config.go:81-84)."""

    name: str
    descending_priority_values: List[str]


def get_node_names_in_priority_order(metadata: NodeGroupSchedulingMetadata) -> List[str]:
    """nodesorting.go:95-122."""
    by_az: Dict[str, List[str]] = {}
    for node_name, md in metadata.items():
        by_az.setdefault(md.zone_label, []).append(node_name)

    az_totals: Dict[str, Resources] = {}
    for az, nodes in by_az.items():
        total = Resources.zero()
        for n in nodes:
            total = total.add(metadata[n].available)
        az_totals[az] = total

    az_order = sorted(
        by_az.keys(),
        key=lambda az: (az_totals[az].memory.exact, az_totals[az].cpu.exact, az),
    )
    az_priority = {az: i for i, az in enumerate(az_order)}

    return sorted(
        metadata.keys(),
        key=lambda n: (
            az_priority[metadata[n].zone_label],
            metadata[n].available.memory.exact,
            metadata[n].available.cpu.exact,
            n,
        ),
    )


def _label_less_than(
    order: LabelPriorityOrder,
) -> "callable":
    value_ranks = {v: i for i, v in enumerate(order.descending_priority_values)}

    def less_than(md1, md2) -> bool:
        rank1 = value_ranks.get(md1.all_labels.get(order.name)) if md1 is not None else None
        rank2 = value_ranks.get(md2.all_labels.get(order.name)) if md2 is not None else None
        if rank1 is None:
            return False
        if rank2 is None:
            return True
        return rank1 < rank2

    return less_than


def _stable_sort_by_less_than(names: List[str], metadata, less_than) -> List[str]:
    return sorted(
        names,
        key=functools.cmp_to_key(
            lambda a, b: -1
            if less_than(metadata.get(a), metadata.get(b))
            else (1 if less_than(metadata.get(b), metadata.get(a)) else 0)
        ),
    )


class NodeSorter:
    """nodesorting.go:25-64."""

    def __init__(
        self,
        driver_prioritized_node_label: Optional[LabelPriorityOrder] = None,
        executor_prioritized_node_label: Optional[LabelPriorityOrder] = None,
    ):
        # public capability surface: consumers (the tensor fast path)
        # read these instead of the comparator internals
        self.driver_label_priority = driver_prioritized_node_label
        self.executor_label_priority = executor_prioritized_node_label
        self._driver_less_than = (
            _label_less_than(driver_prioritized_node_label)
            if driver_prioritized_node_label
            else None
        )
        self._executor_less_than = (
            _label_less_than(executor_prioritized_node_label)
            if executor_prioritized_node_label
            else None
        )

    def potential_nodes(
        self, metadata: NodeGroupSchedulingMetadata, node_names: Sequence[str]
    ) -> Tuple[List[str], List[str]]:
        """(driver candidates ∩ kube list, executor candidates) both in
        priority order (nodesorting.go:41-64)."""
        priority_order = get_node_names_in_priority_order(metadata)
        candidate_set = set(node_names)
        driver_nodes = [n for n in priority_order if n in candidate_set]
        executor_nodes = [
            n for n in priority_order if not metadata[n].unschedulable and metadata[n].ready
        ]
        if self._driver_less_than is not None:
            driver_nodes = _stable_sort_by_less_than(driver_nodes, metadata, self._driver_less_than)
        if self._executor_less_than is not None:
            executor_nodes = _stable_sort_by_less_than(
                executor_nodes, metadata, self._executor_less_than
            )
        return driver_nodes, executor_nodes
