"""Gang binpacking oracles — exact reference semantics on host.

These are the scalar "oracles" for the five packing policies of the
reference (``lib/pkg/binpack/``): tightly-pack, distribute-evenly,
az-aware-tightly-pack, single-az-tightly-pack, single-az-minimal-
fragmentation (+ plain minimal-fragmentation used internally).  The TPU
batch solver (:mod:`.batch_solver`) is validated against these decision
for decision; the oracles are also the fallback execution path.

Behavioral quirks of the reference are reproduced deliberately and marked
with ``# QUIRK`` comments — parity gates on decisions, not on cleaned-up
semantics.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import compat
from ..types.resources import (
    NodeGroupResources,
    NodeGroupSchedulingMetadata,
    Resources,
)
from . import capacity as cap
from .efficiency import (
    PackingEfficiency,
    compute_avg_packing_efficiency,
    compute_packing_efficiencies,
    worst_avg_packing_efficiency,
)


@dataclass
class PackingResult:
    """Result of one gang binpacking (binpack.go:25-40)."""

    driver_node: str = ""
    executor_nodes: List[str] = field(default_factory=list)
    packing_efficiencies: Dict[str, PackingEfficiency] = field(default_factory=dict)
    has_capacity: bool = False
    # set by the tensor fast lanes: avg of per-node max efficiencies with
    # the same float64 value the metrics path would compute by iterating
    # packing_efficiencies — lets the gauge skip materializing 10k lazy
    # entries per request
    max_avg_efficiency: Optional[float] = None


def empty_packing_result() -> PackingResult:
    return PackingResult()


# GenericBinPackFunction (binpack.go:52-57): distributes `count` identical
# items over nodes; returns (nodes, ok) and mutates reserved_resources.
GenericBinPackFunction = Callable[
    [Resources, int, Sequence[str], NodeGroupSchedulingMetadata, NodeGroupResources],
    Tuple[Optional[List[str]], bool],
]

# SparkBinPackFunction (binpack.go:43-50)
SparkBinPackFunction = Callable[
    [Resources, Resources, int, Sequence[str], Sequence[str], NodeGroupSchedulingMetadata],
    PackingResult,
]


def spark_bin_pack(
    driver_resources: Resources,
    executor_resources: Resources,
    executor_count: int,
    driver_node_priority_order: Sequence[str],
    executor_node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
    distribute_executors: GenericBinPackFunction,
) -> PackingResult:
    """Driver-first gang packing loop (binpack.go:60-87): first driver node
    with capacity whose executor distribution succeeds wins."""
    for driver_node_name in driver_node_priority_order:
        md = metadata.get(driver_node_name)
        if md is None or driver_resources.greater_than(md.available):
            continue
        reserved: NodeGroupResources = {driver_node_name: driver_resources.copy()}
        executor_nodes, ok = distribute_executors(
            executor_resources, executor_count, executor_node_priority_order, metadata, reserved
        )
        if ok:
            return PackingResult(
                driver_node=driver_node_name,
                executor_nodes=list(executor_nodes or []),
                has_capacity=True,
                packing_efficiencies=compute_packing_efficiencies(metadata, reserved),
            )
    return empty_packing_result()


def tightly_pack_executors(
    executor_resources: Resources,
    executor_count: int,
    node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
    reserved_resources: NodeGroupResources,
) -> Tuple[Optional[List[str]], bool]:
    """First-fit: fill each node to capacity before moving on
    (pack_tightly.go:34-63)."""
    executor_nodes: List[str] = []
    if executor_count == 0:
        return executor_nodes, True
    for n in node_priority_order:
        if n not in reserved_resources:
            reserved_resources[n] = Resources.zero()
        while True:
            reserved_resources[n] = reserved_resources[n].add(executor_resources)
            md = metadata.get(n)
            if md is None or reserved_resources[n].greater_than(md.available):
                reserved_resources[n] = reserved_resources[n].sub(executor_resources)
                break
            executor_nodes.append(n)
            if len(executor_nodes) == executor_count:
                return executor_nodes, True
    return None, False


def distribute_executors_evenly(
    executor_resources: Resources,
    executor_count: int,
    node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
    reserved_resources: NodeGroupResources,
) -> Tuple[Optional[List[str]], bool]:
    """Round-robin one executor per node per sweep (distribute_evenly.go:34-73)."""
    available_nodes = {name for name in node_priority_order}
    executor_nodes: List[str] = []
    if executor_count == 0:
        return executor_nodes, True
    while available_nodes:
        for n in node_priority_order:
            if n not in available_nodes:
                continue
            if n not in reserved_resources:
                reserved_resources[n] = Resources.zero()
            reserved_resources[n] = reserved_resources[n].add(executor_resources)
            md = metadata.get(n)
            if md is None or reserved_resources[n].greater_than(md.available):
                available_nodes.discard(n)
                reserved_resources[n] = reserved_resources[n].sub(executor_resources)
            else:
                executor_nodes.append(n)
                if len(executor_nodes) == executor_count:
                    return executor_nodes, True
    return None, False


def make_minimal_fragmentation(
    strict_reference_parity: bool = compat.DEFAULT_STRICT,
) -> GenericBinPackFunction:
    """Prefer fewest hosts, avoiding mostly-empty nodes unless needed
    (minimal_fragmentation.go:59-94).

    QUIRK (switchable, install key ``strict-reference-parity``): unlike
    the other distribution functions the reference never writes back into
    reserved_resources, so packing efficiencies reported upstream reflect
    only the driver reservation.  With strict parity off the placements
    are folded in and efficiencies are complete.
    """

    def minimal_fragmentation(
        executor_resources: Resources,
        executor_count: int,
        node_priority_order: Sequence[str],
        metadata: NodeGroupSchedulingMetadata,
        reserved_resources: NodeGroupResources,
    ) -> Tuple[Optional[List[str]], bool]:
        if executor_count == 0:
            return [], True

        capacities = cap.get_node_capacities(
            node_priority_order, metadata, reserved_resources, executor_resources
        )
        capacities = cap.filter_out_nodes_without_capacity(capacities)
        executor_nodes, ok = minimal_fragmentation_from_capacities(
            executor_count, capacities
        )
        if ok and executor_nodes and not strict_reference_parity:
            for n in executor_nodes:
                reserved_resources[n] = reserved_resources.get(
                    n, Resources.zero()
                ).add(executor_resources)
        return executor_nodes, ok

    return minimal_fragmentation


# strict default instance (the reference's exact behavior)
minimal_fragmentation = make_minimal_fragmentation()


def minimal_fragmentation_from_capacities(
    executor_count: int, capacities: List[cap.NodeAndExecutorCapacity]
) -> Tuple[Optional[List[str]], bool]:
    """The capacity-driven core of minimal_fragmentation.go:71-94, shared
    by the oracle and the device decode (bit-identical is a parity
    requirement)."""
    if not capacities:
        return None, False

    capacities = sorted(capacities, key=lambda c: c.capacity)  # stable, ascending
    max_capacity = capacities[-1].capacity
    if executor_count < max_capacity:
        target_capacity = (executor_count + max_capacity) // 2
        first_at_least_target = bisect.bisect_left(
            [c.capacity for c in capacities], target_capacity
        )
        # try a subset that excludes the 'emptiest' nodes
        executor_nodes, ok = _internal_minimal_fragmentation(
            executor_count, capacities[:first_at_least_target]
        )
        if ok:
            return executor_nodes, True

    return _internal_minimal_fragmentation(executor_count, capacities)


def _internal_minimal_fragmentation(
    executor_count: int,
    node_capacities: List[cap.NodeAndExecutorCapacity],
) -> Tuple[Optional[List[str]], bool]:
    """minimal_fragmentation.go:96-137."""
    remaining = list(node_capacities)
    executor_nodes: List[str] = []

    while remaining:
        keys = [c.capacity for c in remaining]
        # first node that can fit everything that's left
        position = bisect.bisect_left(keys, executor_count)
        if position != len(remaining):
            executor_nodes.extend([remaining[position].node_name] * executor_count)
            return executor_nodes, True

        # drain max-capacity nodes
        max_capacity = remaining[-1].capacity
        first_max_idx = bisect.bisect_left(keys, max_capacity)
        current_pos = first_max_idx
        while executor_count >= max_capacity and current_pos < len(remaining):
            executor_nodes.extend([remaining[current_pos].node_name] * max_capacity)
            executor_count -= max_capacity
            current_pos += 1

        if executor_count == 0:
            return executor_nodes, True

        remaining = remaining[:first_max_idx] + remaining[current_pos:]

    return None, False


# ---------------------------------------------------------------------------
# Single-AZ combinator (single_az.go)
# ---------------------------------------------------------------------------


def group_nodes_by_zone(
    node_names: Sequence[str], metadata: NodeGroupSchedulingMetadata
) -> Tuple[List[str], Dict[str, List[str]]]:
    """(zones in first-appearance order, zone → nodes in order)
    (single_az.go:57-72); nodes missing from metadata are dropped."""
    zones_in_order: List[str] = []
    by_zone: Dict[str, List[str]] = {}
    for node_name in node_names:
        md = metadata.get(node_name)
        if md is None:
            continue
        zone = md.zone_label
        if zone not in by_zone:
            zones_in_order.append(zone)
            by_zone[zone] = []
        by_zone[zone].append(node_name)
    return zones_in_order, by_zone


def _choose_best_result(
    metadata: NodeGroupSchedulingMetadata, results: List[PackingResult]
) -> PackingResult:
    """Highest avg packing efficiency among feasible AZs (single_az.go:75-97).

    QUIRK: per-node efficiencies are collected once per pod occurrence
    (driver + each executor), so multi-executor nodes weigh more; and a
    candidate only replaces the current best on a strict Max improvement,
    so an all-zero-efficiency result set returns the empty (infeasible)
    result.
    """
    best = empty_packing_result()
    best_avg = worst_avg_packing_efficiency()
    for result in results:
        node_names = [result.driver_node] + list(result.executor_nodes)
        effs = [result.packing_efficiencies[n] for n in node_names]
        avg = compute_avg_packing_efficiency(metadata, effs)
        if best_avg.less_than(avg):
            best = result
            best_avg = avg
    return best


def _single_az_spark_bin_function(fn: GenericBinPackFunction) -> SparkBinPackFunction:
    """single_az.go:23-55: run the inner packer per AZ, keep feasible AZs,
    pick the best by avg packing efficiency."""

    def packer(
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_node_priority_order: Sequence[str],
        executor_node_priority_order: Sequence[str],
        metadata: NodeGroupSchedulingMetadata,
    ) -> PackingResult:
        driver_zones_in_order, driver_by_zone = group_nodes_by_zone(
            driver_node_priority_order, metadata
        )
        _, executor_by_zone = group_nodes_by_zone(executor_node_priority_order, metadata)

        results: List[PackingResult] = []
        for zone in driver_zones_in_order:
            executor_order = executor_by_zone.get(zone)
            if executor_order is None:
                continue
            result = spark_bin_pack(
                driver_resources,
                executor_resources,
                executor_count,
                driver_by_zone[zone],
                executor_order,
                metadata,
                fn,
            )
            if result.has_capacity:
                results.append(result)

        if not results:
            return empty_packing_result()
        return _choose_best_result(metadata, results)

    return packer


# ---------------------------------------------------------------------------
# The five named SparkBinPackFunctions
# ---------------------------------------------------------------------------


def tightly_pack(
    driver_resources: Resources,
    executor_resources: Resources,
    executor_count: int,
    driver_node_priority_order: Sequence[str],
    executor_node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
) -> PackingResult:
    return spark_bin_pack(
        driver_resources,
        executor_resources,
        executor_count,
        driver_node_priority_order,
        executor_node_priority_order,
        metadata,
        tightly_pack_executors,
    )


def distribute_evenly(
    driver_resources: Resources,
    executor_resources: Resources,
    executor_count: int,
    driver_node_priority_order: Sequence[str],
    executor_node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
) -> PackingResult:
    return spark_bin_pack(
        driver_resources,
        executor_resources,
        executor_count,
        driver_node_priority_order,
        executor_node_priority_order,
        metadata,
        distribute_executors_evenly,
    )


def make_minimal_fragmentation_pack(
    strict_reference_parity: bool = compat.DEFAULT_STRICT,
) -> SparkBinPackFunction:
    fn = make_minimal_fragmentation(strict_reference_parity)

    def minimal_fragmentation_pack(
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_node_priority_order: Sequence[str],
        executor_node_priority_order: Sequence[str],
        metadata: NodeGroupSchedulingMetadata,
    ) -> PackingResult:
        return spark_bin_pack(
            driver_resources,
            executor_resources,
            executor_count,
            driver_node_priority_order,
            executor_node_priority_order,
            metadata,
            fn,
        )

    return minimal_fragmentation_pack


def make_single_az_minimal_fragmentation(
    strict_reference_parity: bool = compat.DEFAULT_STRICT,
) -> SparkBinPackFunction:
    return _single_az_spark_bin_function(
        make_minimal_fragmentation(strict_reference_parity)
    )


minimal_fragmentation_pack = make_minimal_fragmentation_pack()
single_az_tightly_pack = _single_az_spark_bin_function(tightly_pack_executors)
single_az_minimal_fragmentation = make_single_az_minimal_fragmentation()


def az_aware_tightly_pack(
    driver_resources: Resources,
    executor_resources: Resources,
    executor_count: int,
    driver_node_priority_order: Sequence[str],
    executor_node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
) -> PackingResult:
    """Single-AZ first, fall back to plain tightly-pack
    (az_aware_pack_tightly.go:27-38)."""
    result = single_az_tightly_pack(
        driver_resources,
        executor_resources,
        executor_count,
        driver_node_priority_order,
        executor_node_priority_order,
        metadata,
    )
    if result.has_capacity:
        return result
    return tightly_pack(
        driver_resources,
        executor_resources,
        executor_count,
        driver_node_priority_order,
        executor_node_priority_order,
        metadata,
    )
