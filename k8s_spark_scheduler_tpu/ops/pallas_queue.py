"""Pallas TPU kernel for the whole-FIFO-queue gang solve.

The XLA `lax.scan` path (batch_solver.solve_queue) pays per-iteration
dispatch + HBM round-trips for the availability carry; at 1k apps that
overhead dominates (~90µs/step).  This kernel instead runs the queue as
a single `pallas_call` with grid=(A,):

- the cluster availability lives in VMEM scratch, initialized from HBM
  on the first grid step and updated in place after each app — TPU grid
  steps execute sequentially on a core, so the scratch IS the scan
  carry, with zero HBM traffic per step;
- per-app demands are int32 scalars in SMEM via scalar prefetch;
- node arrays are laid out [R, 128] (row-major flattening of the
  priority order) so capacity math runs full-width on the VPU, with
  the flattened-order prefix sums done as lane-cumsum + row-offset.

Decision semantics are identical to batch_solver.solve_app (same
parity guarantees); this kernel returns per-app decisions (feasible,
driver node index) plus the final availability.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batch_solver import EFF_SHIFT, MF_SENT

LANES = 128
BIG = 2**31 - 1  # plain int: a module-level jnp scalar would be a captured const in the kernel


def _row_layout(n: int) -> Tuple[int, int]:
    rows = (n + LANES - 1) // LANES
    # sublane multiple of 8 for int32 tiling
    rows = ((rows + 7) // 8) * 8
    return rows, rows * LANES


def _inclusive_scan(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Hillis–Steele inclusive prefix sum via log-step circular shifts
    (mosaic has no cumsum primitive).  Wrapped lanes are masked off."""
    size = x.shape[axis]
    ids = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    d = 1
    while d < size:
        shifted = pltpu.roll(x, shift=d, axis=axis)
        x = x + jnp.where(ids >= d, shifted, 0)
        d *= 2
    return x


def _flat_cumsum_exclusive(x: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix sum of a [R, 128] int32 array in row-major
    (flattened) order: lane-axis scan within rows plus an exclusive
    row-offset scan across rows."""
    within = _inclusive_scan(x, axis=1)
    row_tot = jnp.broadcast_to(within[:, -1:], x.shape)
    row_incl = _inclusive_scan(row_tot, axis=0)  # lane-constant
    row_off = row_incl - row_tot
    return within + row_off - x


def _queue_kernel(
    # scalar prefetch (SMEM): per-app demand vectors
    dcpu, dmem, dgpu, ecpu, emem, egpu, ks, valids,
    # array inputs (VMEM)
    avail0,        # [R, 128] cpu plane (availability split into 3 planes)
    availm0,       # [R, 128] memory plane
    availg0,       # [R, 128] gpu plane
    rank_ref,      # [R, 128] int32 driver rank (BIG = not a candidate)
    execok_ref,    # [R, 128] int32 0/1
    # outputs
    feas_ref,      # per-app rows (lane 0 = feasible, lane 1 = driver idx)
    avail_out,     # [R, 128] ×3 final availability planes
    availm_out,
    availg_out,
    # scratch: availability carry
    ac, am, ag,
    *,
    evenly: bool,
    n_apps: int,
    apps_per_step: int,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ac[...] = avail0[...]
        am[...] = availm0[...]
        ag[...] = availg0[...]

    rank = rank_ref[...]
    exec_ok = execok_ref[...] != 0
    rows, lanes = rank.shape
    row_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    lane_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    node_ids = row_ids * lanes + lane_ids
    out_lanes = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    # the grid sequences blocks of `apps_per_step` apps; the inner loop is
    # unrolled at trace time, amortizing per-grid-step overhead (grid
    # pipelining + output DMA) over several apps
    for j in range(apps_per_step):
        a = i * apps_per_step + j
        dr = jnp.array([dcpu[a], dmem[a], dgpu[a]], dtype=jnp.int32)
        ex = jnp.array([ecpu[a], emem[a], egpu[a]], dtype=jnp.int32)
        k = ks[a]
        valid = valids[a]

        cpu, mem, gpu = ac[...], am[...], ag[...]

        feasible0, flat_idx, is_driver0, cap0 = _gang_core(
            cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids
        )
        feasible = feasible0 & (valid != 0)
        is_driver = is_driver0 & feasible
        cap = jnp.where(feasible, cap0, 0)

        if evenly:
            has = (cap > 0).astype(jnp.int32)
            rank_excl = _flat_cumsum_exclusive(has)
            exec_mask = (cap > 0) & (rank_excl < k)
        else:
            cum_excl = _flat_cumsum_exclusive(cap)
            x = jnp.clip(k - cum_excl, 0, cap)
            exec_mask = x > 0
        exec_mask = exec_mask & feasible

        # the reference's usage-subtraction quirk: executor overwrites driver
        dc = jnp.where(exec_mask, ex[0], jnp.where(is_driver, dr[0], 0))
        dm = jnp.where(exec_mask, ex[1], jnp.where(is_driver, dr[1], 0))
        dg = jnp.where(exec_mask, ex[2], jnp.where(is_driver, dr[2], 0))
        ac[...] = cpu - dc
        am[...] = mem - dm
        ag[...] = gpu - dg

        # outputs: 8 app-rows per (8, 128) tile
        idx_val = jnp.where(feasible, flat_idx, jnp.int32(rows * lanes))
        out_row = jnp.where(
            out_lanes == 0,
            feasible.astype(jnp.int32),
            jnp.where(out_lanes == 1, idx_val, 0),
        )
        feas_ref[pl.ds((i * apps_per_step + j) % 8, 1), :] = out_row

    @pl.when(i == (n_apps // apps_per_step) - 1)
    def _final():
        avail_out[...] = ac[...]
        availm_out[...] = am[...]
        availg_out[...] = ag[...]


def _gang_core(cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids):
    """The shared gang-solve core on [R, 128] planes (zone-maskable via
    rank/exec_ok), used by both queue kernels: driver selection by the
    capacity-total identity.  Returns (feasible, flat_idx, is_driver,
    cap) with cap already driver-adjusted and zeroed when infeasible."""

    def caps(c, m, g):
        def dim(avail_d, req):
            # zero-requirement → ∞ unless the dimension is already
            # negative (reserved(0) > available → 0, capacity.go:37-44)
            unbounded = jnp.where(avail_d >= 0, BIG, 0)
            return jnp.where(req == 0, unbounded, lax.div(avail_d, jnp.maximum(req, 1)))

        cap = jnp.minimum(jnp.minimum(dim(c, ex[0]), dim(m, ex[1])), dim(g, ex[2]))
        return jnp.clip(cap, 0, k)

    base_cap = jnp.where(exec_ok, caps(cpu, mem, gpu), 0)
    cap_with_driver = jnp.where(
        exec_ok, caps(cpu - dr[0], mem - dr[1], gpu - dr[2]), 0
    )
    driver_fits = (cpu >= dr[0]) & (mem >= dr[1]) & (gpu >= dr[2]) & (rank < BIG)
    total = jnp.sum(base_cap)
    total_d = total - base_cap + cap_with_driver
    feasible_d = driver_fits & (total_d >= k)

    masked_rank = jnp.where(feasible_d, rank, BIG)
    best_rank = jnp.min(masked_rank)
    feasible = best_rank < BIG
    flat_idx = jnp.min(jnp.where(masked_rank == best_rank, node_ids, BIG))
    is_driver = (node_ids == flat_idx) & feasible

    cap = jnp.where(is_driver, cap_with_driver, base_cap)
    cap = jnp.where(feasible, cap, 0)
    return feasible, flat_idx, is_driver, cap


def _mf_caps(cpu, mem, gpu, ex, exec_ok):
    """UNCLAMPED per-node capacity planes for the min-frag drain
    (batch_solver.min_frag_capacity): MF_SENT marks unbounded nodes."""

    def dim(avail_d, req):
        unbounded = jnp.where(avail_d >= 0, MF_SENT, 0)
        return jnp.where(req == 0, unbounded, lax.div(avail_d, jnp.maximum(req, 1)))

    cap = jnp.minimum(jnp.minimum(dim(cpu, ex[0]), dim(mem, ex[1])), dim(gpu, ex[2]))
    cap = jnp.clip(cap, 0, MF_SENT)
    return jnp.where(exec_ok, cap, 0)


def _mf_run(d, sub, k, node_ids):
    """One _internal_minimal_fragmentation pass over eligibility mask
    `sub` (batch_solver.min_frag_counts.run on [R,128] planes): the
    drain-stop value class via 31 masked-sum probes, then the drained
    mask and the final partial placement.  Returns (ok, drained,
    partial_flat_idx, kstar)."""
    dd = jnp.where(sub, d, 0)
    dc = jnp.minimum(dd, k)
    ok = (jnp.sum(dc) >= k) & (k > 0)

    def body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo + 1) // 2
        good = jnp.sum(jnp.where(dd >= mid, dc, 0)) >= k
        return (jnp.where(good, mid, lo), jnp.where(good, hi, mid - 1))

    vstar, _ = lax.fori_loop(0, 31, body, (jnp.int32(1), jnp.int32(MF_SENT)))
    s = jnp.sum(jnp.where(dd > vstar, dd, 0))  # drained classes, < k
    r = k - s
    tstar = jnp.maximum(r - 1, 0) // vstar
    kstar = r - tstar * vstar
    at = sub & (dd == vstar)
    at_rank = _flat_cumsum_exclusive(at.astype(jnp.int32))
    drained = (sub & (dd > vstar)) | (at & (at_rank < tstar))
    cand = sub & (~drained) & (dd >= kstar)
    vp = jnp.min(jnp.where(cand, dd, BIG))
    partial = jnp.min(jnp.where(cand & (dd == vp), node_ids, BIG))
    # empty candidate set → index 0, replicating the host argmax default
    partial = jnp.where(partial == BIG, 0, partial)
    return ok, drained, partial, kstar


def _solve_min_frag(cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids):
    """_gang_core feasibility/driver choice + the min-frag drain
    placement (batch_solver.min_frag_step_counts).  Returns (feasible,
    flat_idx, is_driver, counts) where counts carry the full drain
    values (n_i executors on node i; usage subtraction only needs
    counts > 0, zone scores need the values)."""
    feasible, flat_idx, is_driver, _cap = _gang_core(
        cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids
    )
    ce = cpu - jnp.where(is_driver, dr[0], 0)
    me = mem - jnp.where(is_driver, dr[1], 0)
    ge = gpu - jnp.where(is_driver, dr[2], 0)
    d = _mf_caps(ce, me, ge, ex, exec_ok)
    elig = d > 0

    max_cap = jnp.max(d)
    has_sent = jnp.any(elig & (d == MF_SENT))
    # exact (k + max)//2 without int32 overflow (batch_solver quirk:
    # an unbounded node's host threshold admits every bounded capacity)
    target = (k // 2) + (max_cap // 2) + (((k & 1) + (max_cap & 1)) // 2)
    subset = elig & jnp.where(has_sent, d < MF_SENT, d < target)
    attempt = has_sent | (k < max_cap)

    sub_ok, sub_drained, sub_partial, sub_kstar = _mf_run(
        d, subset & attempt, k, node_ids
    )
    full_ok, full_drained, full_partial, full_kstar = _mf_run(
        d, elig, k, node_ids
    )
    use_sub = attempt & sub_ok
    drained = jnp.where(use_sub, sub_drained, full_drained)
    partial = jnp.where(use_sub, sub_partial, full_partial)
    kstar = jnp.where(use_sub, sub_kstar, full_kstar)
    counts = jnp.where(drained, d, 0) + jnp.where(
        node_ids == partial, kstar, 0
    )
    counts = jnp.where(full_ok & feasible, counts, 0)
    return feasible, flat_idx, is_driver, counts


def _minfrag_queue_kernel(
    # scalar prefetch (SMEM)
    dcpu, dmem, dgpu, ecpu, emem, egpu, ks, valids,
    # VMEM planes
    avail0, availm0, availg0, rank_ref, execok_ref,
    # outputs
    feas_ref, avail_out, availm_out, availg_out,
    # scratch
    ac, am, ag,
    *,
    n_apps: int,
):
    """Whole minimal-fragmentation FIFO queue in one VMEM-resident
    kernel (batch_solver.solve_queue_min_frag decision semantics:
    tightly-pack feasibility/driver identity, min-frag drain placement,
    the usage-subtraction quirk on the carry)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ac[...] = avail0[...]
        am[...] = availm0[...]
        ag[...] = availg0[...]

    rank = rank_ref[...]
    exec_ok = execok_ref[...] != 0
    rows, lanes = rank.shape
    row_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    lane_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    node_ids = row_ids * lanes + lane_ids
    out_lanes = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    dr = jnp.array([dcpu[i], dmem[i], dgpu[i]], dtype=jnp.int32)
    ex = jnp.array([ecpu[i], emem[i], egpu[i]], dtype=jnp.int32)
    k = ks[i]
    valid = valids[i]

    cpu, mem, gpu = ac[...], am[...], ag[...]
    feasible0, flat_idx, is_driver0, counts = _solve_min_frag(
        cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids
    )
    feasible = feasible0 & (valid != 0)
    is_driver = is_driver0 & feasible
    exec_mask = (counts > 0) & feasible

    dc = jnp.where(exec_mask, ex[0], jnp.where(is_driver & ~exec_mask, dr[0], 0))
    dm = jnp.where(exec_mask, ex[1], jnp.where(is_driver & ~exec_mask, dr[1], 0))
    dg = jnp.where(exec_mask, ex[2], jnp.where(is_driver & ~exec_mask, dr[2], 0))
    ac[...] = cpu - dc
    am[...] = mem - dm
    ag[...] = gpu - dg

    idx_val = jnp.where(feasible, flat_idx, jnp.int32(rows * lanes))
    out_row = jnp.where(
        out_lanes == 0,
        feasible.astype(jnp.int32),
        jnp.where(out_lanes == 1, idx_val, 0),
    )
    feas_ref[pl.ds(i % 8, 1), :] = out_row

    @pl.when(i == n_apps - 1)
    def _final():
        avail_out[...] = ac[...]
        availm_out[...] = am[...]
        availg_out[...] = ag[...]


def _solve_tightly(cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids):
    """_gang_core + the tightly-pack greedy fill.  Returns (feasible,
    flat_idx, is_driver, exec_counts)."""
    feasible, flat_idx, is_driver, cap = _gang_core(
        cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids
    )
    cum_excl = _flat_cumsum_exclusive(cap)
    x = jnp.clip(k - cum_excl, 0, cap)
    x = jnp.where(feasible, x, 0)
    return feasible, flat_idx, is_driver, x


def _singleaz_kernel(
    # scalar prefetch (SMEM)
    dcpu, dmem, dgpu, ecpu, emem, egpu, ks, valids, scale_c_ref, scale_g_ref,
    # VMEM planes
    avail0, availm0, availg0, rank_ref, execok_ref, zone_ref,
    scpu_ref, sgpu_ref, thm_ref, invm_ref,
    # outputs
    feas_ref, avail_out, availm_out, availg_out,
    # scratch
    ac, am, ag,
    *,
    n_zones: int,
    az_aware: bool,
    n_apps: int,
    minfrag: bool = False,
    strict: bool = True,
):
    """Whole single-AZ FIFO queue in one VMEM-resident kernel: the
    pallas counterpart of batch_solver.solve_queue_single_az (same
    decision semantics: per-zone tightly-pack — or the min-frag drain
    when minfrag=True, with driver-only efficiency reservations under
    strict parity — certified fixed-point zone score at EFF_SHIFT=18,
    strict-improvement choice in zone order, az-aware cross-zone
    fallback, subtraction quirk)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ac[...] = avail0[...]
        am[...] = availm0[...]
        ag[...] = availg0[...]

    rank = rank_ref[...]
    exec_ok = execok_ref[...] != 0
    zone_plane = zone_ref[...]
    s_cpu = scpu_ref[...]
    s_gpu = sgpu_ref[...]
    th_m = thm_ref[...]
    inv_m = invm_ref[...]
    scale_c = scale_c_ref[0]
    scale_g = scale_g_ref[0]
    rows, lanes = rank.shape
    row_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    lane_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    node_ids = row_ids * lanes + lane_ids
    out_lanes = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    dr = jnp.array([dcpu[i], dmem[i], dgpu[i]], dtype=jnp.int32)
    ex = jnp.array([ecpu[i], emem[i], egpu[i]], dtype=jnp.int32)
    k = ks[i]
    valid = valids[i]
    band = 2 * (k + 1) + 2

    cpu, mem, gpu = ac[...], am[...], ag[...]
    den_c = jnp.maximum(lax.div(s_cpu + 999, jnp.int32(1000)), 1)
    den_g = jnp.maximum(lax.div(s_gpu + 999, jnp.int32(1000)), 1)
    has_gpu = s_gpu > 0

    best_q = jnp.int32(0)
    best_zone = jnp.int32(-1)
    uncertain = jnp.int32(0)
    # int32 planes (not bool): mosaic cannot legalize a select over i1
    # vectors with a scalar predicate
    chosen_exec = jnp.zeros((rows, lanes), jnp.int32)
    chosen_driver = jnp.zeros((rows, lanes), jnp.int32)
    chosen_idx = jnp.int32(rows * lanes)

    def score(x, is_driver, res=None):
        # x weights the occurrences; `res` (default x) is the
        # reservation seen by the efficiency numerators — they differ
        # only under min-frag strict parity (the no-write-back quirk)
        res = x if res is None else res
        w = x + is_driver.astype(jnp.int32)
        new_c = res * ex[0] + jnp.where(is_driver, dr[0], 0)
        new_m = res * ex[1] + jnp.where(is_driver, dr[1], 0)
        new_g = res * ex[2] + jnp.where(is_driver, dr[2], 0)
        m_c = cpu - new_c
        m_m = mem - new_m
        m_g = gpu - new_g
        num_cq = s_cpu - m_c * scale_c
        num_gq = s_gpu - m_g * scale_g
        num_cores = lax.div(num_cq + 999, jnp.int32(1000))
        num_gcores = lax.div(num_gq + 999, jnp.int32(1000))
        ratio_c = num_cores.astype(jnp.float32) / den_c.astype(jnp.float32)
        ratio_g = jnp.where(
            has_gpu, num_gcores.astype(jnp.float32) / den_g.astype(jnp.float32), 0.0
        )
        ratio_m = jnp.maximum(1.0 - m_m.astype(jnp.float32) * inv_m, 0.0)
        eff = jnp.maximum(jnp.maximum(ratio_c, ratio_m), ratio_g)
        q = jnp.floor(eff * jnp.float32(2**EFF_SHIFT) + 0.5).astype(jnp.int32)
        q_sum = jnp.sum(jnp.where(w > 0, w * q, 0))
        nz = jnp.any(
            (w > 0) & ((num_cq > 0) | (m_m < th_m) | (has_gpu & (num_gq > 0)))
        )
        return q_sum, nz

    for z in range(n_zones):
        mask = zone_plane == z
        if minfrag:
            f, flat_idx, is_driver, x = _solve_min_frag(
                cpu, mem, gpu,
                jnp.where(mask, rank, BIG), exec_ok & mask, dr, ex, k, node_ids,
            )
            res = jnp.zeros_like(x) if strict else x
            q_sum, nz = score(x, is_driver, res=res)
        else:
            f, flat_idx, is_driver, x = _solve_tightly(
                cpu, mem, gpu,
                jnp.where(mask, rank, BIG), exec_ok & mask, dr, ex, k, node_ids,
            )
            q_sum, nz = score(x, is_driver)
        first = best_zone < 0
        better = f & jnp.where(first, nz, q_sum > best_q)
        uncertain = uncertain | (
            f & (~first) & (q_sum != best_q) & (jnp.abs(q_sum - best_q) <= band)
        ).astype(jnp.int32)
        best_q = jnp.where(better, q_sum, best_q)
        best_zone = jnp.where(better, jnp.int32(z), best_zone)
        chosen_exec = jnp.where(better, (x > 0).astype(jnp.int32), chosen_exec)
        chosen_driver = jnp.where(better, is_driver.astype(jnp.int32), chosen_driver)
        chosen_idx = jnp.where(better, flat_idx, chosen_idx)

    if az_aware:
        f, flat_idx, is_driver, x = _solve_tightly(
            cpu, mem, gpu, rank, exec_ok, dr, ex, k, node_ids
        )
        use_cross = (best_zone < 0) & f
        chosen_exec = jnp.where(use_cross, (x > 0).astype(jnp.int32), chosen_exec)
        chosen_driver = jnp.where(use_cross, is_driver.astype(jnp.int32), chosen_driver)
        chosen_idx = jnp.where(use_cross, flat_idx, chosen_idx)
        best_zone = jnp.where(use_cross, jnp.int32(n_zones), best_zone)

    placed = (best_zone >= 0) & (valid != 0)
    exec_mask = (chosen_exec != 0) & placed
    driver_mask = (chosen_driver != 0) & placed & ~exec_mask

    ac[...] = cpu - jnp.where(exec_mask, ex[0], jnp.where(driver_mask, dr[0], 0))
    am[...] = mem - jnp.where(exec_mask, ex[1], jnp.where(driver_mask, dr[1], 0))
    ag[...] = gpu - jnp.where(exec_mask, ex[2], jnp.where(driver_mask, dr[2], 0))

    idx_val = jnp.where(placed, chosen_idx, jnp.int32(rows * lanes))
    zone_val = jnp.where(placed, best_zone, jnp.int32(-1))
    out_row = jnp.where(
        out_lanes == 0,
        placed.astype(jnp.int32),
        jnp.where(
            out_lanes == 1,
            idx_val,
            jnp.where(
                out_lanes == 2, zone_val, jnp.where(out_lanes == 3, uncertain, 0)
            ),
        ),
    )
    feas_ref[pl.ds(i % 8, 1), :] = out_row

    @pl.when(i == n_apps - 1)
    def _final():
        avail_out[...] = ac[...]
        availm_out[...] = am[...]
        availg_out[...] = ag[...]


@functools.partial(
    jax.jit, static_argnames=("n_zones", "az_aware", "interpret", "minfrag", "strict")
)
def pallas_solve_queue_single_az(
    avail: jnp.ndarray,        # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    zone_id: jnp.ndarray,      # [N] int32 (zone index; -1 = no candidate zone)
    drivers: jnp.ndarray,      # [A, 3] int32
    executors: jnp.ndarray,    # [A, 3] int32
    counts: jnp.ndarray,       # [A] int32
    app_valid: jnp.ndarray,    # [A] bool
    s_cpu_milli: jnp.ndarray,  # [N] int32
    s_gpu_milli: jnp.ndarray,  # [N] int32
    inv_mem: jnp.ndarray,      # [N] f32
    th_mem: jnp.ndarray,       # [N] int32
    scale_cpu: jnp.ndarray,    # [1] int32
    scale_gpu: jnp.ndarray,    # [1] int32
    n_zones: int = 1,
    az_aware: bool = False,
    interpret: bool = False,
    minfrag: bool = False,
    strict: bool = True,
):
    """Single-kernel single-AZ FIFO solve.  Returns (feasible[A],
    zone_idx[A], driver_idx[A], uncertain[A], avail_after[N, 3]) with
    decisions identical to batch_solver.solve_queue_single_az
    (tests/test_pallas_queue.py proves it on randomized queues).
    minfrag=True gives the single-az-minimal-fragmentation inner policy
    (no az_aware variant exists in the reference; caller guards
    mf_sentinel_safe)."""
    assert not (az_aware and minfrag)
    n = avail.shape[0]
    a = drivers.shape[0]
    rows, padded = _row_layout(n)

    def plane(v, fill=0, dtype=jnp.int32):
        flat = jnp.full((padded,), fill, dtype=dtype)
        flat = flat.at[:n].set(v.astype(dtype))
        return flat.reshape(rows, LANES)

    kernel = functools.partial(
        _singleaz_kernel, n_zones=n_zones, az_aware=az_aware, n_apps=a,
        minfrag=minfrag, strict=strict,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=10,
        grid=(a,),
        in_specs=[pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0))] * 10,
        out_specs=[
            pl.BlockSpec((8, LANES), lambda i, *refs: (i // 8, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((rows, LANES), jnp.int32)] * 3,
    )
    out_shape = [
        jax.ShapeDtypeStruct((a, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
    ]
    feas, c_out, m_out, g_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        drivers[:, 0], drivers[:, 1], drivers[:, 2],
        executors[:, 0], executors[:, 1], executors[:, 2],
        counts, app_valid.astype(jnp.int32),
        scale_cpu.astype(jnp.int32), scale_gpu.astype(jnp.int32),
        plane(avail[:, 0]), plane(avail[:, 1]), plane(avail[:, 2]),
        plane(driver_rank, fill=int(BIG)),
        plane(exec_ok.astype(jnp.int32)),
        plane(zone_id, fill=-1),
        plane(s_cpu_milli), plane(s_gpu_milli),
        plane(th_mem),
        plane(inv_mem, fill=0, dtype=jnp.float32),
    )
    feasible = feas[:, 0] != 0
    driver_idx = jnp.where(feasible, feas[:, 1], jnp.int32(n))
    zone_idx = feas[:, 2]
    uncertain = feas[:, 3] != 0
    avail_after = jnp.stack(
        [c_out.reshape(-1)[:n], m_out.reshape(-1)[:n], g_out.reshape(-1)[:n]], axis=1
    )
    return feasible, zone_idx, driver_idx, uncertain, avail_after


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_solve_queue_min_frag(
    avail: jnp.ndarray,        # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    drivers: jnp.ndarray,      # [A, 3] int32
    executors: jnp.ndarray,    # [A, 3] int32
    counts: jnp.ndarray,       # [A] int32
    app_valid: jnp.ndarray,    # [A] bool
    interpret: bool = False,
):
    """Whole minimal-fragmentation FIFO queue in ONE pallas kernel.
    Returns (feasible[A] bool, driver_idx[A] int32, avail_after[N,3])
    with decisions identical to batch_solver.solve_queue_min_frag
    (tests/test_pallas_queue.py::test_pallas_min_frag_matches_xla).
    Caller guards batch_solver.mf_sentinel_safe, like the XLA lane."""
    n = avail.shape[0]
    a = drivers.shape[0]
    rows, padded = _row_layout(n)

    def plane(v, fill=0):
        flat = jnp.full((padded,), fill, dtype=jnp.int32)
        flat = flat.at[:n].set(v.astype(jnp.int32))
        return flat.reshape(rows, LANES)

    kernel = functools.partial(_minfrag_queue_kernel, n_apps=a)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(a,),
        in_specs=[pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0))] * 5,
        out_specs=[
            pl.BlockSpec((8, LANES), lambda i, *refs: (i // 8, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((rows, LANES), jnp.int32)] * 3,
    )
    out_shape = [
        jax.ShapeDtypeStruct((a, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
    ]
    feas, c_out, m_out, g_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        drivers[:, 0], drivers[:, 1], drivers[:, 2],
        executors[:, 0], executors[:, 1], executors[:, 2],
        counts, app_valid.astype(jnp.int32),
        plane(avail[:, 0]), plane(avail[:, 1]), plane(avail[:, 2]),
        plane(driver_rank, fill=int(BIG)),
        plane(exec_ok.astype(jnp.int32)),
    )
    feasible = feas[:, 0] != 0
    driver_idx = jnp.where(feasible, feas[:, 1], jnp.int32(n))
    avail_after = jnp.stack(
        [c_out.reshape(-1)[:n], m_out.reshape(-1)[:n], g_out.reshape(-1)[:n]], axis=1
    )
    return feasible, driver_idx, avail_after


@functools.partial(
    jax.jit, static_argnames=("evenly", "interpret", "apps_per_step")
)
def pallas_solve_queue(
    avail: jnp.ndarray,        # [N, 3] int32 (N multiple of LANES*8 preferred)
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    drivers: jnp.ndarray,      # [A, 3] int32
    executors: jnp.ndarray,    # [A, 3] int32
    counts: jnp.ndarray,       # [A] int32
    app_valid: jnp.ndarray,    # [A] bool
    evenly: bool = False,
    interpret: bool = False,
    apps_per_step: int = 1,
):
    """Returns (feasible[A] bool, driver_idx[A] int32, avail_after[N,3]).

    apps_per_step batches several apps per grid step (unrolled in the
    kernel body) to amortize per-step overhead; must divide the app
    count and 8 (the output tile height).
    """
    n = avail.shape[0]
    a = drivers.shape[0]
    if apps_per_step <= 0 or a % apps_per_step or 8 % apps_per_step:
        raise ValueError(
            f"apps_per_step={apps_per_step} must be positive and divide {a} and 8"
        )
    rows, padded = _row_layout(n)

    def plane(v, fill=0):
        flat = jnp.full((padded,), fill, dtype=jnp.int32)
        flat = flat.at[:n].set(v.astype(jnp.int32))
        return flat.reshape(rows, LANES)

    cpu_p = plane(avail[:, 0])
    mem_p = plane(avail[:, 1])
    gpu_p = plane(avail[:, 2])
    rank_p = plane(driver_rank, fill=int(BIG))
    exec_p = plane(exec_ok.astype(jnp.int32))

    kernel = functools.partial(
        _queue_kernel, evenly=evenly, n_apps=a, apps_per_step=apps_per_step
    )
    g = apps_per_step
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(a // g,),
        in_specs=[pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0))] * 5,
        out_specs=[
            pl.BlockSpec((8, LANES), lambda i, *refs: ((i * g) // 8, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((rows, LANES), jnp.int32)] * 3,
    )
    out_shape = [
        jax.ShapeDtypeStruct((a, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
    ]
    feas, c_out, m_out, g_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        drivers[:, 0], drivers[:, 1], drivers[:, 2],
        executors[:, 0], executors[:, 1], executors[:, 2],
        counts, app_valid.astype(jnp.int32),
        cpu_p, mem_p, gpu_p, rank_p, exec_p,
    )
    feasible = feas[:, 0] != 0
    driver_idx = jnp.where(feasible, feas[:, 1], jnp.int32(n))
    avail_after = jnp.stack(
        [c_out.reshape(-1)[:n], m_out.reshape(-1)[:n], g_out.reshape(-1)[:n]], axis=1
    )
    return feasible, driver_idx, avail_after
