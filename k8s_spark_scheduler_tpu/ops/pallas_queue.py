"""Pallas TPU kernel for the whole-FIFO-queue gang solve.

The XLA `lax.scan` path (batch_solver.solve_queue) pays per-iteration
dispatch + HBM round-trips for the availability carry; at 1k apps that
overhead dominates (~90µs/step).  This kernel instead runs the queue as
a single `pallas_call` with grid=(A,):

- the cluster availability lives in VMEM scratch, initialized from HBM
  on the first grid step and updated in place after each app — TPU grid
  steps execute sequentially on a core, so the scratch IS the scan
  carry, with zero HBM traffic per step;
- per-app demands are int32 scalars in SMEM via scalar prefetch;
- node arrays are laid out [R, 128] (row-major flattening of the
  priority order) so capacity math runs full-width on the VPU, with
  the flattened-order prefix sums done as lane-cumsum + row-offset.

Decision semantics are identical to batch_solver.solve_app (same
parity guarantees); this kernel returns per-app decisions (feasible,
driver node index) plus the final availability.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
BIG = 2**31 - 1  # plain int: a module-level jnp scalar would be a captured const in the kernel


def _row_layout(n: int) -> Tuple[int, int]:
    rows = (n + LANES - 1) // LANES
    # sublane multiple of 8 for int32 tiling
    rows = ((rows + 7) // 8) * 8
    return rows, rows * LANES


def _inclusive_scan(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Hillis–Steele inclusive prefix sum via log-step circular shifts
    (mosaic has no cumsum primitive).  Wrapped lanes are masked off."""
    size = x.shape[axis]
    ids = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    d = 1
    while d < size:
        shifted = pltpu.roll(x, shift=d, axis=axis)
        x = x + jnp.where(ids >= d, shifted, 0)
        d *= 2
    return x


def _flat_cumsum_exclusive(x: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix sum of a [R, 128] int32 array in row-major
    (flattened) order: lane-axis scan within rows plus an exclusive
    row-offset scan across rows."""
    within = _inclusive_scan(x, axis=1)
    row_tot = jnp.broadcast_to(within[:, -1:], x.shape)
    row_incl = _inclusive_scan(row_tot, axis=0)  # lane-constant
    row_off = row_incl - row_tot
    return within + row_off - x


def _queue_kernel(
    # scalar prefetch (SMEM): per-app demand vectors
    dcpu, dmem, dgpu, ecpu, emem, egpu, ks, valids,
    # array inputs (VMEM)
    avail0,        # [R, 128] cpu plane (availability split into 3 planes)
    availm0,       # [R, 128] memory plane
    availg0,       # [R, 128] gpu plane
    rank_ref,      # [R, 128] int32 driver rank (BIG = not a candidate)
    execok_ref,    # [R, 128] int32 0/1
    # outputs
    feas_ref,      # per-app rows (lane 0 = feasible, lane 1 = driver idx)
    avail_out,     # [R, 128] ×3 final availability planes
    availm_out,
    availg_out,
    # scratch: availability carry
    ac, am, ag,
    *,
    evenly: bool,
    n_apps: int,
    apps_per_step: int,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ac[...] = avail0[...]
        am[...] = availm0[...]
        ag[...] = availg0[...]

    rank = rank_ref[...]
    exec_ok = execok_ref[...] != 0
    rows, lanes = rank.shape
    row_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    lane_ids = lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    node_ids = row_ids * lanes + lane_ids
    out_lanes = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    # the grid sequences blocks of `apps_per_step` apps; the inner loop is
    # unrolled at trace time, amortizing per-grid-step overhead (grid
    # pipelining + output DMA) over several apps
    for j in range(apps_per_step):
        a = i * apps_per_step + j
        dr = jnp.array([dcpu[a], dmem[a], dgpu[a]], dtype=jnp.int32)
        ex = jnp.array([ecpu[a], emem[a], egpu[a]], dtype=jnp.int32)
        k = ks[a]
        valid = valids[a]

        cpu, mem, gpu = ac[...], am[...], ag[...]

        def caps(c, m, g, ex=ex, k=k):
            def dim(avail_d, req):
                return jnp.where(req == 0, BIG, lax.div(avail_d, jnp.maximum(req, 1)))

            cap = jnp.minimum(jnp.minimum(dim(c, ex[0]), dim(m, ex[1])), dim(g, ex[2]))
            return jnp.clip(cap, 0, k)

        base_cap = jnp.where(exec_ok, caps(cpu, mem, gpu), 0)
        cap_with_driver = jnp.where(
            exec_ok, caps(cpu - dr[0], mem - dr[1], gpu - dr[2]), 0
        )

        driver_fits = (cpu >= dr[0]) & (mem >= dr[1]) & (gpu >= dr[2]) & (rank < BIG)
        total = jnp.sum(base_cap)
        total_d = total - base_cap + cap_with_driver
        feasible_d = driver_fits & (total_d >= k)

        masked_rank = jnp.where(feasible_d, rank, BIG)
        best_rank = jnp.min(masked_rank)
        feasible = (best_rank < BIG) & (valid != 0)

        # ranks are unique, so the min-rank node is unique when feasible
        # (mosaic has no int argmin: recover the index via a masked min)
        flat_idx = jnp.min(jnp.where(masked_rank == best_rank, node_ids, BIG))
        is_driver = (node_ids == flat_idx) & feasible

        cap = jnp.where(is_driver, cap_with_driver, base_cap)
        cap = jnp.where(feasible, cap, 0)

        if evenly:
            has = (cap > 0).astype(jnp.int32)
            rank_excl = _flat_cumsum_exclusive(has)
            exec_mask = (cap > 0) & (rank_excl < k)
        else:
            cum_excl = _flat_cumsum_exclusive(cap)
            x = jnp.clip(k - cum_excl, 0, cap)
            exec_mask = x > 0
        exec_mask = exec_mask & feasible

        # the reference's usage-subtraction quirk: executor overwrites driver
        dc = jnp.where(exec_mask, ex[0], jnp.where(is_driver, dr[0], 0))
        dm = jnp.where(exec_mask, ex[1], jnp.where(is_driver, dr[1], 0))
        dg = jnp.where(exec_mask, ex[2], jnp.where(is_driver, dr[2], 0))
        ac[...] = cpu - dc
        am[...] = mem - dm
        ag[...] = gpu - dg

        # outputs: 8 app-rows per (8, 128) tile
        idx_val = jnp.where(feasible, flat_idx, jnp.int32(rows * lanes))
        out_row = jnp.where(
            out_lanes == 0,
            feasible.astype(jnp.int32),
            jnp.where(out_lanes == 1, idx_val, 0),
        )
        feas_ref[pl.ds((i * apps_per_step + j) % 8, 1), :] = out_row

    @pl.when(i == (n_apps // apps_per_step) - 1)
    def _final():
        avail_out[...] = ac[...]
        availm_out[...] = am[...]
        availg_out[...] = ag[...]


@functools.partial(
    jax.jit, static_argnames=("evenly", "interpret", "apps_per_step")
)
def pallas_solve_queue(
    avail: jnp.ndarray,        # [N, 3] int32 (N multiple of LANES*8 preferred)
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    drivers: jnp.ndarray,      # [A, 3] int32
    executors: jnp.ndarray,    # [A, 3] int32
    counts: jnp.ndarray,       # [A] int32
    app_valid: jnp.ndarray,    # [A] bool
    evenly: bool = False,
    interpret: bool = False,
    apps_per_step: int = 1,
):
    """Returns (feasible[A] bool, driver_idx[A] int32, avail_after[N,3]).

    apps_per_step batches several apps per grid step (unrolled in the
    kernel body) to amortize per-step overhead; must divide the app
    count and 8 (the output tile height).
    """
    n = avail.shape[0]
    a = drivers.shape[0]
    if apps_per_step <= 0 or a % apps_per_step or 8 % apps_per_step:
        raise ValueError(
            f"apps_per_step={apps_per_step} must be positive and divide {a} and 8"
        )
    rows, padded = _row_layout(n)

    def plane(v, fill=0):
        flat = jnp.full((padded,), fill, dtype=jnp.int32)
        flat = flat.at[:n].set(v.astype(jnp.int32))
        return flat.reshape(rows, LANES)

    cpu_p = plane(avail[:, 0])
    mem_p = plane(avail[:, 1])
    gpu_p = plane(avail[:, 2])
    rank_p = plane(driver_rank, fill=int(BIG))
    exec_p = plane(exec_ok.astype(jnp.int32))

    kernel = functools.partial(
        _queue_kernel, evenly=evenly, n_apps=a, apps_per_step=apps_per_step
    )
    g = apps_per_step
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(a // g,),
        in_specs=[pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0))] * 5,
        out_specs=[
            pl.BlockSpec((8, LANES), lambda i, *refs: ((i * g) // 8, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
            pl.BlockSpec((rows, LANES), lambda i, *refs: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((rows, LANES), jnp.int32)] * 3,
    )
    out_shape = [
        jax.ShapeDtypeStruct((a, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
    ]
    feas, c_out, m_out, g_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        drivers[:, 0], drivers[:, 1], drivers[:, 2],
        executors[:, 0], executors[:, 1], executors[:, 2],
        counts, app_valid.astype(jnp.int32),
        cpu_p, mem_p, gpu_p, rank_p, exec_p,
    )
    feasible = feas[:, 0] != 0
    driver_idx = jnp.where(feasible, feas[:, 1], jnp.int32(n))
    avail_after = jnp.stack(
        [c_out.reshape(-1)[:n], m_out.reshape(-1)[:n], g_out.reshape(-1)[:n]], axis=1
    )
    return feasible, driver_idx, avail_after
