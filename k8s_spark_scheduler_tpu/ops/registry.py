"""Binpacker registry (reference ``internal/binpacker/binpack.go``).

Name → algorithm map with the reference's names plus the TPU-native
``tpu-batch`` solver.  Unknown names fall back to the default
``distribute-evenly`` (binpack.go:52-58).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from .. import compat
from . import packers
from .packers import SparkBinPackFunction

TIGHTLY_PACK = "tightly-pack"
DISTRIBUTE_EVENLY = "distribute-evenly"
AZ_AWARE_TIGHTLY_PACK = "az-aware-tightly-pack"
SINGLE_AZ_TIGHTLY_PACK = "single-az-tightly-pack"
SINGLE_AZ_MINIMAL_FRAGMENTATION = "single-az-minimal-fragmentation"
MINIMAL_FRAGMENTATION = "minimal-fragmentation"
TPU_BATCH = "tpu-batch"
TPU_BATCH_SINGLE_AZ = "tpu-batch-single-az"
TPU_BATCH_AZ_AWARE = "tpu-batch-az-aware"
TPU_BATCH_MIN_FRAG = "tpu-batch-minimal-fragmentation"
TPU_BATCH_EVENLY = "tpu-batch-distribute-evenly"
TPU_BATCH_SINGLE_AZ_MIN_FRAG = "tpu-batch-single-az-minimal-fragmentation"

DEFAULT = DISTRIBUTE_EVENLY


@dataclass
class Binpacker:
    name: str
    binpack_func: SparkBinPackFunction
    is_single_az: bool
    # device-side whole-queue FIFO solver (set for tpu-batch); None means
    # the extender uses the host earlier-drivers loop
    queue_solver: object = None


_REGISTRY = {}


def register(name: str, fn: SparkBinPackFunction, is_single_az: bool) -> None:
    _REGISTRY[name] = Binpacker(name, fn, is_single_az)


register(TIGHTLY_PACK, packers.tightly_pack, False)
register(DISTRIBUTE_EVENLY, packers.distribute_evenly, False)
register(AZ_AWARE_TIGHTLY_PACK, packers.az_aware_tightly_pack, True)
register(SINGLE_AZ_TIGHTLY_PACK, packers.single_az_tightly_pack, True)
register(SINGLE_AZ_MINIMAL_FRAGMENTATION, packers.single_az_minimal_fragmentation, True)
register(MINIMAL_FRAGMENTATION, packers.minimal_fragmentation_pack, False)


def _minfrag_binpacker(name: str, strict: bool) -> Binpacker:
    """The two host min-frag policies, built for either compat mode —
    the only policies with a switchable quirk (efficiency write-back)."""
    if name == SINGLE_AZ_MINIMAL_FRAGMENTATION:
        return Binpacker(
            name, packers.make_single_az_minimal_fragmentation(strict), True
        )
    return Binpacker(name, packers.make_minimal_fragmentation_pack(strict), False)


def select_binpacker(
    name: str, strict_reference_parity: bool = compat.DEFAULT_STRICT
) -> Binpacker:
    """binpack.go:52-58; unknown → distribute-evenly.

    strict_reference_parity threads the compat policy (compat.py) into
    the minimal-fragmentation variants."""
    if not strict_reference_parity and name in (
        MINIMAL_FRAGMENTATION,
        SINGLE_AZ_MINIMAL_FRAGMENTATION,
    ):
        return _minfrag_binpacker(name, strict_reference_parity)
    if name in (
        TPU_BATCH,
        TPU_BATCH_SINGLE_AZ,
        TPU_BATCH_AZ_AWARE,
        TPU_BATCH_MIN_FRAG,
        TPU_BATCH_EVENLY,
        TPU_BATCH_SINGLE_AZ_MIN_FRAG,
    ):
        try:
            # imported lazily: pulls in jax
            from .batch_adapter import (
                tpu_batch_az_aware_binpacker,
                tpu_batch_binpacker,
                tpu_batch_evenly_binpacker,
                tpu_batch_min_frag_binpacker,
                tpu_batch_single_az_binpacker,
                tpu_batch_single_az_min_frag_binpacker,
            )

            if name == TPU_BATCH_MIN_FRAG:
                return tpu_batch_min_frag_binpacker(strict_reference_parity)
            if name == TPU_BATCH_SINGLE_AZ:
                return tpu_batch_single_az_binpacker()
            if name == TPU_BATCH_AZ_AWARE:
                return tpu_batch_az_aware_binpacker()
            if name == TPU_BATCH_EVENLY:
                return tpu_batch_evenly_binpacker()
            if name == TPU_BATCH_SINGLE_AZ_MIN_FRAG:
                return tpu_batch_single_az_min_frag_binpacker(strict_reference_parity)
            return tpu_batch_binpacker()
        except ImportError:
            # fall back to the host policy with the SAME placement and
            # single-AZ semantics, not the default
            fallback = {
                TPU_BATCH: TIGHTLY_PACK,
                TPU_BATCH_SINGLE_AZ: SINGLE_AZ_TIGHTLY_PACK,
                TPU_BATCH_AZ_AWARE: AZ_AWARE_TIGHTLY_PACK,
                TPU_BATCH_MIN_FRAG: MINIMAL_FRAGMENTATION,
                TPU_BATCH_EVENLY: DISTRIBUTE_EVENLY,
                TPU_BATCH_SINGLE_AZ_MIN_FRAG: SINGLE_AZ_MINIMAL_FRAGMENTATION,
            }[name]
            logging.getLogger(__name__).error(
                "binpack %r configured but the JAX batch solver could not be "
                "imported; falling back to %s",
                name,
                fallback,
                exc_info=True,
            )
            if not strict_reference_parity and fallback in (
                MINIMAL_FRAGMENTATION,
                SINGLE_AZ_MINIMAL_FRAGMENTATION,
            ):
                return _minfrag_binpacker(fallback, strict_reference_parity)
            return _REGISTRY[fallback]
    return _REGISTRY.get(name, _REGISTRY[DEFAULT])


# -- kernel chaos hook --------------------------------------------------------
#
# The simulator's kernel_fault injection point: when armed, every device
# lane entry (tensor driver path, device FIFO solve, tensor reschedule)
# raises through the extender's REAL exception-fallback path, so lane
# demotion/re-probe (resilience/lanehealth.py) is exercised against the
# same control flow production faults take.  None (the default) costs one
# module-attribute read per dispatch.

_kernel_fault_hook = None


def set_kernel_fault_hook(fn) -> None:
    """fn(lane_name) -> Optional[Exception]; None disarms."""
    global _kernel_fault_hook
    _kernel_fault_hook = fn


def check_kernel_fault(lane: str) -> None:
    fn = _kernel_fault_hook
    if fn is not None:
        err = fn(lane)
        if err is not None:
            raise err


def available_binpackers() -> list[str]:
    return sorted(
        _REGISTRY.keys()
        | {
            TPU_BATCH,
            TPU_BATCH_SINGLE_AZ,
            TPU_BATCH_AZ_AWARE,
            TPU_BATCH_MIN_FRAG,
            TPU_BATCH_EVENLY,
            TPU_BATCH_SINGLE_AZ_MIN_FRAG,
        }
    )
