"""Binpacker registry (reference ``internal/binpacker/binpack.go``).

Name → algorithm map with the reference's names plus the TPU-native
``tpu-batch`` solver.  Unknown names fall back to the default
``distribute-evenly`` (binpack.go:52-58).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from . import packers
from .packers import SparkBinPackFunction

TIGHTLY_PACK = "tightly-pack"
DISTRIBUTE_EVENLY = "distribute-evenly"
AZ_AWARE_TIGHTLY_PACK = "az-aware-tightly-pack"
SINGLE_AZ_TIGHTLY_PACK = "single-az-tightly-pack"
SINGLE_AZ_MINIMAL_FRAGMENTATION = "single-az-minimal-fragmentation"
MINIMAL_FRAGMENTATION = "minimal-fragmentation"
TPU_BATCH = "tpu-batch"

DEFAULT = DISTRIBUTE_EVENLY


@dataclass
class Binpacker:
    name: str
    binpack_func: SparkBinPackFunction
    is_single_az: bool
    # device-side whole-queue FIFO solver (set for tpu-batch); None means
    # the extender uses the host earlier-drivers loop
    queue_solver: object = None


_REGISTRY = {}


def register(name: str, fn: SparkBinPackFunction, is_single_az: bool) -> None:
    _REGISTRY[name] = Binpacker(name, fn, is_single_az)


register(TIGHTLY_PACK, packers.tightly_pack, False)
register(DISTRIBUTE_EVENLY, packers.distribute_evenly, False)
register(AZ_AWARE_TIGHTLY_PACK, packers.az_aware_tightly_pack, True)
register(SINGLE_AZ_TIGHTLY_PACK, packers.single_az_tightly_pack, True)
register(SINGLE_AZ_MINIMAL_FRAGMENTATION, packers.single_az_minimal_fragmentation, True)
register(MINIMAL_FRAGMENTATION, packers.minimal_fragmentation_pack, False)


def select_binpacker(name: str) -> Binpacker:
    """binpack.go:52-58; unknown → distribute-evenly."""
    if name == TPU_BATCH:
        try:
            # imported lazily: pulls in jax
            from .batch_adapter import tpu_batch_binpacker

            return tpu_batch_binpacker()
        except ImportError:
            logging.getLogger(__name__).error(
                "binpack 'tpu-batch' configured but the JAX batch solver could "
                "not be imported; falling back to %s",
                DEFAULT,
                exc_info=True,
            )
            return _REGISTRY[DEFAULT]
    return _REGISTRY.get(name, _REGISTRY[DEFAULT])


def available_binpackers() -> list[str]:
    return sorted(_REGISTRY.keys() | {TPU_BATCH})
