"""Tiny adapter type so ops/ doesn't depend on scheduler/."""

from __future__ import annotations

from dataclasses import dataclass

from ..types.resources import Resources


@dataclass
class AppDemand:
    driver_resources: Resources
    executor_resources: Resources
    min_executor_count: int


def app_resources_of(
    driver_resources: Resources, executor_resources: Resources, count: int
) -> AppDemand:
    return AppDemand(driver_resources, executor_resources, count)
