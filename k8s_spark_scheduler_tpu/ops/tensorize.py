"""Snapshot → tensor marshalling for the TPU batch solver.

Cluster state (node availability in priority order) and pending-app
demand become dense integer arrays.  Exactness contract: every quantity
is converted to integer base units (milli-CPU, memory bytes, milli-GPU)
and then divided by the per-dimension GCD across the whole problem so
values fit int32 (fast path on TPU).  Any value that is not exactly
representable flags the snapshot inexact and the caller falls back to
the host oracle — the solver never trades exactness for speed.

Padding: node and app axes are padded to bucket sizes so XLA compiles a
small number of program shapes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types.resources import NodeGroupSchedulingMetadata, Resources
from ..utils.quantity import Quantity

DIMS = 3  # cpu, memory, gpu

# k (executor count) must satisfy N_bucket * k < 2^31 so int32 capacity
# sums cannot overflow (see batch_solver).
INT32_SAFE = 2**31 - 1


_INT64_MAX = 2**63 - 1


def _to_base_units(q: Quantity, dim: int) -> Tuple[int, bool]:
    """cpu/gpu → milli units; memory → bytes.  Returns (value, exact);
    values beyond int64 are clamped and flagged inexact."""
    if dim == 1:
        v = q.exact
        value, exact = math.ceil(v), v.denominator == 1
    else:
        value, exact = q.milli_value_exact()
    if value > _INT64_MAX:
        return _INT64_MAX, False
    if value < -_INT64_MAX:
        return -_INT64_MAX, False
    return value, exact


def _resources_to_base(r: Resources) -> Tuple[List[int], bool]:
    out = []
    exact = True
    for dim, q in enumerate((r.cpu, r.memory, r.nvidia_gpu)):
        v, e = _to_base_units(q, dim)
        out.append(v)
        exact = exact and e
    return out, exact


def _app_base_rows(app) -> Tuple[List[int], List[int], bool]:
    """(driver_row, executor_row, exact) for one AppDemand, stashed on
    the instance: the FIFO pass re-tensorizes the same ~queue-depth apps
    on every Filter request, and the extender serves STABLE AppDemand
    instances per pod version (sparkpods.spark_app_demand_cached), so
    the exact base-unit conversion runs once per app, not per request.
    (Hash-keyed memoization was tried first — hashing three Fractions
    costs as much as the conversion.)"""
    rows = getattr(app, "_base_rows", None)
    if rows is None:
        drow, e1 = _resources_to_base(app.driver_resources)
        erow, e2 = _resources_to_base(app.executor_resources)
        rows = (drow, erow, e1 and e2)
        try:
            app._base_rows = rows
        except AttributeError:  # frozen/slots instances: just recompute
            pass
    return rows


NODE_BUCKETS = (64, 256, 1024, 4096)
APP_BUCKETS = (16, 64, 256, 1024, 4096)


def bucket_size(n: int, buckets: Sequence[int] = NODE_BUCKETS) -> int:
    """Pad to a bounded set of shapes: fixed small buckets, then
    multiples of 1024 (TPU-lane friendly without 60% padding waste at
    the 10k-node scale)."""
    for b in buckets:
        if n <= b:
            return b
    return ((n + 1023) // 1024) * 1024


@dataclass
class ClusterTensor:
    """Node-side arrays.  Row order = executor priority order, followed by
    driver-only candidate nodes; driver ordering is carried as a per-node
    rank so the two priority lists may disagree (label-priority re-sorts
    can reorder them independently, nodesorting.go:59-62)."""

    node_names: List[str]
    avail: np.ndarray        # [N, 3] int64 base units (pre-scaling)
    sched: np.ndarray        # [N, 3] int64 (schedulable totals, for efficiency)
    driver_rank: np.ndarray  # [N] int32 — position in driver priority list, INT32_SAFE if not a candidate
    exec_ok: np.ndarray      # [N] bool — in executor priority list
    zone_id: np.ndarray      # [N] int32
    zone_names: List[str]
    valid: np.ndarray        # [N] bool — padding mask
    exact: bool

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)


@dataclass
class AppTensor:
    """App-side arrays in FIFO order."""

    driver: np.ndarray  # [A, 3] int64 base units
    executor: np.ndarray  # [A, 3] int64
    count: np.ndarray   # [A] int32 (min executor count = gang size)
    valid: np.ndarray   # [A] bool
    exact: bool

    @property
    def n_apps(self) -> int:
        return int(self.valid.sum())


@dataclass
class ScaledProblem:
    """The int32-scaled problem handed to the device kernel."""

    avail: np.ndarray        # [Nb, 3] int32
    driver_rank: np.ndarray  # [Nb] int32
    exec_ok: np.ndarray      # [Nb] bool
    driver: np.ndarray     # [Ab, 3] int32
    executor: np.ndarray   # [Ab, 3] int32
    count: np.ndarray      # [Ab] int32
    app_valid: np.ndarray  # [Ab] bool
    scale: np.ndarray      # [3] int64 per-dimension divisor
    ok: bool               # False → caller must use the host oracle


def tensorize_cluster(
    metadata: NodeGroupSchedulingMetadata,
    driver_order: Sequence[str],
    executor_order: Sequence[str],
) -> ClusterTensor:
    """Marshal a snapshot from the two priority-ordered candidate lists
    (nodes missing from metadata are dropped, as in SparkBinPack's
    metadata lookups)."""
    exec_names = [n for n in executor_order if n in metadata]
    exec_set = set(exec_names)
    driver_names = [n for n in driver_order if n in metadata]
    names = exec_names + [n for n in driver_names if n not in exec_set]
    n = len(names)
    driver_rank_map = {name: i for i, name in enumerate(driver_names)}

    avail = np.zeros((n, DIMS), dtype=np.int64)
    sched = np.zeros((n, DIMS), dtype=np.int64)
    exact = True
    zone_names: List[str] = []
    zone_index: Dict[str, int] = {}
    zone_id = np.zeros(n, dtype=np.int32)
    for i, name in enumerate(names):
        md = metadata[name]
        row, e1 = _resources_to_base(md.available)
        # schedulable totals feed efficiency metrics only, never
        # decisions — clamping them must not force an oracle fallback
        srow, _ = _resources_to_base(md.schedulable)
        exact = exact and e1
        avail[i] = row
        sched[i] = srow
        z = md.zone_label
        if z not in zone_index:
            zone_index[z] = len(zone_names)
            zone_names.append(z)
        zone_id[i] = zone_index[z]
    return ClusterTensor(
        node_names=names,
        avail=avail,
        sched=sched,
        driver_rank=np.array(
            [driver_rank_map.get(name, INT32_SAFE) for name in names], dtype=np.int32
        ),
        exec_ok=np.array([name in exec_set for name in names], dtype=bool),
        zone_id=zone_id,
        zone_names=zone_names,
        valid=np.ones(n, dtype=bool),
        exact=exact,
    )


def tensorize_apps(apps: Sequence) -> AppTensor:
    """apps: sequence of SparkApplicationResources (FIFO order)."""
    a = len(apps)
    driver = np.zeros((a, DIMS), dtype=np.int64)
    executor = np.zeros((a, DIMS), dtype=np.int64)
    count = np.zeros(a, dtype=np.int64)
    exact = True
    for i, app in enumerate(apps):
        drow, erow, e = _app_base_rows(app)
        exact = exact and e
        driver[i] = drow
        executor[i] = erow
        count[i] = app.min_executor_count
    return AppTensor(
        driver=driver,
        executor=executor,
        count=count.astype(np.int64),
        valid=np.ones(a, dtype=bool),
        exact=exact,
    )


def scale_problem(
    cluster: ClusterTensor,
    apps: AppTensor,
    node_bucket: Optional[int] = None,
    app_bucket: Optional[int] = None,
) -> ScaledProblem:
    """GCD-scale each dimension to int32 and pad to bucket shapes."""
    n, a = cluster.avail.shape[0], apps.driver.shape[0]
    nb = node_bucket or bucket_size(n)
    ab = app_bucket or bucket_size(a, buckets=APP_BUCKETS)

    ok = cluster.exact and apps.exact
    scale = np.ones(DIMS, dtype=np.int64)
    avail_s = np.zeros((nb, DIMS), dtype=np.int32)
    driver_s = np.zeros((ab, DIMS), dtype=np.int32)
    executor_s = np.zeros((ab, DIMS), dtype=np.int32)

    if ok:
        # per-dimension GCD + divide + int32 bound check: runs in the
        # native snapshot library when available (numpy otherwise)
        from ..native import scale_rows_int32

        demand_rows = np.concatenate([apps.driver, apps.executor], axis=0)
        scaled_ok, scaled_avail, scaled_demands, scale = scale_rows_int32(
            cluster.avail, demand_rows, nb
        )
        if scaled_ok:
            avail_s = scaled_avail
            driver_s[:a] = scaled_demands[:a]
            executor_s[:a] = scaled_demands[a : 2 * a]
        else:
            ok = False

    # int32 sum-overflow guard: capacities are clamped to k in-kernel, so
    # sums are bounded by Nb * max(k); require it fits int32
    max_k = int(apps.count.max()) if a else 0
    if max_k > 0 and nb * max_k > INT32_SAFE:
        ok = False
    if max_k > INT32_SAFE:
        ok = False

    driver_rank = np.full(nb, INT32_SAFE, dtype=np.int32)
    exec_ok = np.zeros(nb, dtype=bool)
    app_valid = np.zeros(ab, dtype=bool)
    count = np.zeros(ab, dtype=np.int32)
    driver_rank[:n] = cluster.driver_rank
    exec_ok[:n] = cluster.exec_ok
    app_valid[:a] = apps.valid
    count[:a] = np.minimum(apps.count, INT32_SAFE).astype(np.int32)

    return ScaledProblem(
        avail=avail_s,
        driver_rank=driver_rank,
        exec_ok=exec_ok,
        driver=driver_s,
        executor=executor_s,
        count=count,
        app_valid=app_valid,
        scale=scale,
        ok=ok,
    )
