"""Device-mesh utilities: sharding the solver's node axis over ICI.

The scaling axis of this framework is cluster size × queue depth
(SURVEY §5 long-context note): a 10k-node × 1k-app snapshot is held in
HBM with the node axis sharded across the mesh.  All cross-device
communication is XLA collectives inserted by GSPMD from sharding
annotations — reductions (total capacity), cumulative sums (greedy
fill), and argmin (driver selection) ride the ICI ring; the scan over
apps is sequential per-step but every step's node work is fully
parallel.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the node axis.  On a v5e-8 slice this is the 8-chip
    ICI ring; on CPU tests it's the virtual-device array."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS))


def node_matrix_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, devices: int) -> int:
    """Node-axis length must divide evenly across the mesh."""
    if n % devices == 0:
        return n
    return n + devices - (n % devices)
