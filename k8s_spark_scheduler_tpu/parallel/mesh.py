"""Device-mesh utilities: sharding the solver's node axis over ICI.

The scaling axis of this framework is cluster size × queue depth
(SURVEY §5 long-context note): a 10k-node × 1k-app snapshot is held in
HBM with the node axis sharded across the mesh.  All cross-device
communication is XLA collectives inserted by GSPMD from sharding
annotations — reductions (total capacity), cumulative sums (greedy
fill), and argmin (driver selection) ride the ICI ring; the scan over
apps is sequential per-step but every step's node work is fully
parallel.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the node axis.  On a v5e-8 slice this is the 8-chip
    ICI ring; on CPU tests it's the virtual-device array."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS))


def node_matrix_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, devices: int) -> int:
    """Node-axis length must divide evenly across the mesh."""
    if n % devices == 0:
        return n
    return n + devices - (n % devices)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host JAX runtime (jax.distributed): each scheduler
    replica contributes its local chips and the mesh spans all hosts.

    The intra-host slice of the node axis rides ICI; the cross-host hops
    ride DCN — GSPMD emits hierarchical collectives from the same
    sharding annotations, so the solver code is unchanged.  With no
    arguments, configuration comes from the standard JAX env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) or the
    TPU pod metadata.
    """
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def make_multihost_mesh(devices_per_host_axis: bool = False) -> Mesh:
    """Global mesh over every process's devices (call after
    initialize_multihost).  A 1-D layout keeps neighboring node-axis
    shards on intra-host ICI where possible; set devices_per_host_axis
    for an explicit ('hosts', 'nodes') 2-D mesh when the control plane
    wants to address per-host shards (e.g. host-local snapshots reduced
    over DCN)."""
    import jax

    devices = jax.devices()
    if not devices_per_host_axis:
        return Mesh(np.array(devices), (NODE_AXIS,))
    local = jax.local_device_count()
    hosts = len(devices) // local
    return Mesh(np.array(devices).reshape(hosts, local), ("hosts", NODE_AXIS))
