"""Scheduling-policy engine: priority classes, pluggable queue
ordering, gang-aware preemption, and DRF fair share (ROADMAP item 4).

The subsystem turns the FIFO gate into a pluggable ordering+preemption
engine while keeping the default byte-identical to plain FIFO:

- :mod:`.classes` — priority-band parsing from pod labels into ranked
  bands (Borg's priority bands, Verma et al. EuroSys'15);
- :mod:`.ordering` — pluggable queue comparators (fifo,
  priority-then-fifo, DRF deficit) plus the conservative backfill
  probe (EASY-style: a lower-band app may fill current holes only if
  it provably cannot delay the blocked queue head);
- :mod:`.drf` — per-tenant dominant-share accounting off the state
  layer's change observers (Ghodsi et al. NSDI'11);
- :mod:`.victims` — whole-application victim selection with what-if
  validation (never partial gangs);
- :mod:`.preempt` — journaled eviction commit with exactly-once
  failover replay (rides the PR 3 intent-journal format);
- :mod:`.engine` — the facade the extender and wiring consume.

With ``Install.policy.enabled = false`` (the default) no engine is
constructed and every extender hook is a single ``is None`` check —
decisions are byte-identical to pre-policy behavior (pinned by the
5-seed property test in tests/test_policy.py).
"""

from .engine import PolicyEngine  # noqa: F401
