"""Priority classes: band parsing from pod labels into ranked bands.

A *band* is a named priority class with an integer rank (higher rank =
more important), configured via ``Install.policy.bands`` and read from
the driver pod's ``Install.policy.band_label`` label.  Unknown or
missing labels fall back to ``default_band`` — an unlabeled cluster
degenerates to one band, which under every ordering reduces to plain
FIFO.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by

# the label a driver pod carries to select its priority band
DEFAULT_BAND_LABEL = "spark-priority-band"
DEFAULT_BANDS = {"low": 0, "normal": 1, "high": 2}
DEFAULT_BAND = "normal"


@guarded_by("_lock", "_seen")
class PriorityLedger:
    """Band lookup + per-band observation counts for ``/policy/state``.

    The parse itself is a dict lookup; the guarded state is only the
    observation ledger (band → distinct app ids seen), kept so the
    operator surface can answer "which bands exist in this cluster"
    without a full pod scan."""

    def __init__(self, bands: Dict[str, int] = None, default_band: str = DEFAULT_BAND,
                 band_label: str = DEFAULT_BAND_LABEL):
        self.bands = dict(bands) if bands else dict(DEFAULT_BANDS)
        if default_band not in self.bands:
            # a config typo must not make every pod unparseable: fall
            # back to the lowest-ranked configured band
            default_band = min(self.bands, key=lambda b: self.bands[b])
        self.default_band = default_band
        self.band_label = band_label
        self._lock = threading.Lock()
        self._seen: Dict[str, set] = {}

    def band_of(self, pod) -> Tuple[str, int]:
        """(band name, rank) for a pod; unknown labels get the default
        band (never an error — policy misconfiguration must not refuse
        admission)."""
        name = pod.labels.get(self.band_label, self.default_band)
        rank = self.bands.get(name)
        if rank is None:
            name = self.default_band
            rank = self.bands[name]
        return name, rank

    def rank_of(self, pod) -> int:
        return self.band_of(pod)[1]

    def observe(self, pod, app_id: str) -> Tuple[str, int]:
        """band_of + ledger update (called on queue ordering, so the
        state endpoint reflects what the ordering actually saw)."""
        name, rank = self.band_of(pod)
        with self._lock:
            racecheck.note_access(self, "_seen")
            self._seen.setdefault(name, set()).add(app_id or pod.name)
        return name, rank

    def state(self) -> Dict[str, dict]:
        with self._lock:
            racecheck.note_access(self, "_seen")
            seen = {band: len(apps) for band, apps in self._seen.items()}
        return {
            band: {"rank": rank, "appsSeen": seen.get(band, 0)}
            for band, rank in sorted(self.bands.items(), key=lambda kv: -kv[1])
        }
