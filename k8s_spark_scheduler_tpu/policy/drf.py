"""Dominant Resource Fairness accounting (Ghodsi et al., NSDI'11).

The accountant mirrors the ResourceReservation cache through its change
observer (an observer registered with
:meth:`..state.typed_caches.ResourceReservationCache.add_change_observer`
first replays existing contents, so the accounting is restart-safe) and
keeps one reserved-resource vector per tenant.  A tenant's *dominant
share* is ``max_j reserved_j / capacity_j`` over the three base
dimensions, divided by the tenant's weight — the quantity DRF equalizes
via progressive filling.

Tenant attribution: the reservation's namespace by default, overridden
by a tenant-label hint the engine registers from the driver pod at
ordering time (``note_app_tenant``) — an RR carries no tenant label of
its own, so hints re-attribute any vector already booked under the
namespace default.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..analysis import racecheck
from ..analysis.guarded import guarded_by


@guarded_by("_lock", "_by_key", "_tenants", "_hints")
class DrfAccountant:
    """Per-tenant dominant-share accounting off the RR change feed.

    ``snapshot_fn`` (optional) returns the current tensor snapshot;
    cluster capacity for the share denominator is read from it at query
    time so shares track node churn without another observer."""

    def __init__(self, tenant_weights: Dict[str, float] = None,
                 snapshot_fn: Callable[[], object] = None):
        self._weights = {t: float(w) for t, w in (tenant_weights or {}).items()
                         if float(w) > 0.0}
        self._snapshot_fn = snapshot_fn
        self._lock = threading.Lock()
        # (ns, name) -> (tenant, reserved vec[3])
        self._by_key: Dict[Tuple[str, str], Tuple[str, np.ndarray]] = {}
        # tenant -> summed reserved vec[3]
        self._tenants: Dict[str, np.ndarray] = {}
        # (ns, app_id) -> tenant hint from the driver pod's tenant label
        self._hints: Dict[Tuple[str, str], str] = {}

    # -- change-feed plumbing -------------------------------------------

    def observe(self, old, new) -> None:
        """Change observer for the ResourceReservation cache
        (``fn(old, new)``; new None = delete): keeps the per-tenant
        vectors in sync with every semantic content change."""
        obj = new if new is not None else old
        if obj is None:
            return
        ns = obj.namespace
        name = obj.name
        key = (ns, name)
        if new is None:
            with self._lock:
                racecheck.note_access(self, "_by_key")
                racecheck.note_access(self, "_tenants")
                self._remove_locked(key)
            return
        vec = self._reserved_vec(new)
        with self._lock:
            racecheck.note_access(self, "_by_key")
            racecheck.note_access(self, "_tenants")
            racecheck.note_access(self, "_hints")
            tenant = self._hints.get(key, ns)
            self._remove_locked(key)
            self._by_key[key] = (tenant, vec)
            self._tenants[tenant] = self._tenants.get(
                tenant, np.zeros(3, dtype=np.int64)) + vec

    def note_app_tenant(self, ns: str, app_id: str, tenant: str) -> None:
        """Register a tenant-label hint for an app; re-attributes any
        vector already booked under the namespace default."""
        if not tenant:
            return
        key = (ns, app_id)
        with self._lock:
            racecheck.note_access(self, "_hints")
            racecheck.note_access(self, "_by_key")
            racecheck.note_access(self, "_tenants")
            self._hints[key] = tenant
            booked = self._by_key.get(key)
            if booked is not None and booked[0] != tenant:
                _, vec = booked
                self._remove_locked(key)
                self._by_key[key] = (tenant, vec)
                self._tenants[tenant] = self._tenants.get(
                    tenant, np.zeros(3, dtype=np.int64)) + vec

    def _remove_locked(self, key: Tuple[str, str]) -> None:
        booked = self._by_key.pop(key, None)
        if booked is None:
            return
        tenant, vec = booked
        left = self._tenants.get(tenant)
        if left is None:
            return
        left = left - vec
        if (left <= 0).all():
            self._tenants.pop(tenant, None)  # schedlint: disable=LK001 -- _remove_locked is only called with _lock held (see callers)
        else:
            self._tenants[tenant] = np.maximum(left, 0)  # schedlint: disable=LK001 -- _remove_locked is only called with _lock held (see callers)

    @staticmethod
    def _reserved_vec(rr) -> np.ndarray:
        from ..ops.tensorize import _resources_to_base

        total = np.zeros(3, dtype=np.int64)
        for res in rr.spec.reservations.values():
            row, _exact = _resources_to_base(res.resources_value())
            total += np.asarray(row, dtype=np.int64)
        return total

    # -- queries --------------------------------------------------------

    def _capacity(self) -> Optional[np.ndarray]:
        if self._snapshot_fn is None:
            return None
        snap = self._snapshot_fn()
        if snap is None or not len(snap.names):
            return None
        eligible = snap.ready & ~snap.unschedulable
        cap = np.asarray(snap.allocatable, dtype=np.int64)[eligible].sum(axis=0)
        return cap if (cap > 0).any() else None

    def dominant_share(self, tenant: str) -> float:
        """Weighted dominant share in [0, inf); 0.0 for a tenant with
        no reservations or when cluster capacity is unknown."""
        cap = self._capacity()
        with self._lock:
            racecheck.note_access(self, "_tenants")
            vec = self._tenants.get(tenant)
            vec = None if vec is None else vec.copy()
        if vec is None or cap is None:
            return 0.0
        shares = vec[cap > 0] / cap[cap > 0]
        if not len(shares):
            return 0.0
        return float(shares.max()) / self._weights.get(tenant, 1.0)

    def tenant_of(self, ns: str, app_id: str) -> str:
        with self._lock:
            racecheck.note_access(self, "_hints")
            racecheck.note_access(self, "_by_key")
            booked = self._by_key.get((ns, app_id))
            if booked is not None:
                return booked[0]
            return self._hints.get((ns, app_id), ns)

    def over_share_tenants(self) -> Dict[str, float]:
        """Tenants whose weighted dominant share exceeds the equal
        split (1/number-of-active-tenants) — the DRF preemption
        eligibility set: a tenant above its share is preemptible by one
        below."""
        with self._lock:
            racecheck.note_access(self, "_tenants")
            tenants = list(self._tenants)
        if not tenants:
            return {}
        fair = 1.0 / len(tenants)
        out = {}
        for t in tenants:
            share = self.dominant_share(t)
            if share > fair:
                out[t] = share
        return out

    def state(self) -> Dict[str, dict]:
        with self._lock:
            racecheck.note_access(self, "_tenants")
            tenants = list(self._tenants)
        fair = 1.0 / len(tenants) if tenants else 0.0
        return {
            t: {
                "dominantShare": round(self.dominant_share(t), 6),
                "weight": self._weights.get(t, 1.0),
                "fairShare": round(fair, 6),
            }
            for t in sorted(tenants)
        }
