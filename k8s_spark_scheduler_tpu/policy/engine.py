"""The policy facade the extender and wiring consume.

Every extender hook is a single method call behind an ``is None``
check, so with ``policy.enabled = false`` (the default) no engine
exists and the Filter path is byte-identical to pre-policy behavior:

- :meth:`PolicyEngine.earlier_queue` — replaces
  ``SparkPodLister.list_earlier_drivers`` with the configured order's
  queue-ahead set.  Under the ``fifo`` ordering it delegates to
  ``list_earlier_drivers`` verbatim (decision identity is structural,
  not just tested);
- :meth:`PolicyEngine.skip_allowed` — the enforce-after-age skip
  verdict, optionally widened by the conservative backfill probe;
- :meth:`PolicyEngine.on_driver_refusal` — fires on a FIT /
  EARLIER_DRIVER refusal: selects + what-if-validates a whole-app
  victim set, commits it through the evict journal, and returns the
  victim-set note the extender stamps into the FailedNodes message
  (the kube-scheduler's retry then admits into the freed capacity).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..capacity.probe import INT32_SAFE
from ..config import PolicyConfig
from ..scheduler import labels as L
from .classes import PriorityLedger
from .drf import DrfAccountant
from .ordering import (
    ORDER_DRF,
    ORDER_FIFO,
    Gang,
    backfill_cannot_delay,
    queue_sort_key,
)
from .preempt import PreemptionCoordinator
from .victims import VictimSelector

logger = logging.getLogger(__name__)

# outcome strings the refusal hook reacts to (mirrors extender.py; not
# imported from there — the extender imports nothing from policy, and
# policy must not import the extender back)
_FAILURE_FIT = "failure-fit"
_FAILURE_EARLIER_DRIVER = "failure-earlier-driver"


@guarded_by("_lock", "_basis_cache")
class PolicyEngine:
    """Priority ordering + backfill + gang-aware preemption + DRF."""

    def __init__(
        self,
        config: PolicyConfig,
        pod_lister,
        tensor_snapshot=None,
        rr_cache=None,
        api=None,
        journal_path: Optional[str] = None,
        metrics=None,
        provenance=None,
        delta_engine=None,
    ):
        self.config = config
        self._pod_lister = pod_lister
        self._tensor_snapshot = tensor_snapshot
        self._metrics = metrics
        self._provenance = provenance
        self._delta_engine = delta_engine
        self.ledger = PriorityLedger(
            config.bands, config.default_band, config.band_label
        )
        self.drf = DrfAccountant(
            config.tenant_weights, snapshot_fn=self._snapshot_or_none
        )
        if rr_cache is not None:
            # observer registration replays existing contents, so the
            # accounting is correct from boot and across failover
            rr_cache.add_change_observer(self.drf.observe)
        self.selector: Optional[VictimSelector] = None
        self.coordinator: Optional[PreemptionCoordinator] = None
        if config.preemption_enabled and rr_cache is not None and api is not None:
            self.selector = VictimSelector(
                list_rrs=rr_cache.list,
                band_fn=self._band_of_rr,
                tenant_fn=self.drf.tenant_of,
                min_band_gap=config.preemption_min_band_gap,
                max_victims=config.max_victims,
            )
            self.coordinator = PreemptionCoordinator(
                api=api,
                rr_cache=rr_cache,
                journal_path=journal_path,
                metrics=metrics,
                provenance=provenance,
                recent_limit=config.recent_evictions,
            )
        self._lock = threading.Lock()
        # content_key → (avail, exec_ok, driver_rank, node_index)
        self._basis_cache: Tuple[object, tuple] = (None, ())

    # -- queue ordering -------------------------------------------------

    def earlier_queue(self, driver) -> List:
        """The queue-ahead set this driver must prove before admitting,
        in the configured order."""
        app_id = driver.labels.get(L.SPARK_APP_ID_LABEL, driver.name)
        band, rank = self.ledger.observe(driver, app_id)
        self._note_tenant(driver, app_id)
        if self.config.ordering == ORDER_FIFO:
            # structural identity with the pre-policy comparator
            return self._pod_lister.list_earlier_drivers(driver)
        pending = self._pod_lister.list_pending_drivers(driver)
        keyed = []
        self_key = None
        for p in pending:
            pid = p.labels.get(L.SPARK_APP_ID_LABEL, p.name)
            _, prank = self.ledger.observe(p, pid)
            share = 0.0
            if self.config.ordering == ORDER_DRF:
                tenant = self._note_tenant(p, pid)
                share = self.drf.dominant_share(tenant)
            key = queue_sort_key(self.config.ordering, prank, share, p)
            if p.namespace == driver.namespace and p.name == driver.name:
                self_key = key
                continue
            keyed.append((key, p))
        if self_key is None:
            # driver not in the pending view (informer lag): order
            # against its own freshly computed key
            share = 0.0
            if self.config.ordering == ORDER_DRF:
                tenant = self._note_tenant(driver, app_id)
                share = self.drf.dominant_share(tenant)
            self_key = queue_sort_key(self.config.ordering, rank, share, driver)
        keyed.sort(key=lambda kv: kv[0])
        return [p for key, p in keyed if key < self_key]

    def skip_allowed(self, queued, driver, base: bool) -> bool:
        """May the blocked queue-ahead app ``queued`` be skipped so that
        ``driver`` can still admit?  ``base`` is the pre-policy verdict
        (enforce-after-age); backfill can only WIDEN it, and never for a
        head past the starvation age (I-P3)."""
        if base:
            return True
        if not self.config.backfill:
            return False
        age = timesource.now() - queued.creation_timestamp
        if age >= self.config.starvation_age_seconds:
            return False
        try:
            basis = self._basis()
            if basis is None:
                return False
            avail, exec_ok, driver_rank, _ = basis
            verdict = backfill_cannot_delay(
                avail, exec_ok, driver_rank,
                head=self._gang_of(queued),
                candidate=self._gang_of(driver),
            )
        except Exception:
            logger.exception("backfill probe failed; refusing backfill")
            return False
        if verdict and self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.POLICY_BACKFILL_SKIPS)
        return verdict

    # -- preemption -----------------------------------------------------

    def on_driver_refusal(self, driver, app_resources, outcome: str) -> Optional[str]:
        """Called at the extender's refusal sites; returns a message
        note describing the committed eviction, or None when no
        preemption happened (the common case)."""
        if self.selector is None or self.coordinator is None:
            return None
        if outcome not in (_FAILURE_FIT, _FAILURE_EARLIER_DRIVER):
            return None
        app_id = driver.labels.get(L.SPARK_APP_ID_LABEL, driver.name)
        band, rank = self.ledger.band_of(driver)
        try:
            basis = self._basis()
            if basis is None:
                return None
            avail, exec_ok, driver_rank, node_index = basis
            gang = self._gang_of_resources(app_resources)
            blockers: Tuple[str, ...] = ()
            if self._provenance is not None:
                info = self._provenance.pending_shortfall()
                if info is not None:
                    blockers = tuple(info.blockers)
            over_share: Dict[str, float] = {}
            if self.config.ordering == ORDER_DRF:
                over_share = self.drf.over_share_tenants()
            plan = self.selector.select(
                preemptor_app=app_id,
                preemptor_band=band,
                preemptor_rank=rank,
                gang=gang,
                avail=avail,
                exec_ok=exec_ok,
                driver_rank=driver_rank,
                node_index=node_index,
                over_share=over_share,
                blockers=blockers,
                session_validate=self._session_validator(gang),
            )
            if plan is None:
                return None
            evicted = self.coordinator.commit(plan)
        except Exception:
            logger.exception("preemption attempt failed; refusal stands as-is")
            return None
        if not evicted:
            return None
        return "preempting victims: " + ", ".join(sorted(evicted))

    def _session_validator(self, gang: Gang):
        """What-if validation against the warm delta-solve session basis
        (the availability the last queue solve actually ran against);
        None when no engine/session — the numpy verdict then stands."""
        if self._delta_engine is None:
            return None
        basis = self._delta_engine.latest_basis()
        if basis is None:
            return None
        names, avail, exec_ok, driver_rank = basis
        index = {n: i for i, n in enumerate(names)}

        def validate(freed_snapshot_order: np.ndarray) -> Optional[bool]:
            snap_basis = self._basis()
            if snap_basis is None:
                return None
            _, _, _, node_index = snap_basis
            # remap the freed matrix from snapshot row order into the
            # session's cluster row order; capacity on nodes the
            # session does not know is dropped (conservative)
            freed = np.zeros_like(avail)
            for name, si in node_index.items():
                di = index.get(name)
                if di is not None:
                    freed[di] = freed_snapshot_order[si]
            from .victims import whatif_fits

            return whatif_fits(avail, exec_ok, driver_rank, freed, gang)

        return validate

    # -- basis + gang helpers -------------------------------------------

    def _snapshot_or_none(self):
        if self._tensor_snapshot is None:
            return None
        try:
            return self._tensor_snapshot.snapshot()
        except Exception:
            return None

    def _basis(self):
        """(avail [N,3] int64, exec_ok [N] bool, driver_rank [N] int64,
        node_index {name: row}) from the current tensor snapshot, cached
        per content_key."""
        snap = self._snapshot_or_none()
        if snap is None or not len(snap.names):
            return None
        with self._lock:
            racecheck.note_access(self, "_basis_cache")
            ckey, cached = self._basis_cache
            if ckey == snap.content_key:
                return cached
        eligible = np.asarray(snap.ready, dtype=bool) & ~np.asarray(
            snap.unschedulable, dtype=bool
        )
        avail = np.asarray(snap.avail, dtype=np.int64)
        driver_rank = np.where(eligible, np.int64(0), np.int64(INT32_SAFE))
        node_index = {n: i for i, n in enumerate(snap.names)}
        basis = (avail, eligible, driver_rank, node_index)
        with self._lock:
            racecheck.note_access(self, "_basis_cache")
            self._basis_cache = (snap.content_key, basis)
        return basis

    @staticmethod
    def _gang_of_resources(app_resources) -> Gang:
        from ..ops.tensorize import _resources_to_base

        drow, _ = _resources_to_base(app_resources.driver_resources)
        erow, _ = _resources_to_base(app_resources.executor_resources)
        return (
            np.asarray(drow, dtype=np.int64),
            np.asarray(erow, dtype=np.int64),
            int(app_resources.min_executor_count),
        )

    def _gang_of(self, pod) -> Gang:
        from ..scheduler.sparkpods import spark_app_demand_cached

        _, demand = spark_app_demand_cached(pod)
        return self._gang_of_resources(demand)

    def _band_of_rr(self, rr) -> Tuple[str, int]:
        """Band of a RUNNING app = its driver pod's band label; an app
        whose driver pod is gone falls back to the default band."""
        driver = self._pod_lister.get_driver_pod(rr.name, rr.namespace)
        if driver is None:
            return self.ledger.default_band, self.ledger.bands[
                self.ledger.default_band
            ]
        return self.ledger.band_of(driver)

    def _note_tenant(self, pod, app_id: str) -> str:
        tenant = pod.labels.get(self.config.tenant_label) or pod.namespace
        self.drf.note_app_tenant(pod.namespace, app_id, tenant)
        return tenant

    # -- lifecycle + operator surface -----------------------------------

    def recover(self) -> int:
        """Replay pending evict intents (wiring boot + failover)."""
        if self.coordinator is None:
            return 0
        return self.coordinator.recover()

    def close(self) -> None:
        if self.coordinator is not None:
            self.coordinator.close()

    def publish_gauges(self) -> None:
        """Per-tenant dominant-share gauges (called by the capacity
        sampler's tick alongside its own gauges)."""
        if self._metrics is None:
            return
        from ..metrics import names as mnames

        for tenant, info in self.drf.state().items():
            self._metrics.gauge(
                mnames.POLICY_DRF_SHARE,
                info["dominantShare"],
                {"tenant": tenant},
            )

    def state(self) -> Dict[str, object]:
        """``GET /policy/state``: bands, tenant shares, recent
        evictions with reasons."""
        out: Dict[str, object] = {
            "enabled": True,
            "ordering": self.config.ordering,
            "backfill": self.config.backfill,
            "preemptionEnabled": self.config.preemption_enabled,
            "bands": self.ledger.state(),
            "tenants": self.drf.state(),
        }
        if self.coordinator is not None:
            out["preemption"] = self.coordinator.state()
            if self.selector is not None:
                out["preemption"]["whatif"] = self.selector.stats()
        return out
