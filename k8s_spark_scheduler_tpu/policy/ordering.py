"""Pluggable queue orders + the conservative backfill probe.

Orders map each pending driver to a sort key; the queue solve itself is
untouched — the policy only changes *which* drivers count as "earlier"
and in what sequence they are proved, so the gang-atomicity guarantee
(every queue-ahead app fits before this one admits) is preserved under
every ordering.

Backfill (EASY-style, conservative): a lower-band app may admit into
current holes past a blocked queue head only when a what-if placement
probe proves it cannot delay the head's earliest start — the candidate
consumes only capacity the head could not have used anyway.  The probe
reuses the solver's own admission rule (``step_app_plain`` semantics
via :mod:`..capacity.probe`), so a "safe" verdict is a statement about
the real solver, not a heuristic twin.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..capacity.probe import INT32_SAFE, _feasible, caps_unclamped

ORDER_FIFO = "fifo"
ORDER_PRIORITY = "priority-then-fifo"
ORDER_DRF = "drf"
ORDERINGS = (ORDER_FIFO, ORDER_PRIORITY, ORDER_DRF)

# (driver_row[3], executor_row[3], count) in base units
Gang = Tuple[np.ndarray, np.ndarray, int]


def queue_sort_key(ordering: str, band_rank: int, dominant_share: float, pod):
    """Sort key for one pending driver.  Ties always break
    (creation_timestamp, name) so every ordering is a total order and
    the fifo ordering is EXACTLY the pre-policy comparator."""
    if ordering == ORDER_PRIORITY:
        return (-band_rank, pod.creation_timestamp, pod.name)
    if ordering == ORDER_DRF:
        # DRF deficit order: the tenant furthest BELOW its dominant
        # share goes first (Ghodsi et al. NSDI'11, progressive filling)
        return (dominant_share, pod.creation_timestamp, pod.name)
    return (pod.creation_timestamp, pod.name)


def gang_feasible(
    avail: np.ndarray, exec_ok: np.ndarray, driver_rank: np.ndarray, gang: Gang
) -> bool:
    """The solver's admission rule for one gang at queue position 0."""
    driver, executor, count = gang
    cand_mask = np.asarray(driver_rank, dtype=np.int64) < INT32_SAFE
    caps = caps_unclamped(avail, exec_ok, executor)
    return _feasible(avail, exec_ok, cand_mask, caps, driver, executor, int(count))


def place_gang(
    avail: np.ndarray, exec_ok: np.ndarray, driver_rank: np.ndarray, gang: Gang
) -> Optional[np.ndarray]:
    """Greedy deterministic placement: driver on the best-ranked fitting
    candidate, executors packed onto highest-capacity nodes.  Returns
    the availability AFTER placement, or None when the gang does not
    fit.  The placement is a lower bound on how much capacity any real
    placement would consume — sufficient for the conservative backfill
    verdict, which only compares before/after headroom."""
    driver, executor, count = gang
    if not gang_feasible(avail, exec_ok, driver_rank, gang):
        return None
    rank = np.asarray(driver_rank, dtype=np.int64)
    fits = (rank < INT32_SAFE) & (avail >= driver).all(axis=1)
    idx = np.flatnonzero(fits)
    if not len(idx):
        return None
    after = avail.copy()
    dnode = idx[np.argmin(rank[idx])]
    after[dnode] -= driver
    remaining = int(count)
    if remaining > 0:
        caps = np.clip(caps_unclamped(after, exec_ok, executor), 0, remaining)
        order = np.argsort(-caps, kind="stable")
        for i in order:
            if remaining <= 0:
                break
            k = int(min(caps[i], remaining))
            if k <= 0:
                break
            after[i] -= executor * k
            remaining -= k
        if remaining > 0:
            # greedy packing failed even though the admission rule
            # passed (cannot happen for step_app_plain semantics, but
            # fail closed rather than report a bogus placement)
            return None
    return after


def backfill_cannot_delay(
    avail: np.ndarray,
    exec_ok: np.ndarray,
    driver_rank: np.ndarray,
    head: Gang,
    candidate: Gang,
) -> bool:
    """True iff admitting ``candidate`` now provably cannot delay the
    blocked queue head's earliest start.

    Conservative rule: after the candidate's greedy placement, the
    head's feasibility verdict AND its clamped capacity total AND its
    driver-fitting candidate count must be unchanged — the candidate
    consumed only capacity the head could not have used.  Any probe
    failure (candidate infeasible, head capacity moved) refuses the
    backfill; refusing is always safe (the queue just stays FIFO).
    """
    after = place_gang(avail, exec_ok, driver_rank, candidate)
    if after is None:
        return False
    h_driver, h_executor, h_count = head
    rank = np.asarray(driver_rank, dtype=np.int64)
    cand_mask = rank < INT32_SAFE

    def head_view(basis: np.ndarray):
        caps = np.clip(
            caps_unclamped(basis, exec_ok, h_executor), 0, max(int(h_count), 1)
        )
        feasible = _feasible(
            basis, exec_ok, cand_mask, caps_unclamped(basis, exec_ok, h_executor),
            h_driver, h_executor, int(h_count),
        )
        driver_fit = int((cand_mask & (basis >= h_driver).all(axis=1)).sum())
        return feasible, int(caps.sum()), driver_fit

    before_view = head_view(avail)
    after_view = head_view(after)
    return before_view == after_view
