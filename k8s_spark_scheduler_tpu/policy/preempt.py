"""Journaled eviction commit: evict-intents persisted BEFORE any
delete, replayed exactly-once across failover.

The coordinator owns a dedicated intent journal (``<journal>.evict`` —
same JSONL format as the write-back journal, separate file so RR
recovery and eviction recovery never ack each other's intents).  Commit
order per victim application:

1. journal the evict intent (pods + reason + preemptor) — durable
   before the first delete;
2. delete every bound pod of the victim (NotFound tolerated: a pod
   already gone is an eviction already half-landed — replay-safe);
3. delete the victim's ResourceReservation through the write-back
   cache (which journals its own delete in the RR journal);
4. ack the evict intent.

A crash between 1 and 4 leaves the intent pending; the standby's
:meth:`PreemptionCoordinator.recover` replays it idempotently — every
step tolerates "already done" — and acks, so each eviction lands
exactly once across a mid-eviction failover (tests/test_failover.py).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..ha import crashpoint
from ..kube.errors import NotFoundError
from ..resilience.journal import IntentJournal
from ..types.objects import Pod
from .victims import VictimPlan

EVICT_KIND = "PolicyEviction"
EVICT_JOURNAL_SUFFIX = ".evict"


@guarded_by("_lock", "_recent", "_evicted_total", "_victims_total")
class PreemptionCoordinator:
    """Commits validated victim plans through the evict journal and
    keeps the bounded recent-evictions ring for ``/policy/state``."""

    def __init__(
        self,
        api,
        rr_cache,
        journal_path: Optional[str] = None,
        metrics=None,
        provenance=None,
        recent_limit: int = 64,
    ):
        self._api = api
        self._rr_cache = rr_cache
        self._metrics = metrics
        self._provenance = provenance
        path = journal_path + EVICT_JOURNAL_SUFFIX if journal_path else None
        self._journal = IntentJournal(path, metrics=None)
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=max(int(recent_limit), 1))
        self._evicted_total = 0
        self._victims_total = 0
        # HA fencing gate (ha/fencing.FencedWriter), installed by server
        # wiring: a deposed leader may not journal, execute, or ack
        # evictions
        self.fence_gate = None

    def install_fence(self, gate) -> None:
        self.fence_gate = gate
        self._journal.fence_gate = gate
        self._journal.epoch_source = gate.fence.epoch

    # -- commit ---------------------------------------------------------

    def commit(self, plan: VictimPlan) -> List[str]:
        """Evict every victim in ``plan``; returns the app ids actually
        evicted.  Intents for ALL victims are journaled before the
        first delete, so a crash at any point leaves a replayable
        record of the full plan — never a half-planned preemption."""
        gate = self.fence_gate
        if gate is not None:
            # refuse the whole plan up front: a deposed leader must not
            # even journal evict intents (the successor plans its own)
            gate.check("preempt.commit")
        reason = (
            f"preempted by {plan.preemptor_app} "
            f"(band {plan.preemptor_band}, {plan.lane} what-if)"
        )
        for v in plan.victims:
            self._journal.record(
                "delete",
                EVICT_KIND,
                v.namespace,
                v.app_id,
                {
                    "pods": list(v.pods),
                    "reason": reason,
                    "preemptor": plan.preemptor_app,
                    "band": v.band,
                    "tenant": v.tenant,
                },
            )
        crashpoint.maybe_crash(crashpoint.PREEMPT_POST_JOURNAL)
        evicted = []
        for v in plan.victims:
            self._execute(v.namespace, v.app_id, v.pods)
            crashpoint.maybe_crash(crashpoint.PREEMPT_PRE_ACK)
            self._journal.ack("delete", v.namespace, v.app_id)
            if gate is not None:
                gate.commit()
            evicted.append(v.app_id)
            self._note_eviction(
                ns=v.namespace,
                app_id=v.app_id,
                band=v.band,
                tenant=v.tenant,
                pods=len(v.pods),
                reason=reason,
                replayed=False,
            )
        self._stamp(plan, evicted)
        return evicted

    def _execute(self, ns: str, app_id: str, pods: List[str]) -> None:
        """Idempotent eviction of one whole application: every step
        tolerates already-done, which is what makes replay exactly-once
        in effect."""
        for pod in pods:
            try:
                self._api.delete(Pod.KIND, ns, pod)
            except NotFoundError:
                pass
        # the half-evicted-gang window: pods gone, reservation still
        # present — exactly what takeover reconciliation must finish
        crashpoint.maybe_crash(crashpoint.PREEMPT_MID_EXECUTE)
        try:
            self._rr_cache.delete(ns, app_id)
        except NotFoundError:
            pass

    # -- failover replay ------------------------------------------------

    def recover(self) -> int:
        """Replay pending evict intents (crash between journal and
        ack).  Called at wiring boot on the active AND by the standby
        after takeover; idempotent execution + ack = exactly-once."""
        gate = self.fence_gate
        if gate is not None:
            # replay executes deletes: a deposed replica must not
            # re-drive evictions the successor may have superseded.
            # Boot-time recover runs before the fence is installed
            # (gate is None); post-takeover recover runs after the
            # lease grant, so a live leader always passes.
            gate.check("preempt.recover")
        replayed = 0
        for intent in self._journal.pending():
            if intent.get("kind") != EVICT_KIND or intent.get("op") != "delete":
                continue
            obj = intent.get("obj") or {}
            ns, app_id = intent["ns"], intent["name"]
            self._execute(ns, app_id, list(obj.get("pods", ())))
            self._journal.ack("delete", ns, app_id)
            self._note_eviction(
                ns=ns,
                app_id=app_id,
                band=obj.get("band", ""),
                tenant=obj.get("tenant", ""),
                pods=len(obj.get("pods", ())),
                reason=obj.get("reason", "replayed evict intent"),
                replayed=True,
            )
            replayed += 1
        return replayed

    def journal_depth(self) -> int:
        return self._journal.depth()

    def close(self) -> None:
        self._journal.close()

    # -- bookkeeping ----------------------------------------------------

    def _note_eviction(self, ns, app_id, band, tenant, pods, reason, replayed):
        with self._lock:
            racecheck.note_access(self, "_recent")
            racecheck.note_access(self, "_evicted_total")
            self._evicted_total += 1
            self._recent.append(
                {
                    "namespace": ns,
                    "app": app_id,
                    "band": band,
                    "tenant": tenant,
                    "pods": pods,
                    "reason": reason,
                    "replayed": replayed,
                    # timesource so the sim's virtual clock stamps these
                    "at": timesource.now(),
                }
            )

    def _stamp(self, plan: VictimPlan, evicted: List[str]) -> None:
        with self._lock:
            racecheck.note_access(self, "_victims_total")
            self._victims_total += len(evicted)
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.POLICY_PREEMPTION_COUNT)
            self._metrics.counter(
                mnames.POLICY_PREEMPTION_VICTIMS, inc=float(len(evicted))
            )
            self._metrics.histogram(mnames.POLICY_WHATIF_MS, plan.whatif_ms)
        if self._provenance is not None:
            try:
                self._provenance.on_trigger(
                    "policy-preemption",
                    json.dumps(
                        {
                            "preemptor": plan.preemptor_app,
                            "band": plan.preemptor_band,
                            "victims": evicted,
                            "whatifMs": round(plan.whatif_ms, 3),
                            "lane": plan.lane,
                        },
                        sort_keys=True,
                    ),
                )
            except Exception:
                pass

    def state(self) -> Dict[str, object]:
        with self._lock:
            racecheck.note_access(self, "_recent")
            racecheck.note_access(self, "_evicted_total")
            racecheck.note_access(self, "_victims_total")
            return {
                "evictionsTotal": self._evicted_total,
                "victimsTotal": self._victims_total,
                "journalDepth": self._journal.depth(),
                "recent": list(self._recent),
            }
