"""Gang-aware victim selection: whole-application victim sets, never
partial gangs, each set validated by a what-if solve.

Candidates are running applications (one ResourceReservation each) in
the preemptor's instance group whose band rank sits at least
``preemption_min_band_gap`` below the preemptor's — optionally widened
by DRF over-share tenants — seeded/ordered so that apps the explainer
already named as blockers are tried first.  Scoring follows Borg's
eviction order (Verma et al., EuroSys'15): lowest band first, then
youngest first (least work lost), then largest footprint first (fewest
gangs disturbed).

Victim sets accumulate greedily a WHOLE application at a time (the
I-P1 invariant — partial-gang eviction is impossible by construction:
the unit of selection is the app, and every pod of a selected app is
evicted together).  Each accumulated set is validated by
:func:`whatif_fits` — the solver's own admission rule on
``avail + freed`` — before it is ever offered to the committer.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from .ordering import Gang, gang_feasible


def whatif_fits(
    avail: np.ndarray,
    exec_ok: np.ndarray,
    driver_rank: np.ndarray,
    freed: np.ndarray,
    gang: Gang,
) -> bool:
    """Would the preemptor's gang admit after the victims' capacity is
    returned?  Exactly the solver's admission rule on the post-eviction
    basis ``avail + freed`` — a True verdict is a statement about the
    real solver, not a heuristic."""
    return gang_feasible(avail + freed, exec_ok, driver_rank, gang)


@dataclass
class VictimCandidate:
    """One whole running application, with everything the committer
    needs: the pods to delete and the capacity its eviction returns."""

    namespace: str
    app_id: str
    band: str
    band_rank: int
    tenant: str
    created: float
    # [N,3] base-unit capacity this app's reservations return per node
    freed: np.ndarray
    # bound pod names (driver + executors) — evicted together or not at all
    pods: List[str] = field(default_factory=list)

    @property
    def footprint(self) -> int:
        return int(self.freed.sum())


@dataclass
class VictimPlan:
    """A validated eviction plan for one preemptor."""

    preemptor_app: str
    preemptor_band: str
    victims: List[VictimCandidate]
    whatif_ms: float
    lane: str  # "session" when validated against the warm delta-solve basis

    @property
    def victim_apps(self) -> List[str]:
        return [v.app_id for v in self.victims]


@guarded_by("_lock", "_stats")
class VictimSelector:
    """Selects and what-if-validates whole-application victim sets.

    Pure function of its inputs apart from the stats ledger; the engine
    supplies ``list_rrs`` (live ResourceReservations), ``band_fn`` (rr →
    (band, rank) via the driver pod's label) and ``tenant_fn``."""

    def __init__(
        self,
        list_rrs: Callable[[], list],
        band_fn: Callable[[object], Tuple[str, int]],
        tenant_fn: Callable[[str, str], str],
        min_band_gap: int = 1,
        max_victims: int = 4,
    ):
        self._list_rrs = list_rrs
        self._band_fn = band_fn
        self._tenant_fn = tenant_fn
        self._min_band_gap = max(int(min_band_gap), 0)
        self._max_victims = max(int(max_victims), 1)
        self._lock = threading.Lock()
        self._stats = {"attempts": 0, "validated": 0, "rejected": 0}

    # -- candidate enumeration ------------------------------------------

    def candidates(
        self,
        preemptor_rank: int,
        node_index: Dict[str, int],
        n_nodes: int,
        over_share: Dict[str, float] = None,
        blockers: Tuple[str, ...] = (),
    ) -> List[VictimCandidate]:
        """Running apps eligible as victims, best-victim-first.  An app
        qualifies by band gap OR (when DRF preemption is active) by its
        tenant being over fair share; apps named in the explainer's
        blocker set sort ahead of equal-scored peers."""
        from ..ops.tensorize import _resources_to_base

        over_share = over_share or {}
        blocker_set = set(blockers)
        out: List[VictimCandidate] = []
        for rr in self._list_rrs():
            band, rank = self._band_fn(rr)
            tenant = self._tenant_fn(rr.namespace, rr.name)
            by_gap = rank <= preemptor_rank - self._min_band_gap
            by_share = tenant in over_share
            if not (by_gap or by_share):
                continue
            freed = np.zeros((n_nodes, 3), dtype=np.int64)
            touched = False
            for res in rr.spec.reservations.values():
                idx = node_index.get(res.node)
                if idx is None:
                    continue
                row, _exact = _resources_to_base(res.resources_value())
                freed[idx] += np.asarray(row, dtype=np.int64)
                touched = True
            if not touched:
                # app holds nothing on any live node — evicting it
                # frees nothing, never a useful victim
                continue
            out.append(
                VictimCandidate(
                    namespace=rr.namespace,
                    app_id=rr.name,
                    band=band,
                    band_rank=rank,
                    tenant=tenant,
                    created=float(rr.meta.creation_timestamp),
                    freed=freed,
                    pods=sorted(set(rr.status.pods.values())),
                )
            )
        out.sort(
            key=lambda c: (
                c.app_id not in blocker_set,  # blockers first
                c.band_rank,                  # lowest band first
                -c.created,                   # youngest first
                -c.footprint,                 # largest footprint first
                c.app_id,
            )
        )
        return out

    # -- selection + what-if validation ---------------------------------

    def select(
        self,
        preemptor_app: str,
        preemptor_band: str,
        preemptor_rank: int,
        gang: Gang,
        avail: np.ndarray,
        exec_ok: np.ndarray,
        driver_rank: np.ndarray,
        node_index: Dict[str, int],
        over_share: Dict[str, float] = None,
        blockers: Tuple[str, ...] = (),
        session_validate: Callable[[np.ndarray], Optional[bool]] = None,
    ) -> Optional[VictimPlan]:
        """Greedy whole-app accumulation up to ``max_victims``, what-if
        validating after each addition; returns the first (smallest)
        validated set, or None when no eligible set makes the gang fit.

        ``session_validate(freed)`` — when supplied — re-proves the
        winning set against the warm delta-solve session basis; None
        (session unavailable) falls back to the numpy verdict."""
        with self._lock:
            racecheck.note_access(self, "_stats")
            self._stats["attempts"] += 1
        t0 = timesource.perf()
        cands = self.candidates(
            preemptor_rank, node_index, avail.shape[0], over_share, blockers
        )
        chosen: List[VictimCandidate] = []
        freed = np.zeros_like(avail)
        plan = None
        for cand in cands:
            if len(chosen) >= self._max_victims:
                break
            chosen.append(cand)
            freed = freed + cand.freed
            if not whatif_fits(avail, exec_ok, driver_rank, freed, gang):
                continue
            lane = "numpy"
            if session_validate is not None:
                verdict = session_validate(freed)
                if verdict is False:
                    continue
                if verdict is True:
                    lane = "session"
            plan = VictimPlan(
                preemptor_app=preemptor_app,
                preemptor_band=preemptor_band,
                victims=list(chosen),
                whatif_ms=(timesource.perf() - t0) * 1e3,
                lane=lane,
            )
            break
        with self._lock:
            racecheck.note_access(self, "_stats")
            self._stats["validated" if plan else "rejected"] += 1
        return plan

    def stats(self) -> Dict[str, int]:
        with self._lock:
            racecheck.note_access(self, "_stats")
            return dict(self._stats)
