"""Decision provenance: the WHY behind every scheduling verdict.

The extender's answer to kube-scheduler is a bare fit/no-fit; PR 1's
span tree says where the time went but not why a driver was refused.
This package closes that gap end to end:

- :mod:`.records` — bounded per-decision records (snapshot content-key,
  change-feed seq, queue slice, verdicts, shortfall) in a ring the
  ``GET /explain/<pod>`` endpoint serves;
- :mod:`.explain` — the unschedulability explainer over the native
  solver's shortfall vectors and blocker sets
  (``native/fifo_solver.cpp fifo_explain_queue``): tightest dimension,
  magnitude, nearest-fit node, and which earlier FIFO drivers consumed
  the capacity this app needed;
- :mod:`.recorder` — the anomaly flight recorder: a bounded ring of
  replayable decision bundles persisted as JSONL when a trigger fires
  (deadline exceeded, circuit breaker open, warm≠cold parity guard, sim
  invariant violation), replayed byte-for-byte with
  ``python -m k8s_spark_scheduler_tpu.sim --replay-bundle <path>``;
- :mod:`.tracker` — the per-extender facade wiring it all together.

Everything here is diagnostic: provenance never feeds a decision, and
with ``provenance.enabled = false`` no capture code runs at all.
"""

from .explain import DIM_NAMES, ShortfallInfo, shortfall_message  # noqa: F401
from .records import DecisionRecord, ProvenanceRing  # noqa: F401
from .recorder import (  # noqa: F401
    DecisionBundle,
    FlightRecorder,
    replay_bundle,
    replay_bundle_file,
)
from .tracker import ProvenanceTracker, SolveArtifacts  # noqa: F401
