"""The unschedulability explainer: shortfall vectors + blocker sets.

Wraps the native explainer (``native/fifo_solver.cpp
fifo_explain_queue`` via :func:`..native.fifo.explain_queue_native`)
and translates its scaled-integer decomposition back into operator
vocabulary: resource dimension names, base-unit magnitudes, node names
and zones, and earlier-driver pod names.  Diagnostic only — explain
output never feeds a decision, and a missing native library degrades to
"no detail available" rather than an error.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

DIM_NAMES = ("cpu", "memory", "nvidia.com/gpu")
# base units per dimension (ops/tensorize._to_base_units): milli-cpu,
# bytes, milli-gpu
DIM_UNITS = ("milli-cpu", "bytes", "milli-gpu")


@dataclass
class ShortfallInfo:
    """One refused gang's decomposed verdict, in operator units."""

    kind: str                 # "capacity" | "driver-placement"
    tightest_dim: int         # index into DIM_NAMES; -1 = driver-blocked
    dim_name: str             # "" when driver-blocked
    shortfall_execs: int      # executors short in the tightest dimension
    shortfall_base: int       # same, in base units of that dimension
    unit: str
    cap_total: int            # cluster-wide executor capacity (clamped)
    gang_size: int
    dim_totals: Tuple[int, int, int]  # per-dim-alone capacity totals
    nearest_node: str         # best single node ("" = none)
    nearest_zone: str
    nearest_cap: int
    driver_fit: int           # candidates whose availability covers the driver
    flip: int                 # queue position that flipped feasibility
    blockers: List[str] = field(default_factory=list)  # earlier driver pods

    @property
    def blocker_count(self) -> int:
        return len(self.blockers)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "tightestDimension": self.dim_name or None,
            "shortfallExecutors": self.shortfall_execs,
            "shortfallBaseUnits": self.shortfall_base,
            "unit": self.unit if self.dim_name else None,
            "capacityTotal": self.cap_total,
            "gangSize": self.gang_size,
            "dimensionTotals": {
                DIM_NAMES[j]: int(self.dim_totals[j]) for j in range(3)
            },
            "nearestFitNode": self.nearest_node or None,
            "nearestFitZone": self.nearest_zone or None,
            "nearestFitCapacity": self.nearest_cap,
            "driverCandidatesFitting": self.driver_fit,
            "flipPosition": self.flip,
            "blockedByCount": self.blocker_count,
            "blockedBy": list(self.blockers),
        }


def shortfall_message(info: ShortfallInfo) -> str:
    """The actionable one-liner threaded into FailedNodes messages:
    ``short 12 executors (24000 milli-cpu) in cpu, zone az-b; blocked by
    3 earlier drivers``."""
    if info.kind == "driver-placement":
        if info.driver_fit == 0:
            msg = "gang capacity sufficient but no candidate node fits the driver row"
        else:
            msg = (
                "gang capacity sufficient only without the driver placed: "
                f"hosting it on any of the {info.driver_fit} fitting "
                "candidates drops executor capacity below the gang size"
            )
    else:
        where = f" near {info.nearest_node}" if info.nearest_node else ""
        zone = f" (zone {info.nearest_zone})" if info.nearest_zone else ""
        msg = (
            f"short {info.shortfall_execs} executors"
            f" ({info.shortfall_base} {info.unit}) in {info.dim_name}"
            f"{where}{zone}"
        )
    if info.blocker_count:
        names = ", ".join(info.blockers[:3])
        more = "…" if info.blocker_count > 3 else ""
        msg += f"; blocked by {info.blocker_count} earlier drivers ({names}{more})"
    elif info.flip == -2:
        msg += "; not blocked by the pending queue — current capacity is short"
    return msg


def explain_refusal(artifacts, target: int) -> Optional[ShortfallInfo]:
    """Run the native explainer for the app at queue position ``target``
    of a captured solve, translating indices back to names.  None when
    the native explainer is unavailable or the target is feasible."""
    from ..native.fifo import explain_queue_native

    res = explain_queue_native(
        artifacts.basis,
        artifacts.driver_rank,
        artifacts.exec_ok,
        artifacts.packed,
        artifacts.policy_code,
        target,
    )
    if res is None or res.feasible:
        return None

    names = artifacts.node_names
    nearest_node = ""
    nearest_zone = ""
    if 0 <= res.max_node < len(names):
        nearest_node = names[res.max_node]
        nearest_zone = artifacts.zone_of(res.max_node)

    gang = int(artifacts.packed[target, 6])
    if res.tightest_dim >= 0:
        j = res.tightest_dim
        # scaled units × the tensorize scale vector = base units
        per_exec = int(artifacts.packed[target, 3 + j]) * int(
            artifacts.scale[j]
        )
        info = ShortfallInfo(
            kind="capacity",
            tightest_dim=j,
            dim_name=DIM_NAMES[j],
            shortfall_execs=res.shortfall_execs,
            shortfall_base=res.shortfall_execs * per_exec,
            unit=DIM_UNITS[j],
            cap_total=res.cap_total,
            gang_size=gang,
            dim_totals=res.dim_totals,
            nearest_node=nearest_node,
            nearest_zone=nearest_zone,
            nearest_cap=res.max_cap,
            driver_fit=res.driver_fit,
            flip=res.flip,
        )
    else:
        info = ShortfallInfo(
            kind="driver-placement",
            tightest_dim=-1,
            dim_name="",
            shortfall_execs=0,
            shortfall_base=0,
            unit="",
            cap_total=res.cap_total,
            gang_size=gang,
            dim_totals=res.dim_totals,
            nearest_node=nearest_node,
            nearest_zone=nearest_zone,
            nearest_cap=res.max_cap,
            driver_fit=res.driver_fit,
            flip=res.flip,
        )
    qnames = artifacts.queue_names
    info.blockers = [
        (qnames[i] if i < len(qnames) else f"queue-position-{i}")
        for i in range(min(len(res.blockers), artifacts.n_earlier))
        if res.blockers[i]
    ]
    return info
