"""The anomaly flight recorder: replayable decision bundles.

A :class:`DecisionBundle` is the complete, self-contained input of one
native queue solve — the scaled availability basis, driver ranks,
executor eligibility, the packed app rows — plus the verdicts the
production solve produced.  The :class:`FlightRecorder` keeps a bounded
ring of the most recent bundles and, when a trigger fires (deadline
exceeded, circuit breaker open, warm≠cold parity mismatch, sim
invariant violation), persists the ring as one JSONL file: one bundle
per line, deterministic key order, diffable.

``python -m k8s_spark_scheduler_tpu.sim --replay-bundle <path>``
re-runs every bundle through BOTH the stateless cold native solver and
a fresh persistent session (the warm lane, twice — the second solve
resumes fully from cache) and asserts byte-identical verdicts, so a
persisted anomaly is a reproducible artifact, not a log line.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from .. import timesource

BUNDLE_SCHEMA = 1

_POLICY_NAMES = {0: "tightly-pack", 1: "distribute-evenly", 2: "minimal-fragmentation"}


def _avail_sha(avail_after: np.ndarray) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(avail_after, dtype=np.int32).tobytes()
    ).hexdigest()[:16]


class DecisionBundle:
    """Dict-shaped for JSONL friendliness; this class only builds and
    validates the shape.  Materialization is persist-time only: the
    ring holds array REFERENCES (the basis is the session's resident
    copy, never mutated in place; packed rows and verdict arrays are
    per-request), so noting a decision on the hot path costs a tuple
    append, not a 10k-row list conversion."""

    @staticmethod
    def from_artifacts(artifacts, pod: str, outcome: str, seq: int,
                       t: float) -> dict:
        n_earlier = int(artifacts.n_earlier)
        feasible = np.asarray(artifacts.feasible, dtype=bool)[:n_earlier]
        didx = np.asarray(artifacts.didx, dtype=np.int32)[:n_earlier]
        return {
            "schema": BUNDLE_SCHEMA,
            "seq": int(seq),
            "pod": pod,
            "outcome": outcome,
            "t": float(t),
            "lane": artifacts.lane,
            "policy": _POLICY_NAMES.get(artifacts.policy_code, "unknown"),
            "policyCode": int(artifacts.policy_code),
            "nb": int(artifacts.basis.shape[0]),
            "na": int(artifacts.packed.shape[0]),
            "nEarlier": n_earlier,
            "contentKey": (
                list(artifacts.content_key) if artifacts.content_key else None
            ),
            "feedSeq": artifacts.feed_seq,
            "queueNames": list(artifacts.queue_names),
            "basis": artifacts.basis.astype(int).tolist(),
            "driverRank": artifacts.driver_rank.astype(int).tolist(),
            "execOk": [int(v) for v in artifacts.exec_ok],
            "apps8": artifacts.packed.astype(int).tolist(),
            "verdicts": {
                "feasible": [int(v) for v in feasible],
                "didx": didx.astype(int).tolist(),
                "resume": int(artifacts.resume),
                "availAfterSha": (
                    _avail_sha(artifacts.avail_after)
                    if artifacts.avail_after is not None
                    else None
                ),
            },
        }


@guarded_by("_lock", "_ring", "_seq", "_persist_seq", "skipped_oversize",
            "persisted_paths")
class FlightRecorder:
    """Bounded ring of recent decision bundles + trigger-driven persist.

    Bundles over ``max_nodes`` are counted and skipped (a 100k-node
    basis is not a flight-recorder artifact); the ring and every
    persisted file are bounded by ``capacity`` bundles."""

    def __init__(
        self,
        capacity: int = 8,
        out_dir: Optional[str] = None,
        max_nodes: int = 4096,
        metrics=None,
    ):
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._capacity = max(1, int(capacity))
        self._seq = 0
        self._persist_seq = 0
        self.out_dir = out_dir
        self.max_nodes = int(max_nodes)
        self._metrics = metrics
        self.skipped_oversize = 0
        self.persisted_paths: List[str] = []

    def note(self, artifacts, pod: str, outcome: str) -> Optional[int]:
        """Add one decision's bundle to the ring; returns its seq (the
        DecisionRecord cross-reference) or None when skipped.  Hot-path
        cost is one tuple append — JSON materialization waits for a
        trigger (see DecisionBundle)."""
        if artifacts.basis.shape[0] > self.max_nodes:
            with self._lock:
                racecheck.note_access(self, "skipped_oversize")
                self.skipped_oversize += 1
            return None
        t = float(timesource.now())
        with self._lock:
            racecheck.note_access(self, "_ring")
            seq = self._seq
            self._seq += 1
            self._ring.append((seq, artifacts, pod, outcome, t))
            while len(self._ring) > self._capacity:
                self._ring.popleft()
        return seq

    def persist(self, trigger: str, detail: str = "") -> Optional[str]:
        """Write the current ring as one JSONL file (newest last);
        returns the path, or None when the ring is empty or no out_dir
        is configured."""
        with self._lock:
            racecheck.note_access(self, "_ring")
            entries = list(self._ring)
        if not entries or not self.out_dir:
            return None
        with self._lock:
            racecheck.note_access(self, "_persist_seq")
            # numbered only when a file will actually be written, so the
            # on-disk sequence has no gaps an operator could mistake for
            # lost bundles
            self._persist_seq += 1
            pseq = self._persist_seq
        bundles = [
            DecisionBundle.from_artifacts(art, pod, outcome, seq, t)
            for seq, art, pod, outcome, t in entries
        ]
        os.makedirs(self.out_dir, exist_ok=True)
        safe_trigger = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in trigger
        )
        path = os.path.join(
            self.out_dir, f"bundle-{pseq:04d}-{safe_trigger}.jsonl"
        )
        header = {
            "schema": BUNDLE_SCHEMA,
            "header": True,
            "trigger": trigger,
            "detail": detail,
            "t": float(timesource.now()),
            "bundles": len(bundles),
        }
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, sort_keys=True, separators=(",", ":")) + "\n")
            for b in bundles:
                f.write(json.dumps(b, sort_keys=True, separators=(",", ":")) + "\n")
        os.replace(tmp, path)
        with self._lock:
            racecheck.note_access(self, "persisted_paths")
            self.persisted_paths.append(path)
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(
                mnames.PROVENANCE_BUNDLE_PERSISTED, {"trigger": trigger}
            )
            self._metrics.gauge(
                mnames.PROVENANCE_BUNDLE_BYTES, float(os.path.getsize(path))
            )
        return path

    def stats(self) -> Dict:
        with self._lock:
            return {
                "size": len(self._ring),
                "capacity": self._capacity,
                "noted": self._seq,
                "skipped_oversize": self.skipped_oversize,
                "persisted": len(self.persisted_paths),
                # dedupe by array identity: consecutive warm-path bundles
                # share ONE session basis, which must count once
                "ring_bytes": sum(
                    arr.nbytes
                    for arr in {
                        id(a): a
                        for e in self._ring
                        for a in (e[1].basis, e[1].packed)
                    }.values()
                ),
            }


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------


def replay_bundle(bundle: dict) -> dict:
    """Re-run one bundle's decision deterministically on both native
    lanes and compare byte-for-byte against the recorded verdicts.

    Returns {"pod", "seq", "ok", "mismatches": [str], "lanes": {...}}.
    """
    from ..native.fifo import (
        NativeFifoSession,
        native_session_available,
        solve_packed_cold,
    )

    mismatches: List[str] = []
    lanes: Dict[str, str] = {}

    avail = np.array(bundle["basis"], dtype=np.int32)
    rank = np.array(bundle["driverRank"], dtype=np.int32)
    eok = np.array(bundle["execOk"], dtype=np.uint8).astype(bool)
    apps8 = np.array(bundle["apps8"], dtype=np.int32)
    n_earlier = int(bundle["nEarlier"])
    policy_code = int(bundle["policyCode"])
    want_feas = np.array(bundle["verdicts"]["feasible"], dtype=bool)
    want_didx = np.array(bundle["verdicts"]["didx"], dtype=np.int32)
    want_sha = bundle["verdicts"].get("availAfterSha")

    earlier = apps8[:n_earlier]

    def compare(lane: str, feas, didx, after) -> None:
        before = len(mismatches)
        got_feas = np.asarray(feas, dtype=bool)[:n_earlier]
        got_didx = np.asarray(didx, dtype=np.int32)[:n_earlier]
        if got_feas.tobytes() != want_feas.tobytes():
            mismatches.append(f"{lane}: feasible verdicts differ")
        if got_didx.tobytes() != want_didx.tobytes():
            mismatches.append(f"{lane}: driver indices differ")
        if want_sha is not None and _avail_sha(after) != want_sha:
            mismatches.append(f"{lane}: post-queue availability differs")
        lanes[lane] = "ok" if len(mismatches) == before else "mismatch"

    feas, didx, after = solve_packed_cold(policy_code, avail, rank, eok, earlier)
    compare("cold", feas, didx, after)

    if native_session_available():
        sess = NativeFifoSession()
        try:
            sess.load(avail, rank, eok, policy_code)
            resume, feas_w, didx_w, after_w = sess.solve(earlier)
            compare("warm-first", feas_w, didx_w, after_w)
            if resume != 0:
                mismatches.append(
                    f"warm-first: fresh session resumed at {resume}, want 0"
                )
            # second solve of the identical queue must serve fully from
            # the prefix cache — the warm lane proper
            resume2, feas_w2, didx_w2, after_w2 = sess.solve(earlier)
            compare("warm-resume", feas_w2, didx_w2, after_w2)
            if resume2 != n_earlier:
                mismatches.append(
                    f"warm-resume: resumed at {resume2}, want {n_earlier}"
                )
        finally:
            sess.close()
    else:
        lanes["warm"] = "unavailable"

    return {
        "pod": bundle.get("pod", ""),
        "seq": bundle.get("seq"),
        "policy": bundle.get("policy"),
        "nEarlier": n_earlier,
        "ok": not mismatches,
        "mismatches": mismatches,
        "lanes": lanes,
    }


def replay_bundle_file(path: str) -> List[dict]:
    """Replay every bundle in a persisted JSONL file (header line
    skipped); returns the per-bundle results."""
    results = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("header"):
                continue
            results.append(replay_bundle(obj))
    return results
