"""Bounded per-decision provenance records.

One :class:`DecisionRecord` per scheduling decision — small (names,
keys, verdict, optional shortfall decomposition; never tensor data) —
kept in a bounded ring indexed by pod name.  ``GET /explain/<pod>`` and
the enriched ``/debug/schedule/<pod>`` serve from here.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from .explain import ShortfallInfo


@dataclass
class DecisionRecord:
    """What one Filter decision was, and why."""

    pod: str
    namespace: str = "default"
    role: str = ""
    instance_group: str = ""
    trace_id: Optional[str] = None
    t: float = 0.0                    # timesource (virtual in the sim)
    outcome: str = ""
    node: str = ""
    lane: str = ""                    # solver lane that served the queue pass
    policy: str = ""
    content_key: Optional[Tuple] = None  # snapshot content key at solve time
    feed_seq: Optional[int] = None       # change-feed sequence at solve time
    queue_len: int = 0                   # earlier drivers ahead of this one
    queue_slice: Tuple[str, ...] = ()    # first earlier-driver pod names
    earlier_infeasible: Tuple[int, ...] = ()  # blocked earlier queue positions
    shortfall: Optional[ShortfallInfo] = None
    message: str = ""
    bundle_seq: Optional[int] = None  # flight-recorder bundle holding arrays

    def to_dict(self) -> dict:
        out = {
            "pod": self.pod,
            "namespace": self.namespace,
            "role": self.role,
            "instanceGroup": self.instance_group,
            "traceId": self.trace_id,
            "t": self.t,
            "outcome": self.outcome,
            "node": self.node or None,
            "lane": self.lane or None,
            "policy": self.policy or None,
            "contentKey": list(self.content_key) if self.content_key else None,
            "feedSeq": self.feed_seq,
            "queueLength": self.queue_len,
            "queueSlice": list(self.queue_slice),
            "earlierInfeasible": list(self.earlier_infeasible),
            "shortfall": self.shortfall.to_dict() if self.shortfall else None,
            "message": self.message or None,
            "bundleSeq": self.bundle_seq,
        }
        return out


@guarded_by("_lock", "_ring", "_by_pod")
class ProvenanceRing:
    """Bounded decision-record ring with a latest-per-pod index.

    The ring bounds total memory; the index keeps O(1) ``/explain``
    lookups and is pruned as records fall off the ring (an evicted
    record's pod entry is dropped only if it still points at the evicted
    record — a newer decision for the same pod keeps its entry)."""

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self._capacity = max(1, int(capacity))
        self._ring: deque = deque()
        self._by_pod: "OrderedDict[str, DecisionRecord]" = OrderedDict()
        self.recorded = 0

    @staticmethod
    def _key(namespace: str, pod: str) -> str:
        return f"{namespace}/{pod}"

    def record(self, rec: DecisionRecord) -> None:
        key = self._key(rec.namespace, rec.pod)
        with self._lock:
            racecheck.note_access(self, "_ring")
            self._ring.append(rec)
            self._by_pod[key] = rec
            self._by_pod.move_to_end(key)
            self.recorded += 1
            while len(self._ring) > self._capacity:
                old = self._ring.popleft()
                old_key = self._key(old.namespace, old.pod)
                if self._by_pod.get(old_key) is old:
                    del self._by_pod[old_key]

    def latest_for_pod(self, pod: str) -> Optional[DecisionRecord]:
        """Lookup by ``namespace/pod``, or by bare pod name (newest
        match across namespaces — the convenience form the
        ``/explain/<pod>`` endpoint serves; pass ``ns/pod`` to
        disambiguate same-named pods in a multi-tenant cluster)."""
        with self._lock:
            racecheck.note_access(self, "_by_pod")
            if "/" in pod:
                return self._by_pod.get(pod)
            suffix = "/" + pod
            for key in reversed(self._by_pod):
                if key.endswith(suffix):
                    return self._by_pod[key]
            return None

    def recent(self, limit: int = 20) -> List[DecisionRecord]:
        with self._lock:
            racecheck.note_access(self, "_ring")
            items = list(self._ring)
        return items[-max(0, int(limit)):][::-1]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "size": len(self._ring),
                "capacity": self._capacity,
                "recorded": self.recorded,
                "indexed_pods": len(self._by_pod),
            }
