"""ProvenanceTracker: the per-extender decision-provenance facade.

Owns the record ring, the flight recorder, and the per-request capture
slot the solver lanes fill.  The extender drives the lifecycle under its
predicate lock:

    begin_decision(pod, …)     # request context: queue slice, snapshot keys
    <solver lane calls capture(SolveArtifacts)>
    refusal_detail(kind)       # on failure: native explain → message suffix
    finish_decision(outcome)   # record + bundle ring + metrics

HTTP threads only READ (``explain``/``recent``/``stats``) through the
ring's own lock.  With ``enabled=False`` the extender never calls any
of this and the solver capture sinks stay ``None`` — zero cost.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from .. import timesource
from ..metrics import names as mnames
from ..tracing import spans as tracing
from .explain import DIM_NAMES, ShortfallInfo, explain_refusal, shortfall_message
from .records import DecisionRecord, ProvenanceRing
from .recorder import FlightRecorder

logger = logging.getLogger(__name__)

# queue names kept on a record (full queues run to 1k+ apps; the record
# ring must stay small)
_QUEUE_SLICE = 8


@dataclass
class SolveArtifacts:
    """One native queue solve, captured by reference (no copies): the
    arrays a replay or explain needs.  Only the lanes that solve in
    scaled-integer space capture (native session / native stateless);
    Quantity-path decisions record without artifacts."""

    policy_code: int
    lane: str
    basis: np.ndarray         # [Nb, 3] int32 availability at position 0
    driver_rank: np.ndarray   # [Nb] int32
    exec_ok: np.ndarray       # [Nb] bool
    packed: np.ndarray        # [na, 8] int32 (earlier apps + current last)
    n_earlier: int
    feasible: np.ndarray      # [>= n_earlier] bool verdicts
    didx: Optional[np.ndarray] = None  # [>= n_earlier] int32 (native lanes)
    resume: int = 0
    avail_after: Optional[np.ndarray] = None
    scale: Optional[np.ndarray] = None   # [3] int64 tensorize scale
    node_names: Sequence[str] = ()
    zone_names: Sequence[str] = ()
    zone_id: Optional[np.ndarray] = None
    skip_allowed: Sequence[bool] = ()
    content_key: Optional[Tuple] = None
    feed_seq: Optional[int] = None
    queue_names: Tuple[str, ...] = ()

    def memo_sig(self) -> int:
        """Signature of the inputs the refusal explain depends on BEYOND
        the snapshot content key: the candidate-node subset and the
        skip_allowed vector.  kube-scheduler node sampling rotates
        NodeNames between attempts without any state delta, and the
        subset lands in the exec_ok / driver_rank masks (node_names
        spans EVERY affinity-matching node, the same for any subset —
        see _pack_current's domain note), so those mask bytes are what
        the signature must cover; fifo age gating flips skip_allowed
        purely with time.  Hash, not tuple — the memo key must not pin
        per-request arrays."""
        sig = getattr(self, "_memo_sig", None)
        if sig is None:
            sig = hash((
                tuple(self.node_names),
                np.asarray(self.exec_ok, dtype=np.uint8).tobytes(),
                np.asarray(self.driver_rank, dtype=np.int32).tobytes(),
                tuple(bool(s) for s in self.skip_allowed),
            ))
            self._memo_sig = sig
        return sig

    def zone_of(self, node_index: int) -> str:
        if self.zone_id is None or not (0 <= node_index < len(self.zone_id)):
            return ""
        z = int(self.zone_id[node_index])
        if 0 <= z < len(self.zone_names):
            return self.zone_names[z]
        return ""

    def first_blocked_earlier(self) -> Optional[int]:
        """First enforced earlier driver whose verdict is infeasible —
        the FAILURE_EARLIER_DRIVER refusal's explain target."""
        feas = np.asarray(self.feasible, dtype=bool)[: self.n_earlier]
        skip = np.asarray(
            list(self.skip_allowed)[: self.n_earlier]
            if len(self.skip_allowed)
            else np.zeros(self.n_earlier, dtype=bool)
        ).astype(bool)
        blocked = np.flatnonzero(~feas & ~skip)
        if len(blocked):
            return int(blocked[0])
        return None


@guarded_by("_pending_lock", "_pending", "_explain_cache", "_last_trigger")
class ProvenanceTracker:
    """See module docstring.  Thread model: lifecycle methods run under
    the extender's predicate lock (one decision at a time); the pending
    slot still takes its own lock because triggers (breaker open) can
    fire from write-back threads concurrently."""

    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = 128,
        recorder_size: int = 8,
        bundle_dir: Optional[str] = None,
        max_bundle_nodes: int = 4096,
        metrics=None,
        trigger_min_interval: float = 30.0,
    ):
        self.enabled = enabled
        self._metrics = metrics
        self.ring = ProvenanceRing(capacity=ring_size)
        if bundle_dir is None:
            bundle_dir = os.environ.get("SCHED_PROVENANCE_DIR") or None
        self.recorder = FlightRecorder(
            capacity=recorder_size,
            out_dir=bundle_dir,
            max_nodes=max_bundle_nodes,
            metrics=metrics,
        )
        self._pending_lock = threading.Lock()
        self._pending: Optional[dict] = None
        # refusal-explain memo: kube-scheduler requeues a Pending pod
        # against UNCHANGED cluster state far more often than the state
        # changes, and each explain costs ~2 cold solves.  The key is
        # exact: any node/pod/reservation mutation bumps the change feed
        # and with it the snapshot content_key, so a hit can only serve
        # a byte-identical decision's explanation.
        self._explain_cache: "OrderedDict" = OrderedDict()
        # per-trigger persist debounce: a deadline storm during overload
        # must not serialize+write near-identical bundle files per failed
        # request while the predicate lock is held — one persist per
        # trigger type per interval captures the same forensic state
        self.trigger_min_interval = float(trigger_min_interval)
        self._last_trigger: dict = {}
        self.triggers_suppressed = 0
        self.parity_mismatches = 0

    # -- lifecycle (extender, under the predicate lock) ----------------------

    def begin_decision(
        self,
        pod,
        role: str = "",
        queue_names: Sequence[str] = (),
        content_key: Optional[Tuple] = None,
        feed_seq: Optional[int] = None,
    ) -> None:
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            self._pending = {
                "pod": pod.name,
                "namespace": pod.namespace,
                "role": role,
                "queue_names": tuple(queue_names),
                "content_key": content_key,
                "feed_seq": feed_seq,
                "artifacts": None,
                "shortfall": None,
                "message": "",
            }

    def note_context(
        self,
        queue_names: Optional[Sequence[str]] = None,
        content_key: Optional[Tuple] = None,
        feed_seq: Optional[int] = None,
    ) -> None:
        """Attach request context discovered after begin_decision (the
        earlier-driver queue slice, the snapshot keys)."""
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            p = self._pending
            if p is None:
                return
            if queue_names is not None:
                p["queue_names"] = tuple(queue_names)
            if content_key is not None:
                p["content_key"] = content_key
            if feed_seq is not None:
                p["feed_seq"] = feed_seq

    def pending_shortfall(self):
        """The memoized ShortfallInfo of the decision in flight (None
        until a refusal has been explained) — the policy engine reads
        its ``blockers`` list as the victim-candidate seed."""
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            p = self._pending
            return p.get("shortfall") if p else None

    def capture(self, artifacts: SolveArtifacts) -> None:
        """The solver lanes' capture sink (engine + solve_tensor)."""
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            p = self._pending
            if p is None:
                return
            if not artifacts.queue_names:
                artifacts.queue_names = p["queue_names"]
            if artifacts.content_key is None:
                artifacts.content_key = p["content_key"]
            if artifacts.feed_seq is None:
                artifacts.feed_seq = p["feed_seq"]
            p["artifacts"] = artifacts

    EXPLAIN_CACHE_SIZE = 64

    def refusal_detail(self, kind: str) -> str:
        """Explain the pending refusal; returns the message suffix for
        the FailedNodes map ("" when no detail is available).  kind:
        "earlier-driver" | "fit".

        Cost control: an explain replays the queue (≤ 2 cold solves) on
        the request path, so results are memoized by (pod, kind,
        snapshot content_key) — a requeue storm of Pending pods against
        unchanged cluster state explains each refusal ONCE per state
        change, not once per retry."""
        with self._pending_lock:
            p = self._pending
            art = p["artifacts"] if p else None
        if art is None:
            return ""
        cache_key = None
        if art.content_key is not None and p is not None:
            # namespace included: same-named drivers in different
            # namespaces are different gangs with different demands
            cache_key = (
                p["namespace"], p["pod"], kind, art.content_key,
                art.memo_sig(),
            )
            with self._pending_lock:
                racecheck.note_access(self, "_explain_cache")
                hit = self._explain_cache.get(cache_key)
                if hit is not None:
                    self._explain_cache.move_to_end(cache_key)
            if hit is not None:
                info, msg = hit
                self._count_explain("refusal-cached")
                if info is not None:
                    with self._pending_lock:
                        racecheck.note_access(self, "_pending")
                        if self._pending is p:
                            p["shortfall"] = info
                    self._publish_shortfall(info)
                return msg
        if kind == "earlier-driver":
            target = art.first_blocked_earlier()
        else:
            target = art.n_earlier if art.packed.shape[0] > art.n_earlier else None
        if target is None:
            return ""
        try:
            info = explain_refusal(art, target)
        except Exception:
            logger.exception("provenance explain failed (diagnostic only)")
            info = None
        self._count_explain("refusal")
        msg = shortfall_message(info) if info is not None else ""
        if cache_key is not None:
            with self._pending_lock:
                racecheck.note_access(self, "_explain_cache")
                self._explain_cache[cache_key] = (info, msg)
                while len(self._explain_cache) > self.EXPLAIN_CACHE_SIZE:
                    self._explain_cache.popitem(last=False)
        if info is None:
            return ""
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            if self._pending is p and p is not None:
                p["shortfall"] = info
        self._publish_shortfall(info)
        return msg

    def finish_decision(
        self,
        outcome: str,
        node: str = "",
        lane: str = "",
        policy: str = "",
        instance_group: str = "",
        message: str = "",
    ) -> None:
        with self._pending_lock:
            racecheck.note_access(self, "_pending")
            p = self._pending
            self._pending = None
        if p is None:
            return
        art: Optional[SolveArtifacts] = p["artifacts"]
        bundle_seq = None
        earlier_infeasible: Tuple[int, ...] = ()
        if art is not None:
            if art.didx is not None:
                bundle_seq = self.recorder.note(art, p["pod"], outcome)
            feas = np.asarray(art.feasible, dtype=bool)[: art.n_earlier]
            earlier_infeasible = tuple(
                int(i) for i in np.flatnonzero(~feas)[:_QUEUE_SLICE]
            )
        rec = DecisionRecord(
            pod=p["pod"],
            namespace=p["namespace"],
            role=p["role"],
            instance_group=instance_group,
            trace_id=tracing.current_trace_id(),
            t=timesource.now(),
            outcome=outcome,
            node=node,
            lane=(art.lane if art is not None else lane),
            policy=policy,
            content_key=(art.content_key if art is not None else p["content_key"]),
            feed_seq=(art.feed_seq if art is not None else p["feed_seq"]),
            queue_len=len(p["queue_names"]),
            queue_slice=tuple(p["queue_names"][:_QUEUE_SLICE]),
            earlier_infeasible=earlier_infeasible,
            shortfall=p["shortfall"],
            message=message,
            bundle_seq=bundle_seq,
        )
        self.ring.record(rec)
        if self._metrics is not None:
            self._metrics.gauge(
                mnames.PROVENANCE_RECORDS, float(len(self.ring))
            )
            if p["shortfall"] is not None:
                self._metrics.histogram(
                    mnames.PROVENANCE_BLOCKERS,
                    float(p["shortfall"].blocker_count),
                )
            elif outcome == "success" and p["role"] == "driver":
                # a gang just ADMITTED: clear the shortfall gauges so a
                # resolved deficit doesn't read as permanent.  Any gang
                # still short re-asserts its shortfall on its next
                # requeue (kube-scheduler retries Pending pods
                # continuously), so the gauge converges to the truth
                # within one retry interval either way.
                for name in DIM_NAMES:
                    self._metrics.gauge(
                        mnames.PROVENANCE_SHORTFALL, 0.0, {"dim": name}
                    )

    def record_shed(self, pod) -> None:
        """An AdmissionGate shed answered this request before the
        extender ran — no begin_decision, no pending slot, no solve.
        Record the verdict directly so ``/explain`` and
        ``/debug/schedule`` can answer "why did my app not start?" for
        shed requests too (outcome ``shed``; retriable by design)."""
        if not self.enabled:
            return
        from ..scheduler import labels as L

        rec = DecisionRecord(
            pod=pod.name,
            namespace=pod.namespace,
            role=pod.labels.get(L.SPARK_ROLE_LABEL, ""),
            trace_id=tracing.current_trace_id(),
            t=timesource.now(),
            outcome="shed",
            message="admission gate shed: scheduler overloaded; retry",
        )
        self.ring.record(rec)
        if self._metrics is not None:
            self._metrics.gauge(
                mnames.PROVENANCE_RECORDS, float(len(self.ring))
            )

    # -- triggers (any thread) -----------------------------------------------

    def on_trigger(self, trigger: str, detail: str = "") -> Optional[str]:
        """A flight-recorder trigger fired: persist the bundle ring.

        Debounced per trigger type (``trigger_min_interval``): during
        the very overload that causes deadline triggers, repeated
        persists of near-identical ring state would amplify lock hold
        time and disk churn — one file per interval records the same
        forensic evidence."""
        now = timesource.now()
        with self._pending_lock:
            racecheck.note_access(self, "_last_trigger")
            last = self._last_trigger.get(trigger)
            if last is not None and now - last < self.trigger_min_interval:
                self.triggers_suppressed += 1
                return None
        try:
            path = self.recorder.persist(trigger, detail)
        except Exception:
            logger.exception("flight-recorder persist failed (trigger %s)", trigger)
            return None
        if path is not None:
            # stamp the debounce only for a persist that actually wrote:
            # an unproductive trigger (empty ring at startup, no
            # bundle_dir) must not suppress the next real one.  Two
            # concurrent same-type triggers may both persist in the
            # window — an extra file beats a missing forensic bundle.
            with self._pending_lock:
                racecheck.note_access(self, "_last_trigger")
                self._last_trigger[trigger] = now
            logger.warning(
                "flight recorder persisted %s (trigger %s: %s)",
                path, trigger, detail,
            )
        return path

    def on_parity_mismatch(self, detail: dict) -> None:
        """The engine's warm≠cold parity guard detected divergence —
        the one anomaly this subsystem exists to catch in the wild.
        ``detail`` may carry the diverging solve's artifacts (with the
        WARM verdicts recorded): noted into the recorder BEFORE
        persisting, so the bundle file contains the anomaly itself —
        replaying it cold then reproduces the divergence by
        construction, not just the decisions that preceded it."""
        self.parity_mismatches += 1
        if self._metrics is not None:
            self._metrics.counter(
                mnames.PROVENANCE_PARITY_CHECKS, {"result": "mismatch"}
            )
        artifacts = detail.pop("artifacts", None)
        if artifacts is not None:
            try:
                self.recorder.note(
                    artifacts, "parity-check", "warm-cold-parity-mismatch"
                )
            except Exception:
                logger.exception("parity artifacts could not be noted")
        self.on_trigger("warm-cold-parity", str(detail))

    def on_parity_ok(self) -> None:
        if self._metrics is not None:
            self._metrics.counter(
                mnames.PROVENANCE_PARITY_CHECKS, {"result": "ok"}
            )

    # -- read side (HTTP threads) --------------------------------------------

    def explain(self, pod_name: str, source: str = "http") -> Optional[dict]:
        self._count_explain(source)
        rec = self.ring.latest_for_pod(pod_name)
        if rec is None:
            return None
        out = rec.to_dict()
        if rec.shortfall is not None:
            out["summary"] = shortfall_message(rec.shortfall)
        return out

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "ring": self.ring.stats(),
            "recorder": self.recorder.stats(),
            "parity_mismatches": self.parity_mismatches,
        }

    # -- internals -----------------------------------------------------------

    def _count_explain(self, source: str) -> None:
        if self._metrics is not None:
            self._metrics.counter(
                mnames.PROVENANCE_EXPLAIN_COUNT, {"source": source}
            )

    def _publish_shortfall(self, info: ShortfallInfo) -> None:
        if self._metrics is None:
            return
        # per-dimension cluster shortfall: executors short when that
        # dimension alone were the constraint (0 for non-binding dims)
        for j, name in enumerate(DIM_NAMES):
            short = max(0, info.gang_size - int(info.dim_totals[j]))
            self._metrics.gauge(
                mnames.PROVENANCE_SHORTFALL, float(short), {"dim": name}
            )
