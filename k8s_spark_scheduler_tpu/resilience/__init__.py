"""Overload protection and degraded-mode operation for the scheduling
control plane.

The reference extender's one hard guarantee — a driver is admitted only
when the whole gang fits — survives crashes via reconciliation, but a
correct scheduler can still *fail open under pressure*: requests that
outlive their caller keep burning the extender lock, API-server write
failures silently drop reservation intents after bounded retries, and a
wedged device kernel lane drags every request through its timeout.  This
package is the cross-cutting resilience layer:

- :mod:`.deadline` — per-request deadline propagation (contextvar),
  checked at phase boundaries so expired requests answer fail-fast;
- :mod:`.gate` — a bounded admission gate in front of the extender lock
  that sheds excess concurrency with an immediately-retriable response;
- :mod:`.breaker` — a circuit breaker for API-server write-back;
- :mod:`.journal` — a durable JSONL intent journal that captures
  reservation writes while the breaker is open (or retries exhaust) and
  replays them idempotently on recovery and on failover;
- :mod:`.lanehealth` — per-kernel-lane failure/latency scoring with
  hysteresis, demoting xla/pallas lanes to the host/native path after
  repeated faults and re-probing after a cooloff;
- :mod:`.health` — the tri-state (ready/degraded/unready) health state
  machine behind ``/status/readiness``.

Everything is wired by :func:`build_kit` into a :class:`ResilienceKit`,
constructed once per server by ``server/wiring.py`` and threaded through
the HTTP layer, the extender, and the write-back caches.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import deadline
from .breaker import CircuitBreaker
from .gate import AdmissionGate, AdmissionShed
from .health import DEGRADED, READY, UNREADY, HealthMonitor
from .journal import IntentJournal
from .lanehealth import LaneHealth


@dataclass
class ResilienceKit:
    """The per-server resilience components, wired together."""

    gate: AdmissionGate
    breaker: CircuitBreaker
    journal: IntentJournal
    lanes: LaneHealth
    health: HealthMonitor
    # seconds a /predicates request may run before answering fail-fast;
    # derived from kube-scheduler's httpTimeout minus a safety margin so
    # the response reaches a caller that is still listening
    request_timeout: float = 29.0


def build_kit(config, metrics=None) -> ResilienceKit:
    """Construct a kit from a ``config.ResilienceConfig``."""
    gate = AdmissionGate(max_waiters=config.admission_max_waiters, metrics=metrics)
    journal = IntentJournal(path=config.journal_path, metrics=metrics)
    breaker = CircuitBreaker(
        failure_threshold=config.breaker_failure_threshold,
        cooloff_seconds=config.breaker_cooloff_seconds,
        metrics=metrics,
    )
    lanes = LaneHealth(
        failure_threshold=config.lane_failure_threshold,
        cooloff_seconds=config.lane_cooloff_seconds,
        latency_budget_seconds=config.lane_latency_budget_seconds,
        metrics=metrics,
    )
    health = HealthMonitor(
        gate=gate, breaker=breaker, journal=journal, lanes=lanes, metrics=metrics
    )
    return ResilienceKit(
        gate=gate,
        breaker=breaker,
        journal=journal,
        lanes=lanes,
        health=health,
        request_timeout=max(
            config.request_deadline_seconds - config.deadline_margin_seconds, 1.0
        ),
    )


__all__ = [
    "AdmissionGate",
    "AdmissionShed",
    "CircuitBreaker",
    "IntentJournal",
    "LaneHealth",
    "HealthMonitor",
    "ResilienceKit",
    "build_kit",
    "deadline",
    "READY",
    "DEGRADED",
    "UNREADY",
]
