"""Circuit breaker for the API-server write-back path.

The async write-back client retries each request a bounded number of
times and then *drops* it — correct for transient blips, catastrophic
during a real API-server outage: every queued reservation write burns
its retries against a dead server and the intent is lost (the local
cache then lies until the next reconcile).  The breaker turns repeated
write failures into a state the rest of the system can react to:

- ``closed``  — healthy; writes flow.
- ``open``    — ``failure_threshold`` consecutive failures seen; writes
  are diverted to the intent journal instead of burning retries.
- ``half-open`` — the cooloff elapsed; exactly one probe write is let
  through per cooloff window.  Success closes the breaker (and triggers
  journal replay); failure re-opens it.

Time flows through :func:`..timesource.now` so the simulator's virtual
clock drives cooloffs deterministically; production reads the wall
clock through the same hook.
"""

from __future__ import annotations

import threading

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


@guarded_by("_lock", "_state", "_consecutive_failures", "_opened_at", "_probe_in_flight")
class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooloff_seconds: float = 30.0,
        metrics=None,
        name: str = "writeback",
    ):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.cooloff_seconds = cooloff_seconds
        self._metrics = metrics
        self._name = name
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        # optional observer invoked (OUTSIDE the breaker lock — it may
        # do file I/O) when the breaker transitions to open; wiring
        # points it at the provenance flight recorder
        self.on_open = None

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a write be attempted now?  While open, exactly one probe
        is allowed per elapsed cooloff window (half-open)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = timesource.now()
            if (
                not self._probe_in_flight
                and now - self._opened_at >= self.cooloff_seconds
            ):
                self._set_state(HALF_OPEN)
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a previously-open
        breaker — the caller's signal to replay the intent journal."""
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._set_state(CLOSED)
                return True
            return False

    def release_probe(self) -> None:
        """A write granted by :meth:`allow` ended with neither success
        nor failure (e.g. its object was deleted while queued, so no
        request was sent).  Free the probe slot so the next write can
        probe — without this, an aborted half-open probe would wedge the
        breaker open (and the journal undrained) forever."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            racecheck.note_access(self, "_state")
            self._consecutive_failures += 1
            self._probe_in_flight = False
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._opened_at = timesource.now()
                opened = True  # this branch only runs CLOSED/HALF_OPEN → OPEN
                self._set_state(OPEN)
            elif self._state == OPEN:
                # a straggler failure while already open refreshes nothing:
                # the cooloff runs from the instant the breaker opened
                pass
        if opened and self.on_open is not None:
            try:
                self.on_open(self._name)
            except Exception:  # observers must never break the write path
                import logging

                logging.getLogger(__name__).exception(
                    "breaker on_open observer failed"
                )

    def is_open(self) -> bool:
        with self._lock:
            return self._state == OPEN

    def probe_due(self) -> bool:
        """Read-only: would :meth:`allow` admit a write right now?  Used
        by recovery nudges to decide whether re-enqueueing a journaled
        intent has any chance of landing."""
        with self._lock:
            if self._state == CLOSED:
                return True
            return (
                not self._probe_in_flight
                and timesource.now() - self._opened_at >= self.cooloff_seconds
            )

    def trip_half_open(self) -> None:
        """Make the next write attempt a probe immediately, overriding
        the cooloff — the explicit recovery signal ('the API server is
        back') from an operator drain or the simulator's fault-clear."""
        with self._lock:
            if self._state != CLOSED:
                self._opened_at = timesource.now() - self.cooloff_seconds
                self._probe_in_flight = False

    def _set_state(self, state: str) -> None:
        # caller holds the lock
        if state == self._state:
            return
        self._state = state  # schedlint: disable=LK001 -- private helper, every caller holds _lock (see callers)
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.gauge(
                mnames.RESILIENCE_BREAKER_STATE,
                _STATE_VALUE[state],
                {"breaker": self._name},
            )
            self._metrics.counter(
                mnames.RESILIENCE_BREAKER_TRANSITIONS,
                {"breaker": self._name, "to": state},
            )
