"""Per-request deadline propagation.

kube-scheduler gives the extender a hard ``httpTimeout`` (30s in
``examples/extender.yml``); past it the Filter call has already failed
on the caller's side and any work we keep doing for it — most damagingly
holding the single extender lock — is pure overload amplification.  The
HTTP layer binds a deadline into a contextvar at request entry; the
extender checks it at phase boundaries (predicate entry → FIFO gate →
binpack → reservation write-back) and answers fail-fast once expired.

Deadlines ride the *real* monotonic clock, never the (possibly virtual,
frozen) :mod:`..timesource`: they bound wall latency as the HTTP caller
experiences it, and a simulator's frozen clock must never turn a bounded
request into an unbounded one (or spuriously expire one).

The no-deadline fast path — background threads, tests, the simulator
calling ``predicate`` directly — is one contextvar read.
"""

from __future__ import annotations

import contextlib
import time
from contextvars import ContextVar
from typing import Iterator, Optional

# absolute time.monotonic() instant the current request expires at
_deadline: ContextVar[Optional[float]] = ContextVar("request_deadline", default=None)


class DeadlineExceeded(Exception):
    """The request outlived its caller's timeout."""

    def __init__(self, phase: str, overrun_s: float):
        super().__init__(
            f"request deadline expired {overrun_s * 1000.0:.0f}ms ago at {phase}"
        )
        self.phase = phase
        self.overrun_s = overrun_s


@contextlib.contextmanager
def bind(timeout_s: Optional[float]) -> Iterator[None]:
    """Bind a deadline ``timeout_s`` from now for the enclosed work.
    ``None`` binds nothing (and clears any inherited deadline)."""
    token = _deadline.set(
        time.monotonic() + timeout_s if timeout_s is not None else None
    )
    try:
        yield
    finally:
        _deadline.reset(token)


def remaining() -> Optional[float]:
    """Seconds until the bound deadline (may be negative), or None when
    no deadline is bound."""
    at = _deadline.get()
    if at is None:
        return None
    return at - time.monotonic()


def expired() -> bool:
    at = _deadline.get()
    return at is not None and time.monotonic() >= at


def check(phase: str) -> None:
    """Raise :class:`DeadlineExceeded` when the bound deadline passed."""
    at = _deadline.get()
    if at is not None:
        now = time.monotonic()
        if now >= at:
            raise DeadlineExceeded(phase, now - at)
