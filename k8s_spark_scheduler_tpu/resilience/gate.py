"""Admission gate: bounded load shedding in front of the extender lock.

``ThreadingHTTPServer`` spawns a thread per connection; the extender
serializes every ``/predicates`` decision behind one lock.  Under a
request burst (kube-scheduler retry storm, a second scheduler instance
misrouted, a probe loop gone wild) threads pile up on that lock without
bound — each one holding a socket, a stack, and a caller that has long
since timed out.  The gate caps how many requests may sit in front of
the lock; excess requests are *shed* immediately with a retriable
failure instead of queueing, so the server's decision latency for the
admitted requests stays bounded and shed callers learn to back off in
milliseconds rather than at their own timeout.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..analysis import racecheck
from ..analysis.guarded import guarded_by


class AdmissionShed(Exception):
    """Request shed by the admission gate; immediately retriable."""


@guarded_by("_lock", "_in_flight", "_shed_total", "_last_shed_monotonic")
class AdmissionGate:
    def __init__(self, max_waiters: int = 16, metrics=None):
        # max_waiters counts every admitted-but-unfinished request: the
        # one holding the extender lock plus those queued behind it
        self.max_waiters = max(int(max_waiters), 1)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._in_flight = 0
        self._shed_total = 0
        self._last_shed_monotonic: Optional[float] = None

    # -- admission -----------------------------------------------------------

    def try_enter(self) -> bool:
        """Admit the caller, or return False (shed) when the wait queue
        is full.  Never blocks."""
        with self._lock:
            racecheck.note_access(self, "_in_flight")
            if self._in_flight >= self.max_waiters:
                self._shed_total += 1
                self._last_shed_monotonic = time.monotonic()
                if self._metrics is not None:
                    from ..metrics import names as mnames

                    self._metrics.counter(mnames.RESILIENCE_SHED_COUNT)
                return False
            self._in_flight += 1
            return True

    def leave(self) -> None:
        with self._lock:
            racecheck.note_access(self, "_in_flight")
            self._in_flight = max(self._in_flight - 1, 0)

    def admit(self) -> "_Admission":
        """Context manager: raises :class:`AdmissionShed` when full."""
        if not self.try_enter():
            raise AdmissionShed(
                f"admission gate full ({self.max_waiters} requests in flight)"
            )
        return _Admission(self)

    # -- introspection -------------------------------------------------------

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    @property
    def shed_total(self) -> int:
        with self._lock:
            return self._shed_total

    def shed_recently(self, window_s: float = 30.0) -> bool:
        """True when a request was shed within the last ``window_s``
        real seconds — the health monitor's overload signal."""
        with self._lock:
            last = self._last_shed_monotonic
        return last is not None and (time.monotonic() - last) < window_s


class _Admission:
    def __init__(self, gate: AdmissionGate):
        self._gate = gate

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._gate.leave()
        return False
