"""Tri-state health: ready / degraded / unready.

The readiness probe's old boolean answer hid the most operationally
interesting state: *serving, but in a degraded mode* — writes diverted
to the journal, a kernel lane demoted, the admission gate actively
shedding.  Kubernetes must NOT pull a degraded replica out of rotation
(it is still making correct decisions; pulling it would turn overload
into an outage), but operators need to see it.  So:

- ``ready``    — everything healthy; probe answers 200.
- ``degraded`` — serving with reduced machinery; probe answers 200 with
  the component breakdown in the body (and the metrics gauge flips).
- ``unready``  — not serving (caches unsynced, warmup incomplete);
  probe answers 503.  The unready inputs live in the HTTP layer (they
  gate on server wiring state); this monitor owns the ready/degraded
  distinction.
"""

from __future__ import annotations

READY = "ready"
DEGRADED = "degraded"
UNREADY = "unready"

_STATE_VALUE = {READY: 0.0, DEGRADED: 1.0, UNREADY: 2.0}


class HealthMonitor:
    def __init__(self, gate, breaker, journal, lanes, metrics=None):
        self._gate = gate
        self._breaker = breaker
        self._journal = journal
        self._lanes = lanes
        self._metrics = metrics

    def state(self, serving: bool = True) -> str:
        """Current health state; ``serving=False`` (caches unsynced /
        warmup incomplete) forces ``unready``."""
        state = UNREADY if not serving else self._degraded_or_ready()
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.gauge(mnames.RESILIENCE_HEALTH_STATE, _STATE_VALUE[state])
        return state

    def _degraded_or_ready(self) -> str:
        if self._breaker.state != "closed":
            return DEGRADED
        if self._journal.depth() > 0:
            return DEGRADED
        if self._lanes.demoted_lanes():
            return DEGRADED
        if self._gate.shed_recently():
            return DEGRADED
        return READY

    def report(self, serving: bool = True) -> dict:
        """The /status/readiness body: state plus per-component detail."""
        return {
            "state": self.state(serving),
            "components": {
                "writebackBreaker": self._breaker.state,
                "journalDepth": self._journal.depth(),
                "demotedLanes": self._lanes.demoted_lanes(),
                "admissionInFlight": self._gate.in_flight,
                "shedTotal": self._gate.shed_total,
                "shedRecently": self._gate.shed_recently(),
            },
        }
