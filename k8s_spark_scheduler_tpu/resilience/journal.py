"""Durable intent journal for diverted write-back requests.

When the write-back circuit breaker opens (or a request exhausts its
retries), the reservation write is *diverted* here instead of being
dropped: the intent — operation, key, and the object's wire form — is
appended to a framed JSONL file (or kept in memory when no path is
configured) and replayed idempotently once the API server recovers, or
by the next scheduler instance on failover.

File format: one framed record per line, append-only while running::

    f1 <crc32 hex8> <payload bytes> <payload json>

- the payload ``{"a": "put", "seq": N, "op": "create|update|delete",
  "kind": …, "ns": …, "name": …, "obj": {…wire…}}`` is a pending
  intent; the latest put per (ns, name) wins (an app created then
  deleted during an outage nets out to the delete);
- ``{"a": "ack", "seq": N}`` — the intent landed at the API server;
- bare ``{…}`` lines (the pre-framing format) still load, so a journal
  written by an older build replays across an upgrade-failover.

Recovery verifies each frame's length and CRC32; the first bad record
marks a **torn tail** — the process died mid-append — and everything
from that point is truncated with a warning (and counted) instead of
feeding half a record to ``json.loads``.  Loading compacts; while
running, the journal re-compacts opportunistically on the ack path once
acked records exceed a configurable fraction of the file, so journals
stop growing unbounded across failovers.

When a fencing gate is installed (HA wiring), acks are **fenced**: a
deposed leader cannot ack an intent out from under the successor that
will replay it.  Put records are stamped with the writer's fencing
epoch for the post-failover audit trail.

Exactly-once at the CRD level comes from replaying through the
idempotent write path (create → AlreadyExists folds the server copy;
delete → NotFound is success), not from the journal itself.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Dict, List, Optional, Set, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..ha import crashpoint

logger = logging.getLogger(__name__)

Key = Tuple[str, str]  # (namespace, name)

FRAME_MAGIC = "f1"

# create/update collapse to one ack class: both assert "the store's
# content for this key is now at the server", and the queue already
# dedupes them per key
_UPSERT = "upsert"


def _op_class(op: str) -> str:
    return "delete" if op == "delete" else _UPSERT


def _frame(payload: str) -> str:
    raw = payload.encode("utf-8")
    return f"{FRAME_MAGIC} {zlib.crc32(raw):08x} {len(raw)} {payload}\n"


def _unframe(line: str) -> Optional[dict]:
    """Parse one framed (or legacy bare-JSON) line; None = corrupt."""
    if line.startswith(FRAME_MAGIC + " "):
        parts = line.split(" ", 3)
        if len(parts) != 4:
            return None
        _, crc_hex, length, payload = parts
        raw = payload.encode("utf-8")
        try:
            if len(raw) != int(length) or zlib.crc32(raw) != int(crc_hex, 16):
                return None
        except ValueError:
            return None
        try:
            return json.loads(payload)
        except json.JSONDecodeError:
            return None
    if line.startswith("{"):  # legacy unframed record
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            return None
    return None


@guarded_by("_lock", "_pending", "_seq", "_fh", "_file_records")
class IntentJournal:
    def __init__(
        self,
        path: Optional[str] = None,
        metrics=None,
        compact_fraction: float = 0.5,
        compact_min_records: int = 64,
    ):
        self._path = path
        self._metrics = metrics
        self._compact_fraction = compact_fraction
        self._compact_min_records = compact_min_records
        self._lock = threading.Lock()
        # persist→replay happens-before channel; a process-unique token
        # so a recycled object id can never alias journals
        self._hb_key = ("journal", racecheck.channel_token())
        self._seq = 0
        # key → intent dict (latest wins)
        self._pending: Dict[Key, dict] = {}
        self._fh = None
        # records in the file since the last rewrite (puts + acks);
        # drives the acked-fraction compaction trigger
        self._file_records = 0
        # HA hooks, installed by server wiring when the fabric is on:
        # epoch_source stamps put/ack records, fence_gate refuses acks
        # from a deposed leader (ha/fencing.FencedWriter)
        self.epoch_source = None
        self.fence_gate = None
        if path:
            self._load()

    # -- persistence ---------------------------------------------------------

    def _load(self) -> None:
        pending: Dict[Key, dict] = {}
        by_seq: Dict[int, Key] = {}
        max_seq = 0
        torn = False
        if os.path.exists(self._path):
            with open(self._path) as f:
                lines = f.readlines()
            for i, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                rec = _unframe(line)
                if rec is None:
                    # torn tail: the process died mid-append.  Recovery
                    # keeps the good prefix and drops everything from
                    # the first bad record — trailing bytes after a torn
                    # frame are unordered garbage, not intents.
                    dropped = len(lines) - i
                    logger.warning(
                        "journal %s: torn tail at record %d — truncating "
                        "%d trailing line(s)",
                        self._path,
                        i,
                        dropped,
                    )
                    torn = True
                    break
                seq = int(rec.get("seq", 0))
                max_seq = max(max_seq, seq)
                if rec.get("a") == "put":
                    key = (rec.get("ns", ""), rec.get("name", ""))
                    pending[key] = rec
                    by_seq[seq] = key
                elif rec.get("a") == "ack":
                    key = by_seq.get(seq)
                    if key is not None and pending.get(key, {}).get("seq") == seq:
                        pending.pop(key, None)
        # under the lock even though _load only runs from __init__: the
        # lock is the declared guard for this state and holding it here
        # keeps the discipline uniform
        with self._lock:
            self._pending = pending
            self._seq = max_seq
            # compact: rewrite only the still-pending intents so the file
            # doesn't grow across restarts (this also truncates any torn
            # tail — the rewrite persists exactly the verified prefix
            # state)
            self._rewrite_locked()
            self._report_depth()
        if torn and self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.RESILIENCE_JOURNAL_TORN_TAIL)

    def _rewrite_locked(self) -> None:
        """Rewrite the file to pending-only records (caller holds lock)."""
        if self._fh is not None:
            self._fh.close()
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            for rec in self._pending.values():
                f.write(_frame(json.dumps(rec, sort_keys=True)))
        os.replace(tmp, self._path)
        self._fh = open(self._path, "a")  # schedlint: disable=LK001 -- _rewrite_locked is only called with _lock held (see callers)
        self._file_records = len(self._pending)  # schedlint: disable=LK001 -- _rewrite_locked is only called with _lock held (see callers)

    def _append_line(self, rec: dict) -> None:
        if self._fh is not None:
            self._fh.write(_frame(json.dumps(rec, sort_keys=True)))
            self._fh.flush()
            self._file_records += 1  # schedlint: disable=LK001 -- _append_line is only called with _lock held (see callers)

    def _maybe_compact_locked(self) -> None:
        """Opportunistic compaction on the ack path (async worker
        threads — off the decision path): once acked records exceed the
        configured fraction of the file, rewrite pending-only."""
        if self._fh is None or self._file_records < self._compact_min_records:
            return
        # every file record beyond the live pending set is an acked put,
        # a superseded put, or an ack marker — all dead weight
        dead = self._file_records - len(self._pending)
        if dead / self._file_records < self._compact_fraction:
            return
        self._rewrite_locked()
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.RESILIENCE_JOURNAL_COMPACTIONS)

    # -- recording -----------------------------------------------------------

    def record(
        self, op: str, kind: str, namespace: str, name: str, obj_wire: Optional[dict]
    ) -> None:
        """Divert one write intent (latest-wins per key)."""
        crashpoint.maybe_crash(crashpoint.JOURNAL_PRE_APPEND)
        epoch_source = self.epoch_source
        with self._lock:
            racecheck.note_access(self, "_pending")
            self._seq += 1
            rec = {
                "a": "put",
                "seq": self._seq,
                "op": op,
                "kind": kind,
                "ns": namespace,
                "name": name,
                "obj": obj_wire,
            }
            if epoch_source is not None:
                rec["epoch"] = epoch_source()
            self._pending[(namespace, name)] = rec
            self._append_line(rec)
            # persist → replay edge: the recovery loop that reads
            # pending() is ordered after everything recorded here, even
            # when it synchronizes through the file rather than a lock
            racecheck.hb_publish(self._hb_key)
            self._report_depth()
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.counter(
                    mnames.RESILIENCE_JOURNAL_APPENDED, {"op": op, "kind": kind}
                )
        crashpoint.maybe_crash(crashpoint.JOURNAL_POST_APPEND)

    def ack(self, op: str, namespace: str, name: str) -> bool:
        """Mark the pending intent for a key as landed.  Only acks when
        the landed operation's class matches the pending intent's (an
        upsert landing must not ack a newer pending delete).  Fenced
        when HA is wired: a deposed leader's ack would erase an intent
        the successor is about to replay."""
        gate = self.fence_gate
        if gate is not None:
            gate.check("journal.ack")  # raises StaleEpochError when deposed
        crashpoint.maybe_crash(crashpoint.JOURNAL_PRE_ACK)
        epoch_source = self.epoch_source
        with self._lock:
            racecheck.note_access(self, "_pending")
            key = (namespace, name)
            rec = self._pending.get(key)
            if rec is None or _op_class(rec["op"]) != _op_class(op):
                return False
            del self._pending[key]
            ack_rec: dict = {"a": "ack", "seq": rec["seq"]}
            if epoch_source is not None:
                ack_rec["epoch"] = epoch_source()
            self._append_line(ack_rec)
            self._report_depth()
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.counter(mnames.RESILIENCE_JOURNAL_REPLAYED)
            self._maybe_compact_locked()
        crashpoint.maybe_crash(crashpoint.JOURNAL_POST_ACK)
        return True

    # -- introspection -------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def file_records(self) -> int:
        with self._lock:
            return self._file_records

    def pending(self) -> List[dict]:
        """Copies of pending intents in seq order."""
        racecheck.hb_observe(self._hb_key)
        with self._lock:
            return sorted((dict(r) for r in self._pending.values()), key=lambda r: r["seq"])

    def pending_keys(self) -> Set[Key]:
        with self._lock:
            return set(self._pending)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def _report_depth(self) -> None:
        # caller holds the lock
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.gauge(
                mnames.RESILIENCE_JOURNAL_DEPTH, float(len(self._pending))
            )
