"""Durable intent journal for diverted write-back requests.

When the write-back circuit breaker opens (or a request exhausts its
retries), the reservation write is *diverted* here instead of being
dropped: the intent — operation, key, and the object's wire form — is
appended to a JSONL file (or kept in memory when no path is configured)
and replayed idempotently once the API server recovers, or by the next
scheduler instance on failover.

File format: one JSON object per line, append-only while running.

- ``{"a": "put", "seq": N, "op": "create|update|delete", "kind": …,
  "ns": …, "name": …, "obj": {…wire…}}`` — a pending intent; the latest
  put per (ns, name) wins (an app created then deleted during an outage
  nets out to the delete).
- ``{"a": "ack", "seq": N}`` — the intent landed at the API server.

Loading compacts: pending intents are puts without an ack, newest per
key.  Exactly-once at the CRD level comes from replaying through the
idempotent write path (create → AlreadyExists folds the server copy;
delete → NotFound is success), not from the journal itself.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)

Key = Tuple[str, str]  # (namespace, name)

# create/update collapse to one ack class: both assert "the store's
# content for this key is now at the server", and the queue already
# dedupes them per key
_UPSERT = "upsert"


def _op_class(op: str) -> str:
    return "delete" if op == "delete" else _UPSERT


@guarded_by("_lock", "_pending", "_seq", "_fh")
class IntentJournal:
    def __init__(self, path: Optional[str] = None, metrics=None):
        self._path = path
        self._metrics = metrics
        self._lock = threading.Lock()
        # persist→replay happens-before channel; a process-unique token
        # so a recycled object id can never alias journals
        self._hb_key = ("journal", racecheck.channel_token())
        self._seq = 0
        # key → intent dict (latest wins)
        self._pending: Dict[Key, dict] = {}
        self._fh = None
        if path:
            self._load()

    # -- persistence ---------------------------------------------------------

    def _load(self) -> None:
        pending: Dict[Key, dict] = {}
        by_seq: Dict[int, Key] = {}
        max_seq = 0
        if os.path.exists(self._path):
            with open(self._path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        logger.warning("skipping corrupt journal line")
                        continue
                    seq = int(rec.get("seq", 0))
                    max_seq = max(max_seq, seq)
                    if rec.get("a") == "put":
                        key = (rec.get("ns", ""), rec.get("name", ""))
                        pending[key] = rec
                        by_seq[seq] = key
                    elif rec.get("a") == "ack":
                        key = by_seq.get(seq)
                        if key is not None and pending.get(key, {}).get("seq") == seq:
                            pending.pop(key, None)
        # under the lock even though _load only runs from __init__: the
        # lock is the declared guard for this state and holding it here
        # keeps the discipline uniform
        with self._lock:
            self._pending = pending
            self._seq = max_seq
            # compact: rewrite only the still-pending intents so the file
            # doesn't grow across restarts
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                for rec in pending.values():
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
            os.replace(tmp, self._path)
            self._fh = open(self._path, "a")
            self._report_depth()

    def _append_line(self, rec: dict) -> None:
        if self._fh is not None:
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()

    # -- recording -----------------------------------------------------------

    def record(
        self, op: str, kind: str, namespace: str, name: str, obj_wire: Optional[dict]
    ) -> None:
        """Divert one write intent (latest-wins per key)."""
        with self._lock:
            racecheck.note_access(self, "_pending")
            self._seq += 1
            rec = {
                "a": "put",
                "seq": self._seq,
                "op": op,
                "kind": kind,
                "ns": namespace,
                "name": name,
                "obj": obj_wire,
            }
            self._pending[(namespace, name)] = rec
            self._append_line(rec)
            # persist → replay edge: the recovery loop that reads
            # pending() is ordered after everything recorded here, even
            # when it synchronizes through the file rather than a lock
            racecheck.hb_publish(self._hb_key)
            self._report_depth()
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.counter(
                    mnames.RESILIENCE_JOURNAL_APPENDED, {"op": op, "kind": kind}
                )

    def ack(self, op: str, namespace: str, name: str) -> bool:
        """Mark the pending intent for a key as landed.  Only acks when
        the landed operation's class matches the pending intent's (an
        upsert landing must not ack a newer pending delete)."""
        with self._lock:
            racecheck.note_access(self, "_pending")
            key = (namespace, name)
            rec = self._pending.get(key)
            if rec is None or _op_class(rec["op"]) != _op_class(op):
                return False
            del self._pending[key]
            self._append_line({"a": "ack", "seq": rec["seq"]})
            self._report_depth()
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.counter(mnames.RESILIENCE_JOURNAL_REPLAYED)
            return True

    # -- introspection -------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def pending(self) -> List[dict]:
        """Copies of pending intents in seq order."""
        racecheck.hb_observe(self._hb_key)
        with self._lock:
            return sorted((dict(r) for r in self._pending.values()), key=lambda r: r["seq"])

    def pending_keys(self) -> Set[Key]:
        with self._lock:
            return set(self._pending)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def _report_depth(self) -> None:
        # caller holds the lock
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.gauge(
                mnames.RESILIENCE_JOURNAL_DEPTH, float(len(self._pending))
            )
