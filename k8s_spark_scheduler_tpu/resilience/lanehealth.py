"""Per-kernel-lane health tracking with hysteresis.

The extender's device lanes (tensor-snapshot driver path, device FIFO
queue solve, tensor executor reschedule) each fall back to the exact
host path on any exception — silently, *per request*.  A wedged xla or
pallas lane therefore taxes every request with a doomed attempt (and
its timeout / compiler stall) forever.  This tracker scores each lane:

- ``failure_threshold`` consecutive failures — or successes slower than
  ``latency_budget_seconds`` (a deadline blowout is as bad as a fault) —
  **demote** the lane: the extender skips it entirely and dispatches the
  host/native path directly;
- after ``cooloff_seconds`` one request is allowed to **re-probe** the
  demoted lane; success promotes it back, failure restarts the cooloff.

Hysteresis means a single hiccup never flaps the lane, and a demoted
lane never costs more than one probe per cooloff.  Time flows through
:func:`..timesource.now` (virtual in the simulator, wall in prod).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)

HEALTHY = "healthy"
DEMOTED = "demoted"

_STATE_VALUE = {HEALTHY: 0.0, DEMOTED: 1.0}


class _Lane:
    __slots__ = ("state", "consecutive_failures", "demoted_at", "probe_in_flight")

    def __init__(self):
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.demoted_at = 0.0
        self.probe_in_flight = False


@guarded_by("_lock", "_lanes")
class LaneHealth:
    def __init__(
        self,
        failure_threshold: int = 3,
        cooloff_seconds: float = 60.0,
        latency_budget_seconds: Optional[float] = 5.0,
        metrics=None,
    ):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.cooloff_seconds = cooloff_seconds
        self.latency_budget_seconds = latency_budget_seconds
        self._metrics = metrics
        self._lock = threading.Lock()
        self._lanes: Dict[str, _Lane] = {}

    def _lane(self, name: str) -> _Lane:
        racecheck.note_access(self, "_lanes")
        lane = self._lanes.get(name)
        if lane is None:
            lane = self._lanes[name] = _Lane()  # schedlint: disable=LK001 -- private helper, every caller holds _lock
        return lane

    # -- dispatch-side -------------------------------------------------------

    def allow(self, name: str) -> bool:
        """Should the extender attempt this lane?  Demoted lanes admit
        one re-probe per elapsed cooloff."""
        with self._lock:
            lane = self._lane(name)
            if lane.state == HEALTHY:
                return True
            if (
                not lane.probe_in_flight
                and timesource.now() - lane.demoted_at >= self.cooloff_seconds
            ):
                lane.probe_in_flight = True
                return True
            return False

    def record_success(self, name: str, duration_s: Optional[float] = None) -> None:
        budget = self.latency_budget_seconds
        if budget is not None and duration_s is not None and duration_s > budget:
            # a deadline blowout counts against the lane even though the
            # result was usable — the NEXT caller shouldn't pay it again
            self.record_failure(name, reason="latency")
            return
        with self._lock:
            lane = self._lane(name)
            lane.consecutive_failures = 0
            lane.probe_in_flight = False
            if lane.state == DEMOTED:
                self._set_state(name, lane, HEALTHY)
                logger.info("kernel lane %s re-promoted after successful probe", name)

    def release_probe(self, name: str) -> None:
        """The attempt ended neutrally — the lane declined the work
        (unsupported shape, inexact snapshot) rather than succeeding or
        failing.  Free the probe slot so the next request may probe;
        without this a demoted lane whose re-probe hit an unsupported
        request would stay demoted forever."""
        with self._lock:
            self._lane(name).probe_in_flight = False

    def record_failure(self, name: str, reason: str = "error") -> None:
        with self._lock:
            lane = self._lane(name)
            lane.consecutive_failures += 1
            if lane.state == DEMOTED:
                # failed probe: restart the cooloff
                lane.demoted_at = timesource.now()
                lane.probe_in_flight = False
                return
            if lane.consecutive_failures >= self.failure_threshold:
                lane.demoted_at = timesource.now()
                lane.probe_in_flight = False
                self._set_state(name, lane, DEMOTED)
                logger.warning(
                    "kernel lane %s demoted after %d consecutive %s failures; "
                    "re-probing after %.0fs",
                    name,
                    lane.consecutive_failures,
                    reason,
                    self.cooloff_seconds,
                )
                if self._metrics is not None:
                    from ..metrics import names as mnames

                    self._metrics.counter(
                        mnames.RESILIENCE_LANE_DEMOTIONS,
                        {"lane": name, "reason": reason},
                    )

    # -- introspection -------------------------------------------------------

    def demoted_lanes(self) -> List[str]:
        with self._lock:
            return sorted(n for n, l in self._lanes.items() if l.state == DEMOTED)

    def state_of(self, name: str) -> str:
        with self._lock:
            return self._lane(name).state

    def _set_state(self, name: str, lane: _Lane, state: str) -> None:
        # caller holds the lock
        lane.state = state
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.gauge(
                mnames.RESILIENCE_LANE_STATE, _STATE_VALUE[state], {"lane": name}
            )
