"""DemandGC (reference ``internal/extender/demand_gc.go``): deletes a
pod's Demand when the pod gets scheduled, covering race windows the
inline deletions miss."""

from __future__ import annotations

from ..demands.manager import DemandManager
from ..kube.informer import Informer
from . import labels as L


def start_demand_gc(pod_informer: Informer, manager: DemandManager) -> None:
    """demand_gc.go:35-55."""

    def on_update(old, new):
        if L.on_pod_scheduled(old, new):
            manager.delete_demand_if_exists(new, "DemandGC")

    pod_informer.add_event_handler(
        on_update=on_update,
        filter_func=L.is_spark_scheduler_pod,
    )
