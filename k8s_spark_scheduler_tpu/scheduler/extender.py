"""SparkSchedulerExtender: the gang-scheduling Filter implementation
(reference ``internal/extender/resource.go``).

Per-request flow: reconcile-if-idle → DA compaction → role dispatch.
Drivers: idempotent replay, node-affinity filtering, availability
snapshot, AZ-aware sort, FIFO earlier-drivers pass, gang binpack,
demand create/delete, reservation creation.  Executors: bound-
reservation replay, unbound rebinding, rescheduling with optional
single-AZ confinement, soft-reservation consumption.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .. import compat
from .. import timesource
from ..capacity import enter_predicate_lock, exit_predicate_lock
from ..config import FifoConfig
from ..contention.locktime import TimedLock
from ..tracing import spans as tracing
from ..demands.manager import DemandManager
from ..events import events as ev
from ..kube.informer import Informer
from ..metrics import names as mnames
from ..metrics.registry import MetricsRegistry, default_registry
from ..ops import capacity as cap
from ..ops.efficiency import compute_avg_packing_efficiency
from ..ops.nodesort import NodeSorter
from ..ops.registry import SINGLE_AZ_MINIMAL_FRAGMENTATION, Binpacker, check_kernel_fault
from ..resilience import deadline as req_deadline
from ..types.extenderapi import ExtenderArgs, ExtenderFilterResult
from ..types.objects import Node, Pod
from ..types.resources import (
    ZONE_LABEL,
    available_for_nodes,
    node_scheduling_metadata_for_nodes,
    subtract_usage_if_exists,
)
from . import labels as L
from .overhead import OverheadComputer
from .reservations_manager import DRIVER_RESERVATION_NAME, ResourceReservationManager
from .sparkpods import (
    AnnotationError,
    SparkPodLister,
    spark_resource_usage,
    spark_app_demand_cached,
    spark_resources,
    spark_resources_cached,
)

logger = logging.getLogger(__name__)

# outcome constants (resource.go:46-60)
FAILURE_UNBOUND = "failure-unbound"
FAILURE_INTERNAL = "failure-internal"
FAILURE_FIT = "failure-fit"
FAILURE_EARLIER_DRIVER = "failure-earlier-driver"
FAILURE_NON_SPARK_POD = "failure-non-spark-pod"
# the request outlived its caller's httpTimeout: answer fail-fast so the
# extender lock serves callers that are still listening (retriable — the
# next kube-scheduler attempt gets a fresh deadline)
FAILURE_DEADLINE = "failure-deadline-exceeded"
SUCCESS = "success"
SUCCESS_RESCHEDULED = "success-rescheduled"
SUCCESS_ALREADY_BOUND = "success-already-bound"
SUCCESS_SCHEDULED_EXTRA_EXECUTOR = "success-scheduled-extra-executor"

SUCCESS_OUTCOMES = {
    SUCCESS,
    SUCCESS_ALREADY_BOUND,
    SUCCESS_RESCHEDULED,
    SUCCESS_SCHEDULED_EXTRA_EXECUTOR,
}

# reconciliation trigger: default LeaseDuration for core clients
# (resource.go:57-59)
LEADER_ELECTION_INTERVAL_SECONDS = 15.0


class SchedulingFailure(Exception):
    def __init__(self, outcome: str, message: str):
        super().__init__(message)
        self.outcome = outcome


class SparkSchedulerExtender:  # schedlint: disable=LK004 -- _predicate_lock serializes the whole decision path; it guards the flow, not a field set (see ROADMAP-1)
    def __init__(
        self,
        node_informer: Informer,
        pod_lister: SparkPodLister,
        resource_reservation_cache,
        soft_reservation_store,
        resource_reservation_manager: ResourceReservationManager,
        demands_manager: DemandManager,
        is_fifo: bool,
        fifo_config: FifoConfig,
        binpacker: Binpacker,
        should_schedule_dynamically_allocated_executors_in_same_az: bool,
        overhead_computer: OverheadComputer,
        instance_group_label: str,
        node_sorter: NodeSorter,
        metrics: MetricsRegistry | None = None,
        event_log: Optional[ev.EventLog] = None,
        waste_reporter=None,
        tensor_snapshot_cache=None,
        strict_reference_parity: bool = compat.DEFAULT_STRICT,
        tracer: Optional[tracing.Tracer] = None,
        resilience=None,
        delta_solve: bool = True,
        provenance=None,
        policy=None,
    ):
        self._node_informer = node_informer
        self._pod_lister = pod_lister
        self._resource_reservations = resource_reservation_cache
        self._soft_reservation_store = soft_reservation_store
        self._rrm = resource_reservation_manager
        self._demands = demands_manager
        self._is_fifo = is_fifo
        self._fifo_config = fifo_config
        self.binpacker = binpacker
        self._single_az_da = should_schedule_dynamically_allocated_executors_in_same_az
        self._overhead = overhead_computer
        self._instance_group_label = instance_group_label
        self._node_sorter = node_sorter
        self._metrics = metrics or default_registry
        self._event_log = event_log
        self._tracer = tracer if tracer is not None else tracing.default_tracer
        self._waste_reporter = waste_reporter
        # event-driven integer snapshot for the driver fast path; the
        # fast lexsort replicates the NodeSorter ordering including any
        # configured per-role label-priority re-sort
        self._tensor_snapshot = tensor_snapshot_cache
        # kube-scheduler serializes Filter calls per scheduler instance
        # (SURVEY §2.10); the reference's state (lastRequest, the
        # reconcile-then-pack flow) relies on that — enforce it here so a
        # threaded HTTP front end can't interleave predicates.  The
        # TimedLock wrapper (contention/locktime.py) measures every
        # acquire — this is THE lock ROADMAP-1 wants to break, so it
        # records unsampled and stamps lockWaitMs on the request span
        # for the critical-path decomposition.
        self._predicate_lock = TimedLock(
            threading.Lock(), "extender.predicate", sample_every=1, tag_waits=True
        )
        self._fast_path_ok = tensor_snapshot_cache is not None
        # incremental delta-solve engine (ops/deltasolve.py): persistent
        # native solver sessions + prefix-feasibility reuse for the
        # earlier-drivers pass.  None when disabled or when there is no
        # tensor mirror to key invalidation on; the engine itself
        # declines (returns None) per request when it can't serve
        # exactly, so construction is cheap and unconditional otherwise.
        self.delta_engine = None
        if delta_solve and tensor_snapshot_cache is not None:
            from ..ops.deltasolve import DeltaSolveEngine

            self.delta_engine = DeltaSolveEngine(metrics=self._metrics)
        self._strict_reference_parity = strict_reference_parity
        self._resilience = resilience
        self._lane_health = resilience.lanes if resilience is not None else None
        # decision provenance (provenance/tracker.py): None or disabled
        # keeps every capture sink None — the solver lanes then run with
        # zero provenance work (the perf guard pins this)
        self._provenance = provenance
        # scheduling-policy engine (policy/engine.py): None (the
        # default) keeps every hook a single attribute check — the
        # Filter path is then byte-identical to pre-policy behavior
        # (the perf guard + 5-seed identity test pin this)
        self._policy = policy
        if provenance is not None and provenance.enabled:
            solver = getattr(binpacker, "queue_solver", None)
            if solver is not None and hasattr(solver, "capture_sink"):
                solver.capture_sink = provenance.capture
            if self.delta_engine is not None:
                self.delta_engine.capture_sink = provenance.capture
        self._last_request = 0.0
        # diagnostics: which lane served the last executor reschedule
        self.last_reschedule_path: Optional[str] = None
        # HA fabric hook (server/wiring.py): the fencing-epoch reader,
        # so every decision trace carries the epoch it was served under
        # — post-mortems can attribute a decision to a leadership term.
        # None (the default / single-replica) costs one attribute check.
        self.epoch_source: Optional[Callable[[], int]] = None
        # SLO engine hook (server/wiring.py): reads the precomputed
        # alert-tag string (e.g. "eviction_waste:page") so decision
        # traces made during an SLO burn carry that context.  The value
        # is computed at ledger drain time, never on this path.
        self.slo_alert_source: Optional[Callable[[], str]] = None
        # concurrent admission hook (concurrent/engine.py): installed
        # for exactly one predicate call at a time (the commit gate
        # serializes commits), consulted on the driver fast path with
        # the commit-time basis — returns (outcome, zones) when the
        # speculative verdict revalidates, None to run the normal solve.
        # None (the default / serial operation) costs one attribute read.
        self.speculation_intake = None

    # -- entry point ---------------------------------------------------------

    def predicate(self, args: ExtenderArgs) -> ExtenderFilterResult:
        """resource.go:128-183."""
        with self._predicate_lock:
            # mark lock tenure in the thread-local the capacity sampler
            # checks: a probe invoked from inside a decision would
            # stretch lock hold time, so the sampler refuses it
            enter_predicate_lock()
            try:
                # one span per scheduling decision; role/instanceGroup/
                # outcome/node tags land via add_tag as they are
                # computed.  Becomes the trace root when called outside
                # the HTTP layer.
                with self._tracer.span(
                    "predicate",
                    {"pod": args.pod.name, "namespace": args.pod.namespace},
                ):
                    if self.epoch_source is not None:
                        tracing.add_tag("epoch", self.epoch_source())
                    if self.slo_alert_source is not None:
                        alert = self.slo_alert_source()
                        if alert:
                            tracing.add_tag("sloAlert", alert)
                    # the request may have queued behind slow decisions
                    # for its whole deadline; answer fail-fast rather
                    # than spend the lock on a caller that already hung
                    # up
                    try:
                        self._check_deadline("lock-acquired")
                    except SchedulingFailure as err:
                        tracing.add_tag("outcome", err.outcome)
                        if self._provenance is not None and self._provenance.enabled:
                            self._provenance.on_trigger(
                                "deadline-exceeded",
                                f"{args.pod.namespace}/{args.pod.name} at lock-acquired",
                            )
                        return self._fail_with_message(err.outcome, args, str(err))
                    return self._predicate_locked(args)
            finally:
                exit_predicate_lock()

    def _lane_neutral(self, lane: str):
        """A device lane declined the request (unsupported shape, inexact
        snapshot) — neither success nor failure.  Release a possible
        re-probe slot so a demoted lane can't wedge on neutral attempts."""
        if self._lane_health is not None:
            self._lane_health.release_probe(lane)
        return None

    def _check_deadline(self, phase: str) -> None:
        """Phase-boundary deadline check (resilience/deadline.py): one
        contextvar read when no deadline is bound."""
        try:
            req_deadline.check(phase)
        except req_deadline.DeadlineExceeded as err:
            from ..metrics import names as mnames

            self._metrics.counter(
                mnames.RESILIENCE_DEADLINE_EXPIRED_COUNT, {"phase": phase}
            )
            raise SchedulingFailure(FAILURE_DEADLINE, str(err))

    def _predicate_locked(self, args: ExtenderArgs) -> ExtenderFilterResult:
        pod = args.pod
        # the wire pod is authoritative for spec/labels, but reservation
        # owner references need the cluster UID: a UID-less wire pod
        # (kube-scheduler always sends one; simulators may not) would
        # create reservations the owner GC can never match — a permanent
        # capacity leak
        if not pod.meta.uid:
            stored = self._pod_lister.informer.get(pod.namespace, pod.name)
            if stored is None:
                # kube-scheduler always sends the UID and only schedules
                # pods that exist; a UID-less pod unknown to the informer
                # is a broken client — reject rather than create an
                # owner-less (uncollectable) reservation
                logger.warning(
                    "rejecting pod %s/%s: no UID and not in the informer",
                    pod.namespace,
                    pod.name,
                )
                return self._fail_with_message(
                    FAILURE_INTERNAL, args, "pod has no UID and is unknown"
                )
            pod.meta.uid = stored.meta.uid
        role = pod.labels.get(L.SPARK_ROLE_LABEL, "")
        instance_group, ok = L.find_instance_group_from_pod_spec(pod, self._instance_group_label)
        if not ok:
            instance_group = ""
        if self._provenance is not None and self._provenance.enabled:
            self._provenance.begin_decision(pod, role=role)

        t0 = time.perf_counter()
        try:
            self._reconcile_if_needed()
        except Exception as err:
            logger.exception("failed to reconcile")
            self._finish_provenance(
                FAILURE_INTERNAL, instance_group, message="failed to reconcile"
            )
            return self._fail_with_message(FAILURE_INTERNAL, args, "failed to reconcile")
        self._rrm.compact_dynamic_allocation_applications()

        try:
            node_name, outcome = self._select_node(instance_group, role, pod, args.node_names)
        except SchedulingFailure as err:
            self._mark_schedule(instance_group, role, err.outcome, t0, pod)
            self._finish_provenance(err.outcome, instance_group, message=str(err))
            if err.outcome == FAILURE_INTERNAL:
                logger.exception("internal error scheduling pod %s", pod.name)
            else:
                logger.info("failed to schedule pod %s: %s (%s)", pod.name, err, err.outcome)
            return self._fail_with_message(err.outcome, args, str(err))

        self._mark_schedule(instance_group, role, outcome, t0, pod)
        self._finish_provenance(outcome, instance_group, node=node_name)
        tracing.add_tag("node", node_name)

        if role == L.DRIVER:
            try:
                app_resources = spark_resources(pod)
            except AnnotationError as err:
                logger.exception("internal error scheduling pod")
                return self._fail_with_message(FAILURE_INTERNAL, args, str(err))
            ev.emit_application_scheduled(
                instance_group,
                pod.labels.get(L.SPARK_APP_ID_LABEL, ""),
                pod.name,
                pod.namespace,
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
                app_resources.max_executor_count,
                self._event_log,
            )

        logger.info("scheduling pod %s to node %s", pod.name, node_name)
        return ExtenderFilterResult(node_names=[node_name])

    def _mark_schedule(
        self, instance_group: str, role: str, outcome: str, t0: float, pod: Pod = None
    ) -> None:
        """ScheduleTimer semantics (metrics.go:164-219): the retry tag is
        derived statelessly from the pod's PodScheduled condition, the
        last-seen time from that condition's transition time, and the
        first-sight slow log fires only on first tries."""
        from ..metrics import names as mnames

        tracing.add_tag("role", role)
        tracing.add_tag("instanceGroup", instance_group)
        tracing.add_tag("outcome", outcome)
        tags = {"instanceGroup": instance_group, "role": role, "outcome": outcome}
        self._metrics.histogram(mnames.SCHEDULING_PROCESSING_TIME, time.perf_counter() - t0, tags)
        self._metrics.counter(mnames.REQUEST_COUNTER, tags)
        if pod is not None:
            now = timesource.now()
            created = pod.creation_timestamp or now
            scheduled_condition = pod.conditions.get("PodScheduled")
            is_retry = scheduled_condition is not None
            last_seen = (
                scheduled_condition.transition_time
                if is_retry and scheduled_condition.transition_time
                else created
            )
            wait = max(now - created, 0.0)
            self._metrics.histogram(mnames.SCHEDULING_WAIT_TIME, wait, tags)
            self._metrics.histogram(
                mnames.SCHEDULING_RETRY_TIME,
                max(now - last_seen, 0.0),
                dict(tags, retry="true" if is_retry else "false"),
            )
            if wait > mnames.SLOW_LOG_THRESHOLD_SECONDS and not is_retry:
                logger.warning(
                    "pod %s/%s first seen by the extender but older than the slow "
                    "log threshold (%.0fs, outcome %s)",
                    pod.namespace,
                    pod.name,
                    wait,
                    outcome,
                )

    def _finish_provenance(
        self, outcome: str, instance_group: str, node: str = "", message: str = ""
    ) -> None:
        """Seal the pending decision record (provenance/tracker.py) and
        fire the deadline flight-recorder trigger when the decision died
        at a phase boundary."""
        prov = self._provenance
        if prov is None or not prov.enabled:
            return
        # lane comes from the captured artifacts when a queue solve ran
        # for THIS decision; passing the solver's last_queue_lane here
        # would stamp artifact-less decisions (executor replays, early
        # failures) with a stale lane from a previous driver solve
        prov.finish_decision(
            outcome,
            node=node,
            lane="",
            policy=self.binpacker.name,
            instance_group=instance_group,
            message=message,
        )
        if outcome == FAILURE_DEADLINE:
            prov.on_trigger("deadline-exceeded", message)

    def _refusal_message(self, base: str, kind: str) -> str:
        """Thread the tightest-dimension shortfall + blocker set into
        the shared failure message ("short 12 executors … in cpu;
        blocked by 3 earlier drivers").  The enriched message flows
        through uniform_failure into the PR 5 encode-once buffer — one
        serialization per (candidates, message) pair, unchanged."""
        prov = self._provenance
        if prov is None or not prov.enabled:
            return base
        detail = prov.refusal_detail(kind)
        return f"{base}: {detail}" if detail else base

    # -- policy hooks (no-ops when no engine is configured) ------------------

    def _earlier_drivers(self, driver: Pod) -> List[Pod]:
        """The queue-ahead set for the FIFO gate; the policy engine may
        re-order it (priority-then-fifo, DRF) without touching the
        queue solve itself."""
        if self._policy is not None:
            return self._policy.earlier_queue(driver)
        return self._pod_lister.list_earlier_drivers(driver)

    def _skip_verdict(self, queued: Pod, driver: Pod, skip_cutoff: float) -> bool:
        """enforce-after-age skip verdict for one queued driver,
        optionally widened by the policy engine's conservative backfill
        probe (which can only ADD skips, never remove one)."""
        base = queued.creation_timestamp > skip_cutoff
        if self._policy is not None:
            return self._policy.skip_allowed(queued, driver, base)
        return base

    def _raise_driver_refusal(
        self, driver: Pod, app_resources, outcome: str, base_message: str, kind: str
    ):
        """Shared refusal tail for the driver path: enrich the message
        with the shortfall explain, give the policy engine its
        preemption shot (the explain memoized the blocker set it
        seeds from), and stamp any committed victim set into the
        FailedNodes message."""
        message = self._refusal_message(base_message, kind)
        if self._policy is not None:
            note = self._policy.on_driver_refusal(driver, app_resources, outcome)
            if note:
                message = f"{message}; {note}"
        raise SchedulingFailure(outcome, message)

    def _fail_with_message(self, outcome: str, args: ExtenderArgs, message: str) -> ExtenderFilterResult:
        if self._waste_reporter is not None:
            self._waste_reporter.mark_failed_scheduling_attempt(args.pod, outcome)
        # the uniform_failure hint lets the HTTP layer reuse an encoded
        # response buffer for this (candidate tuple, message) pair
        # instead of re-serializing a 10k-entry map per retry
        return ExtenderFilterResult(
            failed_nodes={n: message for n in args.node_names},
            uniform_failure=(args.node_names, message),
        )

    def _reconcile_if_needed(self) -> None:
        """resource.go:194-205."""
        now = timesource.now()
        if now > self._last_request + LEADER_ELECTION_INTERVAL_SECONDS:
            from ..metrics import names as mnames
            from .failover import sync_resource_reservations_and_demands

            t0 = time.perf_counter()
            with self._tracer.span("reconcile"):
                sync_resource_reservations_and_demands(self)
            self._metrics.histogram(
                mnames.RECONCILIATION_TIME, time.perf_counter() - t0
            )
        self._last_request = now

    def _select_node(
        self, instance_group: str, role: str, pod: Pod, node_names: List[str]
    ) -> Tuple[str, str]:
        """resource.go:207-220."""
        if role == L.DRIVER:
            return self._select_driver_node(instance_group, pod, node_names)
        if role == L.EXECUTOR:
            node, outcome = self._select_executor_node(pod, node_names)
            if outcome in SUCCESS_OUTCOMES:
                self._demands.delete_demand_if_exists(pod, "SparkSchedulerExtender")
            return node, outcome
        raise SchedulingFailure(FAILURE_NON_SPARK_POD, "can not schedule non spark pod")

    # -- driver path ---------------------------------------------------------

    def _select_driver_node(
        self, instance_group: str, driver: Pod, node_names: List[str]
    ) -> Tuple[str, str]:
        """resource.go:272-370."""
        app_id = driver.labels.get(L.SPARK_APP_ID_LABEL, "")
        rr = self._rrm.get_resource_reservation(app_id, driver.namespace)
        if rr is not None:
            # idempotent replay: return the previously reserved node
            driver_reserved_node = rr.spec.reservations[DRIVER_RESERVATION_NAME].node
            if driver_reserved_node not in node_names:
                logger.warning(
                    "driver already has a reservation but node %s is not in candidate list; "
                    "returning it anyway",
                    driver_reserved_node,
                )
            return driver_reserved_node, SUCCESS

        try:
            app_resources_early = spark_resources(driver)
        except AnnotationError as err:
            raise SchedulingFailure(FAILURE_INTERNAL, f"failed to get spark resources: {err}")
        fast = self._try_fast_driver_path(
            instance_group, driver, node_names, app_resources_early
        )
        self._metrics.counter(
            mnames.TPU_FASTPATH,
            {"path": "driver", "lane": "fast" if fast is not None else "slow"},
        )
        if fast is not None:
            outcome, zones = fast
            if not outcome.earlier_ok:
                self._demands.create_demand_for_application_in_any_zone(
                    driver, app_resources_early
                )
                self._raise_driver_refusal(
                    driver,
                    app_resources_early,
                    FAILURE_EARLIER_DRIVER,
                    "earlier drivers do not fit to the cluster",
                    "earlier-driver",
                )
            return self._finish_driver_selection(
                instance_group, driver, app_resources_early, outcome.result, zones
            )

        available_nodes: List[Node] = self._node_informer.list_with_predicate(
            lambda node: driver.matches_node(node)
        )

        usage = self._rrm.get_reserved_resources()
        overhead = self._overhead.get_overhead(available_nodes)
        metadata = node_scheduling_metadata_for_nodes(available_nodes, usage, overhead)
        driver_node_names, executor_node_names = self._node_sorter.potential_nodes(
            metadata, node_names
        )
        app_resources = app_resources_early

        packing_result = None
        self._check_deadline("fifo-gate")
        if self._is_fifo:
            queued_drivers = self._earlier_drivers(driver)
            # tpu-batch: the whole earlier-drivers pass plus this driver's
            # pack is ONE device solve (ops/fifo_solver); other policies
            # run the host loop
            outcome = self._try_device_fifo(
                instance_group,
                queued_drivers,
                driver_node_names,
                executor_node_names,
                metadata,
                app_resources,
                current_driver=driver,
            )
            if outcome is not None and outcome.supported:
                earlier_ok = outcome.earlier_ok
                packing_result = outcome.result
            else:
                earlier_ok = self._fit_earlier_drivers(
                    instance_group,
                    queued_drivers,
                    driver_node_names,
                    executor_node_names,
                    metadata,
                    current_driver=driver,
                )
            if not earlier_ok:
                self._demands.create_demand_for_application_in_any_zone(driver, app_resources)
                self._raise_driver_refusal(
                    driver,
                    app_resources,
                    FAILURE_EARLIER_DRIVER,
                    "earlier drivers do not fit to the cluster",
                    "earlier-driver",
                )

        if packing_result is None:
            self._check_deadline("binpack")
            with self._tracer.span(
                "binpack", {"policy": self.binpacker.name, "lane": "host"}
            ) as sp:
                packing_result = self.binpacker.binpack_func(
                    app_resources.driver_resources,
                    app_resources.executor_resources,
                    app_resources.min_executor_count,
                    driver_node_names,
                    executor_node_names,
                    metadata,
                )
                sp.tag("hasCapacity", packing_result.has_capacity)
        efficiency = compute_avg_packing_efficiency(
            metadata, list(packing_result.packing_efficiencies.values())
        ) if packing_result.has_capacity else None
        zones = {
            node.name: node.labels.get(ZONE_LABEL, "") for node in available_nodes
        }
        return self._finish_driver_selection(
            instance_group, driver, app_resources, packing_result, zones, efficiency
        )

    def _finish_driver_selection(
        self, instance_group, driver, app_resources, packing_result, zones, efficiency=None
    ) -> Tuple[str, str]:
        """Common driver-path tail: demand lifecycle, metrics, reservation
        creation (resource.go:347-369)."""
        self._check_deadline("reservation-writeback")
        if not packing_result.has_capacity:
            self._demands.create_demand_for_application_in_any_zone(driver, app_resources)
            self._raise_driver_refusal(
                driver,
                app_resources,
                FAILURE_FIT,
                "application does not fit to the cluster",
                "fit",
            )

        if efficiency is None:
            if packing_result.max_avg_efficiency is not None:
                # precomputed by the tensor lanes (same float64 value as
                # the iteration below, without materializing every node)
                max_avg = packing_result.max_avg_efficiency
            else:
                # fast path: average the per-node efficiencies directly
                # (the device adapters compute them with exact value()
                # semantics)
                effs = list(packing_result.packing_efficiencies.values())
                max_sum = sum(max(e.gpu, e.cpu, e.memory) for e in effs)
                max_avg = max_sum / max(len(effs), 1)
        else:
            max_avg = efficiency.max
        self._metrics.gauge(
            mnames.PACKING_EFFICIENCY_MAX,
            max_avg,
            {"instanceGroup": instance_group, "binpacker": self.binpacker.name},
        )
        self._report_placement_metrics(instance_group, packing_result, zones)

        self._demands.delete_demand_if_exists(driver, "SparkSchedulerExtender")
        self._rrm.create_reservations(
            driver,
            app_resources,
            packing_result.driver_node,
            packing_result.executor_nodes,
        )
        return packing_result.driver_node, SUCCESS

    def _try_fast_driver_path(self, instance_group, driver, node_names, app_resources):
        """Whole driver decision (FIFO pass + gang pack) from the
        event-driven tensor snapshot: zero Quantity arithmetic.  Returns
        (FifoOutcome, zones) or None to use the Quantity path."""
        solver = getattr(self.binpacker, "queue_solver", None)
        # the tensor-snapshot lane needs a solver that accepts prebuilt
        # tensors; the single-AZ FIFO solver requires Quantity metadata
        # (zone efficiency choice) and goes through the metadata path
        if (
            solver is None
            or not hasattr(solver, "solve_tensor")
            or not self._fast_path_ok
        ):
            return None
        if self._lane_health is not None and not self._lane_health.allow(
            "tensor_driver"
        ):
            return None  # demoted: host path serves until the re-probe
        t0 = time.perf_counter()
        try:
            check_kernel_fault("tensor_driver")
            from ..ops.fast_path import build_cluster_tensor
            from ..ops.sparkapp import AppDemand

            snap = self._tensor_snapshot.snapshot()

            prov = self._provenance
            if prov is not None and not prov.enabled:
                prov = None
            earlier_apps = []
            skip_allowed = []
            queue_names: Optional[List[str]] = [] if prov is not None else None
            if self._is_fifo:
                skip_cutoff = self._fifo_skip_cutoff(instance_group)
                for queued in self._earlier_drivers(driver):
                    try:
                        # stable AppDemand per pod version: tensor rows
                        # are computed once per app, not per request
                        _, demand = spark_app_demand_cached(queued)
                    except AnnotationError:
                        logger.warning(
                            "failed to get driver resources, skipping driver %s",
                            queued.name,
                        )
                        continue
                    earlier_apps.append(demand)
                    skip_allowed.append(self._skip_verdict(queued, driver, skip_cutoff))
                    if queue_names is not None:
                        queue_names.append(queued.name)
            if prov is not None:
                prov.note_context(
                    queue_names=queue_names,
                    content_key=snap.content_key,
                    feed_seq=int(snap.content_key[1]),
                )
            current = AppDemand(
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
            )

            # speculative-verdict intake first (concurrent/engine.py):
            # the commit gate installed a verdict solved outside the
            # lock; consume it only if it revalidates against THIS
            # basis (seq → memcmp → conflict) — a conflict falls
            # through to the warm delta solve below (the bounded
            # re-solve), so decisions never depend on speculation
            intake = self.speculation_intake
            if intake is not None:
                served = intake(
                    driver, snap, node_names, earlier_apps, skip_allowed, current
                )
                if served is not None:
                    outcome, zones = served
                    tracing.add_tag("speculation", "hit")
                    if self._lane_health is not None:
                        self._lane_health.record_success(
                            "tensor_driver", time.perf_counter() - t0
                        )
                    return outcome, zones

            # incremental lane first: a warm session skips the tensor
            # build, the sorts, the GCD scaling, AND the already-proved
            # queue prefix — the engine declines (None) whenever it
            # cannot serve the request exactly
            if self.delta_engine is not None:
                served = self.delta_engine.solve(
                    snap, driver, node_names, self._node_sorter,
                    earlier_apps, skip_allowed, current, solver,
                )
                if served is not None:
                    outcome, zones = served
                    if self._lane_health is not None:
                        self._lane_health.record_success(
                            "tensor_driver", time.perf_counter() - t0
                        )
                    return outcome, zones

            with self._tracer.span("fast_path.build_tensor") as sp:
                # node_names flows through verbatim — on the HTTP path
                # it is the interned tuple, so prep-cache keys share one
                # string set instead of pinning per-request copies
                built = build_cluster_tensor(
                    snap,
                    driver,
                    node_names,
                    driver_label_priority=self._node_sorter.driver_label_priority,
                    executor_label_priority=self._node_sorter.executor_label_priority,
                )
                sp.tag("exact", built is not None)
            if built is None:
                return self._lane_neutral("tensor_driver")
            cluster, zones = built
            outcome = solver.solve_tensor(
                cluster,
                earlier_apps,
                skip_allowed,
                current,
            )
            if not outcome.supported:
                return self._lane_neutral("tensor_driver")
            if self._lane_health is not None:
                self._lane_health.record_success(
                    "tensor_driver", time.perf_counter() - t0
                )
            return outcome, zones
        except Exception:
            if self._lane_health is not None:
                self._lane_health.record_failure("tensor_driver")
            logger.exception("tensor-snapshot fast path failed; using Quantity path")
            return None

    def _try_device_fifo(
        self,
        instance_group: str,
        queued_drivers: List[Pod],
        driver_node_names: List[str],
        executor_node_names: List[str],
        metadata,
        app_resources,
        current_driver: Optional[Pod] = None,
    ):
        """Run the FIFO pass + current pack on device when the configured
        binpacker provides a queue solver; returns None when unavailable
        (host loop takes over)."""
        solver = getattr(self.binpacker, "queue_solver", None)
        if solver is None:
            return None
        if self._lane_health is not None and not self._lane_health.allow(
            "device_fifo"
        ):
            return None  # demoted: the host earlier-drivers loop serves
        from ..ops.sparkapp import AppDemand

        prov = self._provenance
        if prov is not None and not prov.enabled:
            prov = None
        earlier_apps = []
        skip_allowed = []
        queue_names: Optional[List[str]] = [] if prov is not None else None
        skip_cutoff = self._fifo_skip_cutoff(instance_group)
        for queued in queued_drivers:
            try:
                _, demand = spark_app_demand_cached(queued)
            except AnnotationError:
                logger.warning(
                    "failed to get driver resources, skipping driver %s", queued.name
                )
                continue
            earlier_apps.append(demand)
            if current_driver is not None:
                skip_allowed.append(
                    self._skip_verdict(queued, current_driver, skip_cutoff)
                )
            else:
                skip_allowed.append(queued.creation_timestamp > skip_cutoff)
            if queue_names is not None:
                queue_names.append(queued.name)
        if prov is not None:
            prov.note_context(queue_names=queue_names)
        t0 = time.perf_counter()
        try:
            check_kernel_fault("device_fifo")
            outcome = solver.solve(
                metadata,
                driver_node_names,
                executor_node_names,
                earlier_apps,
                skip_allowed,
                AppDemand(
                    app_resources.driver_resources,
                    app_resources.executor_resources,
                    app_resources.min_executor_count,
                ),
            )
            lane = getattr(solver, "last_path", None)
            if lane is not None:
                # single-AZ solvers report fused (one-dispatch) vs host
                # (exact fallback) — the ops signal for how often the
                # certified fixed-point zone choice holds
                self._metrics.counter(
                    mnames.SINGLEAZ_LANE, {"lane": lane}
                )
            if self._lane_health is not None:
                self._lane_health.record_success(
                    "device_fifo", time.perf_counter() - t0
                )
            return outcome
        except Exception:
            if self._lane_health is not None:
                self._lane_health.record_failure("device_fifo")
            logger.exception("device FIFO solve failed; falling back to host loop")
            return None

    def _fit_earlier_drivers(
        self,
        instance_group: str,
        drivers: List[Pod],
        node_names: List[str],
        executor_node_names: List[str],
        metadata,
        current_driver: Optional[Pod] = None,
    ) -> bool:
        """resource.go:224-262: binpack every earlier driver and subtract
        its usage before considering this one."""
        with self._tracer.span(
            "fifo_gate", {"lane": "host", "earlierApps": len(drivers)}
        ) as sp:
            for driver in drivers:
                try:
                    app_resources = spark_resources_cached(driver)
                except AnnotationError:
                    logger.warning("failed to get driver resources, skipping driver %s", driver.name)
                    continue
                packing_result = self.binpacker.binpack_func(
                    app_resources.driver_resources,
                    app_resources.executor_resources,
                    app_resources.min_executor_count,
                    node_names,
                    executor_node_names,
                    metadata,
                )
                if not packing_result.has_capacity:
                    base_skip = self._should_skip_driver_fifo(driver, instance_group)
                    if self._policy is not None and current_driver is not None:
                        base_skip = self._policy.skip_allowed(
                            driver, current_driver, base_skip
                        )
                    if base_skip:
                        logger.debug(
                            "skipping non-fitting driver %s from FIFO: not old enough", driver.name
                        )
                        continue
                    logger.warning("failed to fit earlier driver %s", driver.name)
                    sp.tag("earlierOk", False).tag("blockedBy", driver.name)
                    return False
                subtract_usage_if_exists(
                    metadata,
                    spark_resource_usage(
                        app_resources.driver_resources,
                        app_resources.executor_resources,
                        packing_result.driver_node,
                        packing_result.executor_nodes,
                    ),
                )
            sp.tag("earlierOk", True)
            return True

    def _should_skip_driver_fifo(self, pod: Pod, instance_group: str) -> bool:
        """resource.go:264-270."""
        return pod.creation_timestamp > self._fifo_skip_cutoff(instance_group)

    def _fifo_skip_cutoff(self, instance_group: str) -> float:
        """Creation-time cutoff above which a queued driver is young
        enough to skip — hoistable out of the per-request queue loop
        (one clock sample per request instead of one per queued pod;
        the reference's per-pod time.Now() drift within a request is
        sub-millisecond wall clock, not decision semantics)."""
        enforce_after = self._fifo_config.enforce_after_pod_age_by_instance_group.get(
            instance_group, self._fifo_config.default_enforce_after_pod_age
        )
        return timesource.now() - enforce_after

    # -- executor path -------------------------------------------------------

    def _select_executor_node(self, executor: Pod, node_names: List[str]) -> Tuple[str, str]:
        """resource.go:383-435."""
        try:
            already_bound_node, found = self._rrm.find_already_bound_reservation_node(executor)
        except KeyError as err:
            raise SchedulingFailure(
                FAILURE_INTERNAL, f"error when looking for already bound reservations: {err}"
            )
        if found:
            result = self._reservation_node_from_node_list([already_bound_node], node_names)
            if result is not None:
                return result, SUCCESS_ALREADY_BOUND
            logger.info(
                "found already bound node %s for executor, but not in potential nodes",
                already_bound_node,
            )

        try:
            unbound_nodes, found_unbound = self._rrm.find_unbound_reservation_nodes(executor)
        except KeyError as err:
            raise SchedulingFailure(
                FAILURE_INTERNAL, f"error when looking for unbound reservations: {err}"
            )
        if found_unbound:
            result = self._reservation_node_from_node_list(unbound_nodes, node_names)
            if result is not None:
                try:
                    self._rrm.reserve_for_executor_on_unbound_reservation(executor, result)
                except Exception as err:
                    raise SchedulingFailure(
                        FAILURE_INTERNAL, f"failed to reserve node for executor: {err}"
                    )
                return result, SUCCESS

        try:
            free_spots = self._rrm.get_remaining_allowed_executor_count(
                executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
            )
        except KeyError as err:
            raise SchedulingFailure(
                FAILURE_INTERNAL, f"error when checking remaining allowed executors: {err}"
            )
        if free_spots > 0:
            is_extra_executor = not found_unbound
            node_name, outcome = self._reschedule_executor(executor, node_names, is_extra_executor)
            try:
                self._rrm.reserve_for_executor_on_rescheduled_node(executor, node_name)
            except Exception as err:
                raise SchedulingFailure(
                    FAILURE_INTERNAL, f"failed to reserve node for rescheduled executor: {err}"
                )
            return node_name, outcome

        raise SchedulingFailure(
            FAILURE_UNBOUND, "application has no free executor spots to schedule this one"
        )

    @staticmethod
    def _reservation_node_from_node_list(
        reservation_nodes: List[str], node_names: List[str]
    ) -> Optional[str]:
        """resource.go:438-447."""
        reservation_set = set(reservation_nodes)
        for name in node_names:
            if name in reservation_set:
                return name
        return None

    def _get_nodes(self, node_names: List[str]) -> List[Node]:
        nodes = []
        for name in node_names:
            node = self._node_informer.get("default", name)
            if node is None:
                logger.warning("failed to find node %s in cache, skipping", name)
                continue
            nodes.append(node)
        return nodes

    def _reschedule_executor(
        self, executor: Pod, node_names: List[str], is_extra_executor: bool
    ) -> Tuple[str, str]:
        """resource.go:594-673."""
        driver = self._pod_lister.get_driver_pod_for_executor(executor)
        if driver is None:
            raise SchedulingFailure(FAILURE_INTERNAL, "failed to get driver pod for executor")
        try:
            app_resources = spark_resources(driver)
        except AnnotationError as err:
            raise SchedulingFailure(FAILURE_INTERNAL, str(err))
        executor_resources = app_resources.executor_resources

        should_schedule_into_single_az = False
        single_az_zone = ""
        if self.binpacker.is_single_az and self._single_az_da:
            zone, all_in_same_az = self._get_common_zone_for_executors_application(executor)
            if all_in_same_az:
                single_az_zone = zone
                should_schedule_into_single_az = True

        potential_outcome = (
            SUCCESS_SCHEDULED_EXTRA_EXECUTOR if is_extra_executor else SUCCESS_RESCHEDULED
        )

        # executor fast lane: order + fit from the event-driven tensor
        # mirror, zero Quantity arithmetic and no O(all-reservations)
        # usage walk (ref hot path resource.go:594-663)
        fast = self._try_fast_reschedule(
            executor,
            node_names,
            executor_resources,
            single_az_zone if should_schedule_into_single_az else None,
        )
        self._metrics.counter(
            mnames.TPU_FASTPATH,
            {"path": "executor", "lane": "fast" if fast is not None else "slow"},
        )
        if fast is not None:
            hit, name = fast
            if hit:
                return name, potential_outcome
            self._reschedule_miss(
                executor, executor_resources, should_schedule_into_single_az, single_az_zone
            )

        available_nodes = self._get_nodes(node_names)
        if should_schedule_into_single_az:
            available_nodes = self._filter_nodes_to_zone(available_nodes, single_az_zone)
            node_names = [n.name for n in available_nodes]

        usage = self._rrm.get_reserved_resources()
        overhead = self._overhead.get_overhead(available_nodes)
        metadata = node_scheduling_metadata_for_nodes(available_nodes, usage, overhead)

        # QUIRK (switchable, install key strict-reference-parity;
        # reference resource.go:638-643 + resources.go:61-100): the Go
        # NodeSchedulingMetadataForNodes mutates the caller's usage map
        # in place (usage[node].Add(overhead) through a shared pointer) for
        # nodes that have a usage entry, and the subsequent usage.Add(
        # overhead) adds it AGAIN — so the first-fit reschedule path sees
        # allocatable − reserved − 2×overhead on nodes with reservations,
        # and allocatable − overhead on nodes without.  Replicated exactly
        # for decision parity; with strict parity off overhead counts once
        # on every node (the driver path's semantics).
        double_overhead = self._strict_reference_parity
        for node_name, node_overhead in overhead.items():
            if node_name in usage:
                usage[node_name] = usage[node_name].add(node_overhead)
                if double_overhead:
                    usage[node_name] = usage[node_name].add(node_overhead)
            else:
                usage[node_name] = node_overhead
        available_resources = available_for_nodes(available_nodes, usage)

        _, executor_node_names = self._node_sorter.potential_nodes(metadata, node_names)

        if self._is_single_az_min_frag():
            name = self._reschedule_executor_with_minimal_fragmentation(
                executor, executor_node_names, metadata, overhead, executor_resources
            )
            if name is not None:
                return name, potential_outcome
        else:
            for name in executor_node_names:
                if not executor_resources.greater_than(available_resources[name]):
                    return name, potential_outcome

        self._reschedule_miss(
            executor, executor_resources, should_schedule_into_single_az, single_az_zone
        )

    def _is_single_az_min_frag(self) -> bool:
        """Both the host policy and its tpu-batch counterpart use the
        min-frag reschedule variant (resource.go:652's name check) — the
        device name must not silently flip the variant to first-fit."""
        return self.binpacker.name.endswith(SINGLE_AZ_MINIMAL_FRAGMENTATION)

    def _reschedule_miss(
        self, executor: Pod, executor_resources, into_single_az: bool, zone: str
    ):
        """Shared no-capacity tail of the reschedule path
        (resource.go:664-672): demand creation + failure."""
        if into_single_az:
            self._metrics.counter(
                mnames.SINGLE_AZ_DA_PACK_FAILURE_ZONED,
                {"zone": zone},
            )
            self._demands.create_demand_for_executor_in_specific_zone(
                executor, executor_resources, zone
            )
        else:
            self._demands.create_demand_for_executor_in_any_zone(executor, executor_resources)
        raise SchedulingFailure(FAILURE_FIT, "not enough capacity to reschedule the executor")

    def _try_fast_reschedule(
        self,
        executor: Pod,
        node_names: List[str],
        executor_resources,
        zone: Optional[str],
    ):
        """Executor reschedule served entirely from the tensor mirror:
        AZ-aware executor order (including label priority) and the fit
        check in vectorized integer math.  Returns (hit, node_name) or
        None to use the Quantity path.  Decision parity: availability
        rows equal the slow path's alloc − reserved − overhead exactly
        (tests/test_tensor_snapshot.py); the double-overhead reschedule
        quirk applies to reservation-entry nodes under strict parity
        (compat.py #1).  The single-az-minimal-fragmentation policy's
        app-attraction variant (resource.go:675-703) is served as a
        vectorized lexicographic min instead of first-fit."""
        self.last_reschedule_path = "slow"
        if self._tensor_snapshot is None or not self._fast_path_ok:
            return None
        if self._lane_health is not None and not self._lane_health.allow(
            "tensor_reschedule"
        ):
            return None  # demoted: the Quantity path serves until the re-probe
        t0 = time.perf_counter()
        try:
            check_kernel_fault("tensor_reschedule")
            with self._tracer.span("executor.fast_reschedule") as span:
                result = self._try_fast_reschedule_traced(
                    executor, node_names, executor_resources, zone, span
                )
            if self._lane_health is not None:
                if result is not None:
                    self._lane_health.record_success(
                        "tensor_reschedule", time.perf_counter() - t0
                    )
                else:
                    # neutral: the lane declined (inexact snapshot) —
                    # release a possible probe so it isn't wedged demoted
                    self._lane_health.release_probe("tensor_reschedule")
            return result
        except Exception:
            if self._lane_health is not None:
                self._lane_health.record_failure("tensor_reschedule")
            logger.exception("fast reschedule lane failed; using Quantity path")
            return None

    def _try_fast_reschedule_traced(
        self, executor, node_names, executor_resources, zone, span
    ):
        from ..ops.fast_path import executor_reschedule_order
        from ..ops.tensorize import _resources_to_base

        snap = self._tensor_snapshot.snapshot()
        exec_row, exact = _resources_to_base(executor_resources)
        if not exact:
            return None
        built = executor_reschedule_order(
            snap,
            list(node_names),
            self._node_sorter.executor_label_priority,
            zone,
        )
        if built is None:
            return None
        names, avail, overhead, res_entry = built
        row = np.array(exec_row, dtype=np.int64)
        if self._is_single_az_min_frag():
            hit_name = self._fast_min_frag_reschedule(
                executor, names, avail, overhead, row
            )
            self.last_reschedule_path = "fast"
            span.tag("hit", hit_name is not None)
            if hit_name is not None:
                return True, hit_name
            return False, None
        fit_avail = avail
        if self._strict_reference_parity and len(names):
            # QUIRK #1 (resource.go:638-643): nodes with a usage
            # entry see overhead subtracted twice on this path
            fit_avail = avail.copy()
            fit_avail[res_entry] -= overhead[res_entry]
        fits = (fit_avail >= row[None, :]).all(axis=1)
        hit = np.flatnonzero(fits)
        self.last_reschedule_path = "fast"
        span.tag("hit", bool(len(hit)))
        if len(hit):
            return True, names[int(hit[0])]
        return False, None

    def _fast_min_frag_reschedule(self, executor, names, avail, overhead, row):
        """resource.go:675-703 from the mirror: capacity per node with
        overhead passed as the reserved map (the reference's
        GetNodeCapacities call — net DOUBLE overhead on top of the
        availability rows, which already subtract it once; unconditional
        in the reference, unlike the first-fit branch's flagged quirk),
        then the best node = lexicographic min of (not-hosting-this-app,
        capacity, priority position) among capacity ≥ 1 — identical to
        the sequential strict-improvement loop."""
        if not len(names):
            return None
        # capacity_against_single_dimension per dim: reserved > available
        # → 0; zero requirement → unbounded; else exact floor division
        diff = avail - overhead
        per_dim = np.where(
            overhead > avail,
            np.int64(0),
            np.where(
                row[None, :] == 0,
                np.int64(2**62),
                np.floor_divide(diff, np.maximum(row[None, :], 1)),
            ),
        )
        cap = per_dim.min(axis=1)
        candidates = np.flatnonzero(cap >= 1)
        if not len(candidates):
            return None
        app_nodes = self._get_nodes_with_executors_belonging_to_same_app(executor)
        not_in_app = np.fromiter(
            (names[i] not in app_nodes for i in candidates),
            dtype=bool,
            count=len(candidates),
        )
        order = np.lexsort((candidates, cap[candidates], not_in_app))
        return names[int(candidates[order[0]])]

    def _reschedule_executor_with_minimal_fragmentation(
        self,
        executor: Pod,
        executor_node_names: List[str],
        metadata,
        overhead,
        executor_resources,
    ) -> Optional[str]:
        """resource.go:675-703: prefer nodes already hosting this app, then
        least capacity."""
        capacities = cap.get_node_capacities(
            executor_node_names, metadata, overhead, executor_resources
        )
        app_nodes = self._get_nodes_with_executors_belonging_to_same_app(executor)

        best: Optional[cap.NodeAndExecutorCapacity] = None
        for node_capacity in capacities:
            if node_capacity.capacity >= 1:
                if best is None:
                    best = node_capacity
                elif node_capacity.node_name in app_nodes and best.node_name not in app_nodes:
                    best = node_capacity
                elif (node_capacity.node_name in app_nodes) == (best.node_name in app_nodes) and (
                    node_capacity.capacity < best.capacity
                ):
                    best = node_capacity
        return best.node_name if best is not None else None

    def _get_nodes_with_executors_belonging_to_same_app(self, executor: Pod) -> set:
        """resource.go:565-584."""
        nodes = set()
        app_id = executor.labels.get(L.SPARK_APP_ID_LABEL, "")
        rr = self._rrm.get_resource_reservation(app_id, executor.namespace)
        if rr is not None:
            for pod, reservation in rr.spec.reservations.items():
                if pod != DRIVER_RESERVATION_NAME:
                    nodes.add(reservation.node)
        sr, ok = self._rrm.get_soft_resource_reservation(app_id)
        if ok:
            for pod, reservation in sr.reservations.items():
                if pod != DRIVER_RESERVATION_NAME:
                    nodes.add(reservation.node)
        return nodes

    # -- single-AZ helpers ---------------------------------------------------

    def _get_common_zone_for_executors_application(self, executor: Pod) -> Tuple[str, bool]:
        """resource.go:493-515."""
        app_id = executor.labels.get(L.SPARK_APP_ID_LABEL)
        if app_id is None:
            raise SchedulingFailure(FAILURE_INTERNAL, "executor has no spark app id label")
        app_pods = self._pod_lister.list(
            namespace=executor.namespace, label_selector={L.SPARK_APP_ID_LABEL: app_id}
        )
        from ..types.objects import PodPhase

        running = [p for p in app_pods if p.phase == PodPhase.RUNNING]
        zones = set()
        for pod in running:
            node = self._node_informer.get("default", pod.node_name)
            if node is None:
                raise SchedulingFailure(FAILURE_INTERNAL, f"node {pod.node_name} not found")
            zone = node.labels.get(ZONE_LABEL)
            if zone is None:
                raise SchedulingFailure(
                    FAILURE_INTERNAL, "could not read zone label from node"
                )
            zones.add(zone)
        if len(zones) > 1:
            return "", False
        if len(zones) == 0:
            raise SchedulingFailure(
                FAILURE_INTERNAL,
                "application has no scheduled pods, can't make scheduling decisions based on AZ",
            )
        return next(iter(zones)), True

    def _filter_nodes_to_zone(self, nodes: List[Node], zone: str) -> List[Node]:
        """resource.go:463-478."""
        out = []
        for node in nodes:
            zone_label = node.labels.get(ZONE_LABEL)
            if zone_label is None:
                raise SchedulingFailure(
                    FAILURE_INTERNAL, "could not read zone label from node"
                )
            if zone_label == zone:
                out.append(node)
        return out

    # -- metrics -------------------------------------------------------------

    def _report_placement_metrics(self, instance_group, packing_result, zones) -> None:
        executor_nodes = set(packing_result.executor_nodes)
        self._metrics.gauge(
            mnames.DRIVER_EXECUTOR_COLLOCATION,
            1.0 if packing_result.driver_node in executor_nodes else 0.0,
            {"instanceGroup": instance_group},
        )
        self._metrics.gauge(
            mnames.EXECUTOR_NODE_COUNT,
            float(len(executor_nodes)),
            {"instanceGroup": instance_group},
        )
        used_zones = {zones.get(n, "") for n in executor_nodes | {packing_result.driver_node}}
        self._metrics.gauge(
            mnames.APP_CROSS_ZONE,
            1.0 if len(used_zones) > 1 else 0.0,
            {"instanceGroup": instance_group},
        )
