"""Failover reconciliation (reference ``internal/extender/failover.go``).

Async write-back means reservation writes can be lost on leader change;
before serving requests after an idle period the extender rebuilds:
hard reservations for scheduled pods missing from any RR, soft
reservations for DA extra executors, and deletes demands of
now-scheduled pods.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..types.objects import Node, Pod, PodPhase, Reservation
from ..types.resources import (
    NodeGroupResources,
    Resources,
    available_for_nodes,
    group_add,
    usage_for_nodes,
)
from . import labels as L
from .reservations_manager import (
    executor_reservation_name,
    new_resource_reservation,
)
from .sparkpods import AnnotationError, spark_resources

logger = logging.getLogger(__name__)


@dataclass
class _SparkPods:
    """failover.go:84-91: stale state for one app."""

    app_id: str
    inconsistent_driver: Optional[Pod] = None
    inconsistent_executors: List[Pod] = field(default_factory=list)


def sync_resource_reservations_and_demands(extender) -> None:
    """failover.go:43-82.  `extender` is the SparkSchedulerExtender; the
    reconciler reads through its wired components."""
    pods = extender._pod_lister.list()
    nodes = extender._node_informer.list()
    rrs = extender._resource_reservations.list()
    fast = _available_resources_fast(extender, nodes)
    if fast is not None:
        available, ordered_nodes = fast
    else:
        overhead = extender._overhead.get_overhead(nodes)
        soft_overhead = (
            extender._soft_reservation_store.used_soft_reservation_resources()
        )
        available, ordered_nodes = _available_resources_per_instance_group(
            extender._instance_group_label, rrs, nodes, overhead, soft_overhead
        )
    stale = _unreserved_spark_pods_by_spark_id(rrs, extender._soft_reservation_store, pods)
    logger.info("starting reconciliation for %d stale apps", len(stale))

    r = _Reconciler(
        pod_lister=extender._pod_lister,
        resource_reservations=extender._resource_reservations,
        soft_reservations=extender._soft_reservation_store,
        demands=extender._demands,
        available_resources=available,
        ordered_nodes=ordered_nodes,
        instance_group_label=extender._instance_group_label,
    )

    extra_executors_with_no_rrs: Dict[str, List[Pod]] = {}
    for sp in stale.values():
        extra = r.sync_resource_reservations(sp)
        if extra:
            extra_executors_with_no_rrs[sp.app_id] = extra
        r.sync_demands(sp)
    r.sync_soft_reservations(extra_executors_with_no_rrs)


def _unreserved_spark_pods_by_spark_id(
    rrs, soft_store, pods: List[Pod]
) -> Dict[str, _SparkPods]:
    """failover.go:243-280: scheduled spark pods missing from every
    RR.Status.Pods and the soft store."""
    pods_with_rrs = set()
    for rr in rrs:
        for pod_name in rr.status.pods.values():
            pods_with_rrs.add(pod_name)

    by_app: Dict[str, _SparkPods] = {}
    for pod in pods:
        if _is_not_scheduled_spark_pod(pod) or pod.name in pods_with_rrs:
            continue
        if pod.labels.get(L.SPARK_ROLE_LABEL) == L.EXECUTOR and soft_store.executor_has_soft_reservation(pod):
            continue
        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        sp = by_app.setdefault(app_id, _SparkPods(app_id=app_id))
        role = pod.labels.get(L.SPARK_ROLE_LABEL)
        if role == L.DRIVER:
            sp.inconsistent_driver = pod
        elif role == L.EXECUTOR:
            sp.inconsistent_executors.append(pod)
        else:
            logger.error("received non spark pod %s, ignoring", pod.name)
    return by_app


def _is_not_scheduled_spark_pod(pod: Pod) -> bool:
    """failover.go:282-284."""
    return (
        pod.scheduler_name != L.SPARK_SCHEDULER_NAME
        or pod.meta.deletion_timestamp is not None
        or pod.node_name == ""
    )


class _LazyNodeGroupResources(dict):
    """NodeGroupResources materialized on demand from exact integer
    availability rows.  Reconciliation touches only the handful of nodes
    the greedy filler probes, so constructing 3 Quantities for every
    node in a 10k-node snapshot up front (the dominant reconcile cost)
    is wasted work; reads through [] / .get build entries lazily and
    writes behave like a plain dict."""

    def __init__(self, rows_by_name):
        super().__init__()
        self._rows = rows_by_name  # name → int64 base-unit row

    def __missing__(self, name):
        row = self._rows[name]  # KeyError for unknown nodes, like the eager map
        res = _resources_from_base_row(row)
        self[name] = res
        return res

    def get(self, name, default=None):
        try:
            return self[name]
        except KeyError:
            return default


def _resources_from_base_row(row) -> Resources:
    from fractions import Fraction

    from ..utils.quantity import Quantity

    return Resources(
        Quantity(Fraction(int(row[0]), 1000)),
        Quantity(int(row[1])),
        Quantity(Fraction(int(row[2]), 1000)),
    )


def _schedulable_nodes_by_group(
    instance_group_label: str, nodes: List[Node]
) -> Dict[str, List[Node]]:
    """failover.go:286-323's node-eligibility step, shared by both
    availability lanes: ready schedulable nodes grouped by instance
    group, newest first."""
    ordered = sorted(nodes, key=lambda n: n.creation_timestamp, reverse=True)
    schedulable: Dict[str, List[Node]] = {}
    for n in ordered:
        if n.unschedulable or not n.ready:
            continue
        group = n.labels.get(instance_group_label, "")
        schedulable.setdefault(group, []).append(n)
    return schedulable


def _available_resources_fast(extender, nodes: List[Node]):
    """The reconcile availability map served from the tensor mirror:
    identical values to _available_resources_per_instance_group
    (mirror avail = allocatable − reservations − overhead − soft, proven
    by tests/test_tensor_snapshot.py), with per-node Resources built
    only when the reconciler actually reads them.  Returns None when the
    mirror fast paths are disabled (_fast_path_ok, the same kill switch
    the extender's other mirror lanes honor), the mirror is absent or
    inexact, or it is out of step with the informer."""
    cache = getattr(extender, "_tensor_snapshot", None)
    if cache is None or not getattr(extender, "_fast_path_ok", False):
        return None
    snap = cache.snapshot()
    if not snap.exact:
        return None
    index = snap.name_index
    rows = snap.avail
    schedulable = _schedulable_nodes_by_group(extender._instance_group_label, nodes)
    available = {}
    for group, ns in schedulable.items():
        group_rows = {}
        for n in ns:
            i = index.get(n.name)
            if i is None:
                return None  # informer/mirror drift: take the exact path
            group_rows[n.name] = rows[i]
        available[group] = _LazyNodeGroupResources(group_rows)
    return available, schedulable


def _available_resources_per_instance_group(
    instance_group_label: str,
    rrs,
    nodes: List[Node],
    overhead: NodeGroupResources,
    soft_reservation_overhead: NodeGroupResources,
):
    """failover.go:286-323: ready schedulable nodes grouped by instance
    group (newest first), availability = allocatable − RRs − overhead −
    soft usage."""
    schedulable = _schedulable_nodes_by_group(instance_group_label, nodes)

    usages = usage_for_nodes(rrs)
    group_add(usages, overhead)
    group_add(usages, soft_reservation_overhead)
    available = {
        group: available_for_nodes(ns, usages) for group, ns in schedulable.items()
    }
    return available, schedulable


@dataclass
class _Reconciler:
    """failover.go:95-103."""

    pod_lister: object
    resource_reservations: object
    soft_reservations: object
    demands: object
    available_resources: Dict[str, NodeGroupResources]
    ordered_nodes: Dict[str, List[Node]]
    instance_group_label: str

    def sync_resource_reservations(self, sp: _SparkPods) -> List[Pod]:
        """failover.go:105-163."""
        extra_executors: List[Pod] = []
        if sp.inconsistent_driver is None and sp.inconsistent_executors:
            # driver keeps its RR: claim reservations for orphan executors
            exec0 = sp.inconsistent_executors[0]
            rr = self.resource_reservations.get(exec0.namespace, sp.app_id)
            if rr is None:
                logger.error("resource reservation deleted, ignoring %s", sp.app_id)
                return []
            new_rr = self._patch_resource_reservation(sp.inconsistent_executors, rr.deepcopy())
            if new_rr is None:
                return []
            pods_with_rr = set(new_rr.status.pods.values())
            for executor in sp.inconsistent_executors:
                if executor.name not in pods_with_rr:
                    extra_executors.append(executor)
        elif sp.inconsistent_driver is not None:
            # driver stale: a fresh RR must be constructed
            try:
                app_resources = self._get_app_resources(sp)
            except (AnnotationError, KeyError) as err:
                logger.error("could not get app resources for %s: %s", sp.app_id, err)
                return []
            group, _ = L.find_instance_group_from_pod_spec(
                sp.inconsistent_driver, self.instance_group_label
            )
            end_idx = min(len(sp.inconsistent_executors), app_resources.min_executor_count)
            executors_up_to_min = sp.inconsistent_executors[:end_idx]
            extra_executors = sp.inconsistent_executors[end_idx:]

            built = self._construct_resource_reservation(
                sp.inconsistent_driver, executors_up_to_min, group, app_resources
            )
            if built is None:
                return []
            new_rr, reserved = built
            try:
                self.resource_reservations.create(new_rr)
            except Exception:
                logger.info("resource reservation already exists for %s, force updating", sp.app_id)
                try:
                    self.resource_reservations.update(new_rr)
                except Exception:
                    logger.error("resource reservation deleted, ignoring %s", sp.app_id)
                    return []
            group_avail = self.available_resources.get(group)
            if group_avail is not None:
                for node, res in reserved.items():
                    group_avail[node] = group_avail.get(node, Resources.zero()).sub(res)
        return extra_executors

    def sync_demands(self, sp: _SparkPods) -> None:
        """failover.go:165-172."""
        if sp.inconsistent_driver is not None:
            self.demands.delete_demand_if_exists(sp.inconsistent_driver, "Reconciler")
        for e in sp.inconsistent_executors:
            self.demands.delete_demand_if_exists(e, "Reconciler")

    def sync_soft_reservations(self, extra_executors_by_app: Dict[str, List[Pod]]) -> None:
        """failover.go:174-212."""
        self._sync_application_soft_reservations()
        for app_id, extra_executors in extra_executors_by_app.items():
            driver = self.pod_lister.get_driver_pod_for_executor(extra_executors[0])
            if driver is None:
                logger.error("error getting driver pod for app %s, skipping", app_id)
                continue
            try:
                app_resources = spark_resources(driver)
            except AnnotationError:
                logger.exception("error getting spark resources for app %s, skipping", app_id)
                continue
            max_extra = app_resources.max_executor_count - app_resources.min_executor_count
            for i, extra_executor in enumerate(extra_executors):
                if i >= max_extra:
                    break
                try:
                    self.soft_reservations.add_reservation_for_pod(
                        app_id,
                        extra_executor.name,
                        Reservation.for_resources(
                            extra_executor.node_name, app_resources.executor_resources
                        ),
                    )
                except KeyError:
                    logger.exception("failed to add soft reservation on failover")

    def _sync_application_soft_reservations(self) -> None:
        """failover.go:216-241: prefill the store with running DA drivers."""
        drivers = self.pod_lister.list(label_selector={L.SPARK_ROLE_LABEL: L.DRIVER})
        for d in drivers:
            if (
                d.scheduler_name != L.SPARK_SCHEDULER_NAME
                or d.node_name == ""
                or d.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
            ):
                continue
            try:
                app_resources = spark_resources(d)
            except AnnotationError:
                logger.exception("failed to get driver resources, skipping driver %s", d.name)
                continue
            if app_resources.max_executor_count > app_resources.min_executor_count:
                self.soft_reservations.create_soft_reservation_if_not_exists(
                    d.labels.get(L.SPARK_APP_ID_LABEL, "")
                )

    def _patch_resource_reservation(self, execs: List[Pod], rr):
        """failover.go:325-346: claim reservations on matching nodes for
        orphan executors (unbound, or bound to a gone/terminated pod)."""
        for e in execs:
            for name, reservation in rr.spec.reservations.items():
                if reservation.node != e.node_name:
                    continue
                current_pod_name = rr.status.pods.get(name)
                if current_pod_name is None:
                    rr.status.pods[name] = e.name
                    break
                pod = self.pod_lister.informer.get(e.namespace, current_pod_name)
                if pod is None or L.is_pod_terminated(pod):
                    rr.status.pods[name] = e.name
                    break
        try:
            self.resource_reservations.update(rr)
        except Exception:
            logger.error("resource reservation deleted, ignoring %s", rr.name)
            return None
        return rr

    def _construct_resource_reservation(
        self, driver: Pod, executors: List[Pod], group: str, app_resources
    ):
        """failover.go:348-390."""
        nodes = self.ordered_nodes.get(group)
        available = self.available_resources.get(group)
        if nodes is None or available is None:
            logger.error("instance group %r not found", group)
            return None

        reserved_node_names: List[str] = []
        reserved: NodeGroupResources = {}
        to_assign = app_resources.min_executor_count - len(executors)
        if to_assign > 0:
            reserved_node_names, reserved = _find_nodes(
                to_assign, app_resources.executor_resources, available, nodes
            )
            if len(reserved_node_names) < to_assign:
                logger.error("could not reserve space for all executors of %s", driver.name)

        executor_nodes = [e.node_name for e in executors] + reserved_node_names
        rr = new_resource_reservation(
            driver.node_name,
            executor_nodes,
            driver,
            app_resources.driver_resources,
            app_resources.executor_resources,
        )
        for i, e in enumerate(executors):
            rr.status.pods[executor_reservation_name(i)] = e.name
        return rr, reserved

    def _get_app_resources(self, sp: _SparkPods):
        """failover.go:392-407."""
        if sp.inconsistent_driver is not None:
            driver = sp.inconsistent_driver
        elif sp.inconsistent_executors:
            driver = self.pod_lister.get_driver_pod_for_executor(sp.inconsistent_executors[0])
            if driver is None:
                raise KeyError("error getting driver pod for executor")
        else:
            raise KeyError("no inconsistent driver or executor")
        return spark_resources(driver)


def _find_nodes(
    executor_count: int,
    executor_resources: Resources,
    available_resources: NodeGroupResources,
    ordered_nodes: List[Node],
):
    """failover.go:412-436: greedy fill in node order.

    QUIRK: the failed probe is NOT subtracted back (failover.go:424-427),
    so the returned reserved map is inflated by one executor per exhausted
    node — and that inflated map is subtracted from instance-group
    availability by the caller.  Reference behavior, kept for parity.
    """
    executor_node_names: List[str] = []
    reserved: NodeGroupResources = {}
    for n in ordered_nodes:
        if n.name not in reserved:
            reserved[n.name] = Resources.zero()
        while True:
            reserved[n.name] = reserved[n.name].add(executor_resources)
            if reserved[n.name].greater_than(available_resources.get(n.name, Resources.zero())):
                break
            executor_node_names.append(n.name)
            if len(executor_node_names) == executor_count:
                return executor_node_names, reserved
    return executor_node_names, reserved
