"""Debug invariant checker — the sanitizer/race-detection analog of
SURVEY §5 (the reference leans on Go's race detector + single-writer
design; here the state invariants are checked directly).

Enabled with SCHED_DEBUG_INVARIANTS=1 (or explicitly in tests): after
every Predicate the scheduler's state must satisfy:

  I1  every RR status.pods key names an existing reservation;
  I2  no pod is bound to two reservations of the same app;
  I3  soft reservations only exist for apps with an RR (or pending
      creation in the local cache);
  I4  per-node hard+soft reserved resources never exceed the node's
      allocatable (capacity safety — gang admission must not overbook);
  I5  the tensor mirror (when present) matches the Quantity-path
      availability exactly.

Violations raise InvariantViolation (tests) or log CRITICAL (prod).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


class InvariantViolation(AssertionError):
    pass


def enabled() -> bool:
    return os.environ.get("SCHED_DEBUG_INVARIANTS") == "1"


def check(server, raise_on_violation: bool = True) -> list:
    """Run all invariants against a wired Server; returns violations."""
    violations = []

    rrs = server.resource_reservation_cache.list()
    soft = server.soft_reservation_store.get_all_soft_reservations_copy()

    # I1 + I2
    for rr in rrs:
        bound = {}
        for res_name, pod_name in rr.status.pods.items():
            if res_name not in rr.spec.reservations:
                violations.append(
                    f"I1: {rr.name} status.pods[{res_name}] has no reservation"
                )
            if pod_name in bound:
                violations.append(
                    f"I2: {rr.name} pod {pod_name} bound to {res_name} and {bound[pod_name]}"
                )
            bound[pod_name] = res_name

    # I3
    rr_apps = {rr.name for rr in rrs}
    for app_id in soft:
        if app_id not in rr_apps:
            violations.append(f"I3: soft reservations for {app_id} without an RR")

    # I4
    from ..types.resources import Resources, usage_for_nodes

    usage = usage_for_nodes(rrs)
    for node_name, res in server.soft_reservation_store.used_soft_reservation_resources().items():
        usage[node_name] = usage.get(node_name, Resources.zero()).add(res)
    nodes = {n.name: n for n in server.node_informer.list()}
    for node_name, used in usage.items():
        node = nodes.get(node_name)
        if node is None:
            continue  # reservation on a departed node: reconciliation's job
        if used.greater_than(node.allocatable):
            violations.append(
                f"I4: node {node_name} overbooked: reserved {used} > allocatable {node.allocatable}"
            )

    # I5
    snapshot_cache = getattr(server, "tensor_snapshot", None)
    if snapshot_cache is not None:
        import numpy as np

        from ..ops.tensorize import _resources_to_base
        from ..types.resources import node_scheduling_metadata_for_nodes

        snap = snapshot_cache.snapshot()
        if snap.exact:
            overhead = server.overhead_computer.get_overhead(list(nodes.values()))
            usage2 = server.resource_reservation_manager.get_reserved_resources()
            metadata = node_scheduling_metadata_for_nodes(
                nodes.values(), usage2, overhead
            )
            mirror = {name: snap.avail[i] for i, name in enumerate(snap.names)}
            for name, md in metadata.items():
                row, exact = _resources_to_base(md.available)
                if not exact:
                    continue
                got = mirror.get(name)
                if got is None or not (got == np.array(row, np.int64)).all():
                    violations.append(
                        f"I5: tensor mirror drift on {name}: {got} != {row}"
                    )

    if violations:
        for v in violations:
            logger.critical("scheduler invariant violated: %s", v)
        if raise_on_violation:
            raise InvariantViolation("; ".join(violations))
    return violations
