"""Labels/annotations contract + pod classification helpers.

internal/common/constants.go:17-51, internal/common/utils/pods.go,
internal/podspec.go.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..types.objects import Pod

SPARK_SCHEDULER_NAME = "spark-scheduler"
SPARK_ROLE_LABEL = "spark-role"
SPARK_APP_ID_LABEL = "spark-app-id"
DRIVER = "driver"
EXECUTOR = "executor"

DRIVER_CPU = "spark-driver-cpu"
DRIVER_MEMORY = "spark-driver-mem"
DRIVER_NVIDIA_GPUS = "spark-driver-nvidia.com/gpu"
EXECUTOR_CPU = "spark-executor-cpu"
EXECUTOR_MEMORY = "spark-executor-mem"
EXECUTOR_NVIDIA_GPUS = "spark-executor-nvidia.com/gpu"
DYNAMIC_ALLOCATION_ENABLED = "spark-dynamic-allocation-enabled"
EXECUTOR_COUNT = "spark-executor-count"
DA_MIN_EXECUTOR_COUNT = "spark-dynamic-allocation-min-executor-count"
DA_MAX_EXECUTOR_COUNT = "spark-dynamic-allocation-max-executor-count"

# default instance-group label with back-compat fallback
# (cmd/server.go:67-71)
DEFAULT_INSTANCE_GROUP_LABEL = "resource_channel"


def is_spark_scheduler_pod(pod: Pod) -> bool:
    """utils/pods.go:29-33: has a spark role and targets our scheduler."""
    return bool(pod.labels.get(SPARK_ROLE_LABEL)) and pod.scheduler_name == SPARK_SCHEDULER_NAME


def is_spark_scheduler_executor_pod(pod: Pod) -> bool:
    """utils/pods.go:36-40."""
    return is_spark_scheduler_pod(pod) and pod.labels.get(SPARK_ROLE_LABEL) == EXECUTOR


def is_pod_terminated(pod: Pod) -> bool:
    """utils/pods.go:69-75: at least one container status, all terminated."""
    return pod.is_terminated()


def find_instance_group_from_pod_spec(pod: Pod, instance_group_label: str) -> Tuple[str, bool]:
    """internal/podspec.go:29-53: instance group from nodeSelector or
    required node affinity."""
    value = pod.node_selector.get(instance_group_label)
    if value is not None:
        return value, True
    values = pod.node_affinity.get(instance_group_label)
    if values:
        return values[0], True
    for term in pod.affinity_terms:
        for key, operator, term_values in term:
            if key == instance_group_label and operator == "In" and term_values:
                return term_values[0], True
    return "", False


def match_pod_instance_group(pod_a: Pod, pod_b: Pod, instance_group_label: str) -> bool:
    """internal/podspec.go:22-26."""
    group_a, ok_a = find_instance_group_from_pod_spec(pod_a, instance_group_label)
    group_b, ok_b = find_instance_group_from_pod_spec(pod_b, instance_group_label)
    return ok_a and ok_b and group_a == group_b


def on_pod_scheduled(old: Optional[Pod], new: Pod) -> bool:
    """utils/pods.go:78-103 transition detector: pod just got a node."""
    if new.node_name == "":
        return False
    return old is None or old.node_name == ""
