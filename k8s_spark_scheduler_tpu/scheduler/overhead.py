"""Overhead computer (reference ``internal/extender/overhead.go``):
event-driven tracking of requests of pods without reservations.

Overhead = requests of pods that have a node but no reservation of ours;
non-schedulable overhead = the subset not managed by this scheduler at
all (daemonsets etc.).  Pod requests = max(sum of containers, each init
container) per dimension (overhead.go:195-209)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Tuple

from ..kube.informer import Informer
from ..types.objects import Node, Pod
from ..types.resources import NodeGroupResources, Resources
from . import labels as L
from ..analysis.guarded import guarded_by


def pod_to_resources(pod: Pod) -> Resources:
    """max(sum containers, init containers) (overhead.go:195-209)."""
    total = Resources.zero()
    for c in pod.containers:
        total = total.add(c.requests)
    for c in pod.init_containers:
        total = total.set_max(c.requests)
    return total


@dataclass
class _PodRequestInfo:
    pod_name: str
    pod_namespace: str
    requests: Resources


@guarded_by("_lock", "_requests")
class OverheadComputer:
    """overhead.go:33-209."""

    def __init__(self, pod_informer: Informer, resource_reservation_manager):
        self._pod_informer = pod_informer
        self._rrm = resource_reservation_manager
        self._lock = threading.RLock()
        # node → {pod uid → request info}
        self._requests: Dict[str, Dict[str, _PodRequestInfo]] = {}
        pod_informer.add_event_handler(
            on_add=self._add_pod_requests,
            on_update=self._on_update,
            on_delete=self._delete_pod_requests,
        )

    # informer wiring: the reference filters to pods with a nodeName
    # (overhead.go:72-79, 155-161); updates matter here because our
    # informer delivers bind transitions as MODIFIED

    def _on_update(self, old: Pod, new: Pod) -> None:
        if new.node_name != "":
            self._add_pod_requests(new)

    def _add_pod_requests(self, pod: Pod) -> None:
        if pod.node_name == "":
            return
        with self._lock:
            self._requests.setdefault(pod.node_name, {})[pod.meta.uid] = _PodRequestInfo(
                pod.name, pod.namespace, pod_to_resources(pod)
            )

    def _delete_pod_requests(self, pod: Pod) -> None:
        if pod.node_name == "":
            return
        with self._lock:
            node_requests = self._requests.get(pod.node_name)
            if node_requests is None or pod.meta.uid not in node_requests:
                return
            del node_requests[pod.meta.uid]
            if not node_requests:
                del self._requests[pod.node_name]

    # -- queries -------------------------------------------------------------

    def get_overhead(self, nodes: Iterable[Node]) -> NodeGroupResources:
        return {n.name: self._compute_node_overhead(n.name)[0] for n in nodes}

    def get_non_schedulable_overhead(self, nodes: Iterable[Node]) -> NodeGroupResources:
        """Overhead from pods not managed by this scheduler (used by the
        unschedulable-pod marker, unschedulablepods.go:149-151)."""
        return {n.name: self._compute_node_overhead(n.name)[1] for n in nodes}

    def _compute_node_overhead(self, node_name: str) -> Tuple[Resources, Resources]:
        """overhead.go:120-153."""
        with self._lock:
            node_requests = dict(self._requests.get(node_name, {}))
        overhead = Resources.zero()
        non_schedulable = Resources.zero()
        for info in node_requests.values():
            pod = self._pod_informer.get(info.pod_namespace, info.pod_name)
            if pod is None:
                continue
            if not self._rrm.pod_has_reservation(pod):
                overhead = overhead.add(info.requests)
                if pod.scheduler_name != L.SPARK_SCHEDULER_NAME:
                    non_schedulable = non_schedulable.add(info.requests)
        return overhead, non_schedulable
