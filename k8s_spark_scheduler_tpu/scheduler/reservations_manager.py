"""ResourceReservationManager (reference
``internal/extender/resourcereservations.go``): the single authority for
creating/binding/querying hard (CRD) and soft (in-memory) reservations,
unbound-reservation discovery, and dynamic-allocation compaction."""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from .. import timesource
from ..kube.informer import Informer
from ..analysis.guarded import guarded_by
from ..state.softreservations import SoftReservation, SoftReservationStore
from ..state.typed_caches import ResourceReservationCache
from ..types.objects import (
    ObjectMeta,
    OwnerReference,
    Pod,
    Reservation,
    ResourceReservation,
    ResourceReservationSpec,
    ResourceReservationStatus,
    now,
)
from ..types.resources import NodeGroupResources, Resources, usage_for_nodes
from . import labels as L
from .sparkpods import SparkApplicationResources, SparkPodLister, spark_resources

logger = logging.getLogger(__name__)

# slow time-to-first-bind log threshold (resourcereservations.go:42-44)
SLOW_LOG_DURATION_SECONDS = 120.0

DRIVER_RESERVATION_NAME = "driver"


def executor_reservation_name(i: int) -> str:
    """resourcereservations.go:531-533 (1-based)."""
    return f"executor-{i + 1}"


def new_resource_reservation(
    driver_node: str,
    executor_nodes: List[str],
    driver: Pod,
    driver_resources: Resources,
    executor_resources: Resources,
) -> ResourceReservation:
    """resourcereservations.go:491-528."""
    reservations: Dict[str, Reservation] = {
        DRIVER_RESERVATION_NAME: Reservation.for_resources(driver_node, driver_resources)
    }
    for idx, node_name in enumerate(executor_nodes):
        reservations[executor_reservation_name(idx)] = Reservation.for_resources(
            node_name, executor_resources
        )
    app_id = driver.labels.get(L.SPARK_APP_ID_LABEL, "")
    return ResourceReservation(
        meta=ObjectMeta(
            name=app_id,
            namespace=driver.namespace,
            creation_timestamp=now(),
            labels={L.SPARK_APP_ID_LABEL: app_id},
            owner_references=[OwnerReference(kind="Pod", name=driver.name, uid=driver.meta.uid)],
        ),
        spec=ResourceReservationSpec(reservations=reservations),
        status=ResourceReservationStatus(pods={DRIVER_RESERVATION_NAME: driver.name}),
    )


@guarded_by("_da_compaction_lock", "_da_compaction_apps")
class ResourceReservationManager:
    """resourcereservations.go:68-102."""

    def __init__(
        self,
        resource_reservations: ResourceReservationCache,
        soft_reservation_store: SoftReservationStore,
        pod_lister: SparkPodLister,
        pod_informer: Informer,
        metrics=None,
        tracer=None,
    ):
        from ..metrics.registry import default_registry
        from ..tracing import default_tracer

        self._resource_reservations = resource_reservations
        self._soft_reservations = soft_reservation_store
        self._metrics = metrics if metrics is not None else default_registry
        self._tracer = tracer if tracer is not None else default_tracer
        self._pod_lister = pod_lister
        self._mutex = threading.RLock()
        self._da_compaction_apps: Dict[str, str] = {}  # appID → namespace
        self._da_compaction_lock = threading.Lock()
        pod_informer.add_event_handler(
            on_delete=self._on_executor_pod_deletion,
            filter_func=L.is_spark_scheduler_executor_pod,
        )

    # -- reads ---------------------------------------------------------------

    def get_resource_reservation(self, app_id: str, namespace: str) -> Optional[ResourceReservation]:
        return self._resource_reservations.get(namespace, app_id)

    def get_soft_resource_reservation(self, app_id: str) -> Tuple[SoftReservation, bool]:
        return self._soft_reservations.get_soft_reservation(app_id)

    def pod_has_reservation(self, pod: Pod) -> bool:
        """resourcereservations.go:115-132."""
        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL)
        if app_id is None:
            return False
        rr = self.get_resource_reservation(app_id, pod.namespace)
        if rr is not None and pod.name in rr.status.pods.values():
            return True
        if pod.labels.get(L.SPARK_ROLE_LABEL) == L.EXECUTOR:
            if self._soft_reservations.executor_has_soft_reservation(pod):
                return True
        return False

    def get_reserved_resources(self) -> NodeGroupResources:
        """All hard reservations + soft reservations per node
        (resourcereservations.go:258-263)."""
        usage = usage_for_nodes(self._resource_reservations.list())
        for node, r in self._soft_reservations.used_soft_reservation_resources().items():
            usage[node] = usage.get(node, Resources.zero()).add(r)
        return usage

    # -- creation ------------------------------------------------------------

    def create_reservations(
        self,
        driver: Pod,
        application_resources: SparkApplicationResources,
        driver_node: str,
        executor_nodes: List[str],
    ) -> ResourceReservation:
        """resourcereservations.go:136-159."""
        app_id = driver.labels.get(L.SPARK_APP_ID_LABEL, "")
        with self._tracer.span(
            "reservation.writeback",
            {"app": app_id, "executors": len(executor_nodes)},
        ) as sp:
            rr = self.get_resource_reservation(app_id, driver.namespace)
            sp.tag("replay", rr is not None)
            if rr is None:
                rr = new_resource_reservation(
                    driver_node,
                    executor_nodes,
                    driver,
                    application_resources.driver_resources,
                    application_resources.executor_resources,
                )
                self._resource_reservations.create(rr)
                # the async write-back queue drains to the API server;
                # its depth at enqueue time is the staleness signal for
                # a slow write-back investigation
                try:
                    sp.tag(
                        "writeQueueDepth",
                        sum(self._resource_reservations.inflight_queue_lengths()),
                    )
                except Exception:
                    pass

            if application_resources.max_executor_count > application_resources.min_executor_count:
                # only DA apps can request extra executors
                self._soft_reservations.create_soft_reservation_if_not_exists(app_id)
            return rr

    # -- executor binding ----------------------------------------------------

    def find_already_bound_reservation_node(self, executor: Pod) -> Tuple[Optional[str], bool]:
        """Idempotent-retry path (resourcereservations.go:163-179)."""
        rr = self.get_resource_reservation(
            executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
        )
        if rr is None:
            raise KeyError("failed to get resource reservations")
        for name, reservation in rr.spec.reservations.items():
            if rr.status.pods.get(name) == executor.name:
                return reservation.node, True
        sr = self._soft_reservations.get_executor_soft_reservation(executor)
        if sr is not None:
            return sr.node, True
        return None, False

    def find_unbound_reservation_nodes(self, executor: Pod) -> Tuple[List[str], bool]:
        """resourcereservations.go:184-196."""
        unbound = self._get_unbound_reservations(
            executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
        )
        nodes = sorted(set(unbound.values()))
        return nodes, len(nodes) > 0

    def get_remaining_allowed_executor_count(self, app_id: str, namespace: str) -> int:
        """unbound hard reservations + free soft spots
        (resourcereservations.go:199-209)."""
        unbound = self._get_unbound_reservations(app_id, namespace)
        return len(unbound) + self._get_free_soft_reservation_spots(app_id, namespace)

    def reserve_for_executor_on_unbound_reservation(self, executor: Pod, node: str) -> None:
        """resourcereservations.go:213-228."""
        with self._mutex:
            unbound = self._get_unbound_reservations(
                executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
            )
            for reservation_name, reservation_node in unbound.items():
                if reservation_node == node:
                    self._bind_executor_to_resource_reservation(executor, reservation_name, node)
                    return
        raise RuntimeError("failed to find free reservation on requested node for executor")

    def reserve_for_executor_on_rescheduled_node(self, executor: Pod, node: str) -> None:
        """Rebind an unbound hard reservation onto a new node, else consume
        a soft spot (resourcereservations.go:232-255)."""
        with self._mutex:
            app_id = executor.labels.get(L.SPARK_APP_ID_LABEL, "")
            unbound = self._get_unbound_reservations(app_id, executor.namespace)
            if unbound:
                reservation_name = next(iter(unbound))
                self._bind_executor_to_resource_reservation(executor, reservation_name, node)
                return
            free_spots = self._get_free_soft_reservation_spots(app_id, executor.namespace)
            if free_spots > 0:
                self._bind_executor_to_soft_reservation(executor, node)
                return
        raise RuntimeError("failed to find free reservation for executor")

    # -- DA compaction -------------------------------------------------------

    def compact_dynamic_allocation_applications(self) -> None:
        """Move soft reservations onto hard reservations freed by dead
        executors (resourcereservations.go:268-298)."""
        apps = self._drain_da_compaction_apps()
        with self._mutex:
            for app_id, namespace in apps.items():
                sr, ok = self._soft_reservations.get_soft_reservation(app_id)
                if not ok:
                    continue
                pods = self._get_active_pods(app_id, namespace)
                for pod_name in list(sr.reservations):
                    pod = pods.get(pod_name)
                    if pod is None:
                        continue  # no longer active
                    self._compact_soft_reservation_pod(pod)

    def _compact_soft_reservation_pod(self, pod: Pod) -> None:
        """resourcereservations.go:302-336 (caller holds the mutex)."""
        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        try:
            unbound = self._get_unbound_reservations(app_id, pod.namespace)
        except KeyError:
            logger.exception("failed to get unbound reservations for %s", pod.name)
            return
        if not unbound:
            return
        # prefer an unbound reservation on the pod's own node
        for reservation_name, reservation_node in unbound.items():
            if reservation_node == pod.node_name:
                self._bind_executor_to_resource_reservation(pod, reservation_name, reservation_node)
                self._soft_reservations.remove_executor_reservation(app_id, pod.name)
                return
        # cross-node: bind keeping the RESERVATION's node (the reference
        # passes unboundReservationsToNodes[name], resourcereservations.go
        # :326-335 — the reservation stays on its node and, since the pod
        # runs elsewhere, remains discoverable as unbound for rebinding)
        reservation_name = next(iter(unbound))
        self._bind_executor_to_resource_reservation(
            pod, reservation_name, unbound[reservation_name]
        )
        self._soft_reservations.remove_executor_reservation(app_id, pod.name)

    def _drain_da_compaction_apps(self) -> Dict[str, str]:
        with self._da_compaction_lock:
            drained = dict(self._da_compaction_apps)
            self._da_compaction_apps = {}
            return drained

    def _on_executor_pod_deletion(self, pod: Pod) -> None:
        """resourcereservations.go:469-488: queue DA apps for compaction
        when an executor without a soft reservation dies (it may free a
        hard reservation a soft-reserved executor can take)."""
        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        _, has_soft_store = self._soft_reservations.get_soft_reservation(app_id)
        if has_soft_store and not self._soft_reservations.executor_has_soft_reservation(pod):
            with self._da_compaction_lock:
                self._da_compaction_apps[app_id] = pod.namespace

    # -- internals -----------------------------------------------------------

    def _bind_executor_to_resource_reservation(
        self, executor: Pod, reservation_name: str, node: str
    ) -> None:
        """resourcereservations.go:349-389."""
        rr = self.get_resource_reservation(
            executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
        )
        if rr is None:
            raise KeyError(f"failed to get resource reservation {reservation_name}")
        copy_rr = rr.deepcopy()
        reservation = copy_rr.spec.reservations[reservation_name]
        reservation.node = node
        first_bind = reservation_name not in rr.status.pods
        copy_rr.status.pods[reservation_name] = executor.name
        self._resource_reservations.update(copy_rr)

        # time-to-first-bind metric + slow log, only on the reservation's
        # first binding (resourcereservations.go:364-387)
        if first_bind and rr.meta.creation_timestamp:

            from ..metrics import names as mnames

            duration = timesource.now() - rr.meta.creation_timestamp
            self._metrics.histogram(mnames.TIME_TO_FIRST_BIND, duration)
            snap = self._metrics.get_histogram(mnames.TIME_TO_FIRST_BIND)
            self._metrics.gauge(mnames.TIME_TO_FIRST_BIND_MEDIAN, snap["p50"])
            self._metrics.gauge(mnames.TIME_TO_FIRST_BIND_MEAN, snap["mean"])
            if duration > SLOW_LOG_DURATION_SECONDS:
                logger.warning(
                    "time to first executor bind above threshold: "
                    "duration=%.0fs appID=%s node=%s executor=%s reservation=%s",
                    duration,
                    rr.labels.get(L.SPARK_APP_ID_LABEL, ""),
                    node,
                    executor.name,
                    reservation_name,
                )

    def _bind_executor_to_soft_reservation(self, executor: Pod, node: str) -> None:
        """resourcereservations.go:391-409."""
        driver = self._pod_lister.get_driver_pod_for_executor(executor)
        if driver is None:
            raise KeyError("failed to get driver pod for executor")
        app_resources = spark_resources(driver)
        reservation = Reservation.for_resources(node, app_resources.executor_resources)
        self._soft_reservations.add_reservation_for_pod(
            driver.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.name, reservation
        )

    def _get_unbound_reservations(self, app_id: str, namespace: str) -> Dict[str, str]:
        """reservationName → node for reservations that are unbound, bound
        to a dead executor, or bound to an executor now on another node
        (resourcereservations.go:413-432)."""
        rr = self.get_resource_reservation(app_id, namespace)
        if rr is None:
            raise KeyError("failed to get resource reservation")
        active_pods = self._get_active_pods(app_id, namespace)
        unbound: Dict[str, str] = {}
        for reservation_name, reservation in rr.spec.reservations.items():
            pod_identifier = rr.status.pods.get(reservation_name)
            pod = active_pods.get(pod_identifier) if pod_identifier is not None else None
            if (
                pod_identifier is None
                or pod is None
                or (pod.node_name != "" and pod.node_name != reservation.node)
            ):
                unbound[reservation_name] = reservation.node
        return unbound

    def _get_free_soft_reservation_spots(self, app_id: str, namespace: str) -> int:
        """resourcereservations.go:434-451."""
        sr, ok = self._soft_reservations.get_soft_reservation(app_id)
        if not ok:
            return 0
        used = len(sr.reservations)
        driver = self._pod_lister.get_driver_pod(app_id, namespace)
        if driver is None:
            raise KeyError("failed to get driver pod")
        app_resources = spark_resources(driver)
        max_extra = app_resources.max_executor_count - app_resources.min_executor_count
        return max(max_extra - used, 0)

    def _get_active_pods(self, app_id: str, namespace: str) -> Dict[str, Pod]:
        """resourcereservations.go:454-467."""
        pods = self._pod_lister.list(
            namespace=namespace, label_selector={L.SPARK_APP_ID_LABEL: app_id}
        )
        return {p.name: p for p in pods if not L.is_pod_terminated(p)}
