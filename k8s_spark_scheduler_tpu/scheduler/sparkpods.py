"""Spark pod lister: FIFO queue view + annotation parsing
(reference ``internal/extender/sparkpods.go``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..kube.informer import Informer
from ..types.objects import Pod
from ..types.resources import NodeGroupResources, Resources
from ..utils.quantity import Quantity
from . import labels as L


@dataclass
class SparkApplicationResources:
    """internal/types SparkApplicationResources."""

    driver_resources: Resources
    executor_resources: Resources
    min_executor_count: int
    max_executor_count: int


class AnnotationError(ValueError):
    pass


def spark_resources(pod: Pod) -> SparkApplicationResources:
    """Parse the app's resource annotations (sparkpods.go:73-137).

    Error cases mirror the reference: bad DA boolean, missing
    executor-count without DA, missing DA min/max with DA, missing
    driver/executor cpu/mem, unparseable quantity.
    """
    annotations = pod.annotations
    da_raw = annotations.get(L.DYNAMIC_ALLOCATION_ENABLED)
    dynamic_allocation_enabled = False
    if da_raw is not None:
        if da_raw.lower() in ("true", "1", "t"):
            dynamic_allocation_enabled = True
        elif da_raw.lower() in ("false", "0", "f"):
            dynamic_allocation_enabled = False
        else:
            raise AnnotationError(
                "annotation DynamicAllocationEnabled could not be parsed as a boolean"
            )

    parsed: Dict[str, Quantity] = {}
    for key in (
        L.DRIVER_CPU,
        L.DRIVER_MEMORY,
        L.DRIVER_NVIDIA_GPUS,
        L.EXECUTOR_CPU,
        L.EXECUTOR_MEMORY,
        L.EXECUTOR_NVIDIA_GPUS,
        L.EXECUTOR_COUNT,
        L.DA_MIN_EXECUTOR_COUNT,
        L.DA_MAX_EXECUTOR_COUNT,
    ):
        value = annotations.get(key)
        if value is None:
            if key in (L.DRIVER_NVIDIA_GPUS, L.EXECUTOR_NVIDIA_GPUS):
                continue  # optional: GPUs not required
            if not dynamic_allocation_enabled and key == L.EXECUTOR_COUNT:
                raise AnnotationError(
                    "annotation ExecutorCount is required when DynamicAllocationEnabled is false"
                )
            if dynamic_allocation_enabled and key in (
                L.DA_MIN_EXECUTOR_COUNT,
                L.DA_MAX_EXECUTOR_COUNT,
            ):
                raise AnnotationError(
                    f"annotation {key} is required when DynamicAllocationEnabled is true"
                )
            if key in (L.EXECUTOR_COUNT, L.DA_MIN_EXECUTOR_COUNT, L.DA_MAX_EXECUTOR_COUNT):
                continue  # not needed in this mode
            raise AnnotationError(f"annotation {key} is missing from driver")
        try:
            parsed[key] = Quantity(value)
        except ValueError:
            raise AnnotationError(
                f"annotation {key} does not have a parseable value {value}"
            ) from None

    if dynamic_allocation_enabled:
        min_executor_count = parsed[L.DA_MIN_EXECUTOR_COUNT].value()
        max_executor_count = parsed[L.DA_MAX_EXECUTOR_COUNT].value()
    else:
        min_executor_count = parsed[L.EXECUTOR_COUNT].value()
        max_executor_count = min_executor_count

    zero = Quantity(0)
    return SparkApplicationResources(
        driver_resources=Resources(
            parsed[L.DRIVER_CPU], parsed[L.DRIVER_MEMORY], parsed.get(L.DRIVER_NVIDIA_GPUS, zero)
        ),
        executor_resources=Resources(
            parsed[L.EXECUTOR_CPU],
            parsed[L.EXECUTOR_MEMORY],
            parsed.get(L.EXECUTOR_NVIDIA_GPUS, zero),
        ),
        min_executor_count=min_executor_count,
        max_executor_count=max_executor_count,
    )


def spark_resource_usage(
    driver_resources: Resources,
    executor_resources: Resources,
    driver_node: str,
    executor_nodes: List[str],
) -> NodeGroupResources:
    """sparkpods.go:139-146.

    QUIRK (reference behavior): per-node entries are *assigned*, not
    accumulated — a node hosting N executors contributes one executor's
    worth, and a driver node that also hosts executors is counted as
    executors only.  The FIFO pass subtracts this, so preserving it is
    required for decision parity.
    """
    usage: NodeGroupResources = {}
    usage[driver_node] = driver_resources
    for node in executor_nodes:
        usage[node] = executor_resources
    return usage


class SparkPodLister:
    """sparkpods.go:36-71 + driver lookups."""

    def __init__(self, pod_informer: Informer, instance_group_label: str):
        self._informer = pod_informer
        self._instance_group_label = instance_group_label

    @property
    def informer(self) -> Informer:
        return self._informer

    def list(self, namespace: Optional[str] = None, label_selector=None) -> List[Pod]:
        return self._informer.list(namespace=namespace, label_selector=label_selector)

    def list_earlier_drivers(self, driver: Pod) -> List[Pod]:
        """Unscheduled drivers in the same instance group, targeted at the
        same scheduler, created strictly earlier, sorted by creation time
        (sparkpods.go:45-71)."""
        drivers = self._informer.list(label_selector={L.SPARK_ROLE_LABEL: L.DRIVER})
        earlier = [
            p
            for p in drivers
            if p.node_name == ""
            and p.scheduler_name == driver.scheduler_name
            and L.match_pod_instance_group(p, driver, self._instance_group_label)
            and p.creation_timestamp < driver.creation_timestamp
            and p.meta.deletion_timestamp is None
        ]
        earlier.sort(key=lambda p: p.creation_timestamp)
        return earlier

    def get_driver_pod_for_executor(self, executor: Pod) -> Optional[Pod]:
        return self.get_driver_pod(
            executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
        )

    def get_driver_pod(self, app_id: str, namespace: str) -> Optional[Pod]:
        """sparkpods.go:152-159 (exactly one match or None)."""
        drivers = self._informer.list(
            namespace=namespace,
            label_selector={L.SPARK_APP_ID_LABEL: app_id, L.SPARK_ROLE_LABEL: L.DRIVER},
        )
        if len(drivers) != 1:
            return None
        return drivers[0]
