"""Spark pod lister: FIFO queue view + annotation parsing
(reference ``internal/extender/sparkpods.go``)."""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..kube.informer import Informer
from ..types.objects import Pod
from ..types.resources import NodeGroupResources, Resources
from ..utils.quantity import Quantity
from . import labels as L


@dataclass
class SparkApplicationResources:
    """internal/types SparkApplicationResources."""

    driver_resources: Resources
    executor_resources: Resources
    min_executor_count: int
    max_executor_count: int


class AnnotationError(ValueError):
    pass


def spark_resources(pod: Pod) -> SparkApplicationResources:
    """Parse the app's resource annotations (sparkpods.go:73-137).

    Error cases mirror the reference: bad DA boolean, missing
    executor-count without DA, missing DA min/max with DA, missing
    driver/executor cpu/mem, unparseable quantity.
    """
    annotations = pod.annotations
    da_raw = annotations.get(L.DYNAMIC_ALLOCATION_ENABLED)
    dynamic_allocation_enabled = False
    if da_raw is not None:
        if da_raw.lower() in ("true", "1", "t"):
            dynamic_allocation_enabled = True
        elif da_raw.lower() in ("false", "0", "f"):
            dynamic_allocation_enabled = False
        else:
            raise AnnotationError(
                "annotation DynamicAllocationEnabled could not be parsed as a boolean"
            )

    parsed: Dict[str, Quantity] = {}
    for key in (
        L.DRIVER_CPU,
        L.DRIVER_MEMORY,
        L.DRIVER_NVIDIA_GPUS,
        L.EXECUTOR_CPU,
        L.EXECUTOR_MEMORY,
        L.EXECUTOR_NVIDIA_GPUS,
        L.EXECUTOR_COUNT,
        L.DA_MIN_EXECUTOR_COUNT,
        L.DA_MAX_EXECUTOR_COUNT,
    ):
        value = annotations.get(key)
        if value is None:
            if key in (L.DRIVER_NVIDIA_GPUS, L.EXECUTOR_NVIDIA_GPUS):
                continue  # optional: GPUs not required
            if not dynamic_allocation_enabled and key == L.EXECUTOR_COUNT:
                raise AnnotationError(
                    "annotation ExecutorCount is required when DynamicAllocationEnabled is false"
                )
            if dynamic_allocation_enabled and key in (
                L.DA_MIN_EXECUTOR_COUNT,
                L.DA_MAX_EXECUTOR_COUNT,
            ):
                raise AnnotationError(
                    f"annotation {key} is required when DynamicAllocationEnabled is true"
                )
            if key in (L.EXECUTOR_COUNT, L.DA_MIN_EXECUTOR_COUNT, L.DA_MAX_EXECUTOR_COUNT):
                continue  # not needed in this mode
            raise AnnotationError(f"annotation {key} is missing from driver")
        try:
            parsed[key] = Quantity(value)
        except ValueError:
            raise AnnotationError(
                f"annotation {key} does not have a parseable value {value}"
            ) from None

    if dynamic_allocation_enabled:
        min_executor_count = parsed[L.DA_MIN_EXECUTOR_COUNT].value()
        max_executor_count = parsed[L.DA_MAX_EXECUTOR_COUNT].value()
    else:
        min_executor_count = parsed[L.EXECUTOR_COUNT].value()
        max_executor_count = min_executor_count

    zero = Quantity(0)
    return SparkApplicationResources(
        driver_resources=Resources(
            parsed[L.DRIVER_CPU], parsed[L.DRIVER_MEMORY], parsed.get(L.DRIVER_NVIDIA_GPUS, zero)
        ),
        executor_resources=Resources(
            parsed[L.EXECUTOR_CPU],
            parsed[L.EXECUTOR_MEMORY],
            parsed.get(L.EXECUTOR_NVIDIA_GPUS, zero),
        ),
        min_executor_count=min_executor_count,
        max_executor_count=max_executor_count,
    )


# (uid, resourceVersion) → (SparkApplicationResources, AppDemand) |
# AnnotationError.  Annotations are immutable per resource version, and
# the FIFO pass re-reads the same ~queue-depth pods on EVERY Filter
# request — without this cache, Quantity re-parsing alone cost
# ~200ms/request at the 10k-node × 1k-queue shape.  The AppDemand
# instance is STABLE across requests so the tensorize layer can stash
# its exact base-unit rows on it (tensorize._app_base_rows).
_SPARK_RESOURCES_CACHE: OrderedDict = OrderedDict()
_SPARK_RESOURCES_CACHE_MAX = 16384
_spark_resources_lock = threading.Lock()


def _cache_lookup(pod: Pod):
    key = (pod.meta.uid, pod.meta.resource_version)
    if not key[0]:
        return None, None  # no identity to key on
    with _spark_resources_lock:
        hit = _SPARK_RESOURCES_CACHE.get(key)
        if hit is not None:
            _SPARK_RESOURCES_CACHE.move_to_end(key)
    return key, hit


def _cache_store(key, value) -> None:
    with _spark_resources_lock:
        _SPARK_RESOURCES_CACHE[key] = value
        while len(_SPARK_RESOURCES_CACHE) > _SPARK_RESOURCES_CACHE_MAX:
            _SPARK_RESOURCES_CACHE.popitem(last=False)


def _cached_entry(pod: Pod):
    """(SparkApplicationResources, AppDemand) for the pod's current
    version, parsed at most once; AnnotationErrors are cached too (a bad
    annotation stays bad for that version) and re-raised fresh."""
    from ..ops.sparkapp import AppDemand

    key, hit = _cache_lookup(pod)
    if hit is None:
        try:
            sar = spark_resources(pod)
            demand = AppDemand(
                sar.driver_resources,
                sar.executor_resources,
                sar.min_executor_count,
            )
            # precompute the exact tensor rows BEFORE the instance is
            # shared: request threads then only read the stash, so the
            # tensorize-layer lazy fallback never writes to a shared
            # AppDemand from concurrent requests (ADVICE r4 #3)
            from ..ops.tensorize import _app_base_rows

            _app_base_rows(demand)
            hit = (sar, demand)
        except AnnotationError as err:
            hit = err
        if key is not None:
            _cache_store(key, hit)
    if isinstance(hit, AnnotationError):
        raise AnnotationError(*hit.args)
    return hit


def spark_resources_cached(pod: Pod) -> SparkApplicationResources:
    """``spark_resources`` memoized by (uid, resourceVersion)."""
    return _cached_entry(pod)[0]


def spark_app_demand_cached(pod: Pod):
    """(SparkApplicationResources, stable AppDemand) for the pod's
    current version — the FIFO queue loops use this so per-app tensor
    rows are computed once per pod version, not once per request."""
    return _cached_entry(pod)


def spark_resource_usage(
    driver_resources: Resources,
    executor_resources: Resources,
    driver_node: str,
    executor_nodes: List[str],
) -> NodeGroupResources:
    """sparkpods.go:139-146.

    QUIRK (reference behavior): per-node entries are *assigned*, not
    accumulated — a node hosting N executors contributes one executor's
    worth, and a driver node that also hosts executors is counted as
    executors only.  The FIFO pass subtracts this, so preserving it is
    required for decision parity.
    """
    usage: NodeGroupResources = {}
    usage[driver_node] = driver_resources
    for node in executor_nodes:
        usage[node] = executor_resources
    return usage


class SparkPodLister:
    """sparkpods.go:36-71 + driver lookups."""

    def __init__(self, pod_informer: Informer, instance_group_label: str):
        self._informer = pod_informer
        self._instance_group_label = instance_group_label
        # (informer revision, pending drivers sorted by creation time) —
        # the FIFO pass re-derives this view on every Filter request; at
        # a 1k-deep queue the raw list+filter+sort cost ~9ms/request
        self._pending_cache = (-1, [])

    @property
    def informer(self) -> Informer:
        return self._informer

    def list(self, namespace: Optional[str] = None, label_selector=None) -> List[Pod]:
        return self._informer.list(namespace=namespace, label_selector=label_selector)

    def list_earlier_drivers(self, driver: Pod) -> List[Pod]:
        """Unscheduled drivers in the same instance group, targeted at the
        same scheduler, created strictly earlier, sorted by creation time
        (sparkpods.go:45-71).  The driver-independent part (pending
        drivers, time-sorted) is cached per informer revision."""
        # keyed on the driver-role bucket revision: executor pod churn
        # (the dominant event stream) leaves the cache valid
        rev = self._informer.selector_revision(L.SPARK_ROLE_LABEL, L.DRIVER)
        cached_rev, pending = self._pending_cache
        if cached_rev != rev:
            drivers = self._informer.list(
                label_selector={L.SPARK_ROLE_LABEL: L.DRIVER}
            )
            pending = [
                p
                for p in drivers
                if p.node_name == "" and p.meta.deletion_timestamp is None
            ]
            pending.sort(key=lambda p: p.creation_timestamp)
            self._pending_cache = (rev, pending)
        cut = driver.creation_timestamp
        return [
            p
            for p in pending
            if p.creation_timestamp < cut
            and p.scheduler_name == driver.scheduler_name
            and L.match_pod_instance_group(p, driver, self._instance_group_label)
        ]

    def list_pending_drivers(self, driver: Pod) -> List[Pod]:
        """The full pending-driver set ``driver`` competes with: same
        filters as :meth:`list_earlier_drivers` MINUS the creation-time
        cut (and including ``driver`` itself when pending), still
        creation-time sorted.  The policy engine re-orders this set
        under non-FIFO comparators; it shares ``_pending_cache`` so the
        policy path costs no extra informer scan."""
        rev = self._informer.selector_revision(L.SPARK_ROLE_LABEL, L.DRIVER)
        cached_rev, pending = self._pending_cache
        if cached_rev != rev:
            drivers = self._informer.list(
                label_selector={L.SPARK_ROLE_LABEL: L.DRIVER}
            )
            pending = [
                p
                for p in drivers
                if p.node_name == "" and p.meta.deletion_timestamp is None
            ]
            pending.sort(key=lambda p: p.creation_timestamp)
            self._pending_cache = (rev, pending)
        return [
            p
            for p in pending
            if p.scheduler_name == driver.scheduler_name
            and L.match_pod_instance_group(p, driver, self._instance_group_label)
        ]

    def get_driver_pod_for_executor(self, executor: Pod) -> Optional[Pod]:
        return self.get_driver_pod(
            executor.labels.get(L.SPARK_APP_ID_LABEL, ""), executor.namespace
        )

    def get_driver_pod(self, app_id: str, namespace: str) -> Optional[Pod]:
        """sparkpods.go:152-159 (exactly one match or None)."""
        drivers = self._informer.list(
            namespace=namespace,
            label_selector={L.SPARK_APP_ID_LABEL: app_id, L.SPARK_ROLE_LABEL: L.DRIVER},
        )
        if len(drivers) != 1:
            return None
        return drivers[0]
