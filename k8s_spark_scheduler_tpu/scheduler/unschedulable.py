"""Unschedulable-pod marker (reference
``internal/extender/unschedulablepods.go``).

Periodically scans pending drivers older than the timeout and checks
whether the gang could fit an *otherwise-empty* cluster (zero usage, but
still subtracting non-schedulable overhead — daemonset pods etc.,
unschedulablepods.go:149-151).  Sets/clears the
``PodExceedsClusterCapacity`` pod condition.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .. import timesource
from ..kube.apiserver import APIServer
from ..kube.informer import Informer
from ..ops.registry import Binpacker
from ..types.objects import Pod, PodCondition
from ..types.resources import Resources, node_scheduling_metadata_for_nodes
from . import labels as L
from .overhead import OverheadComputer
from .sparkpods import AnnotationError, spark_resources

logger = logging.getLogger(__name__)

POD_EXCEEDS_CLUSTER_CAPACITY = "PodExceedsClusterCapacity"
UNSCHEDULABLE_POLLING_INTERVAL_SECONDS = 60.0
DEFAULT_TIMEOUT_SECONDS = 600.0


class UnschedulablePodMarker:
    def __init__(
        self,
        api: APIServer,
        node_informer: Informer,
        pod_informer: Informer,
        overhead_computer: OverheadComputer,
        binpacker: Binpacker,
        timeout_seconds: float = DEFAULT_TIMEOUT_SECONDS,
        polling_interval_seconds: float = UNSCHEDULABLE_POLLING_INTERVAL_SECONDS,
    ):
        if timeout_seconds <= 0:
            timeout_seconds = DEFAULT_TIMEOUT_SECONDS
        self._api = api
        self._node_informer = node_informer
        self._pod_informer = pod_informer
        self._overhead = overhead_computer
        self._binpacker = binpacker
        self._timeout = timeout_seconds
        self._interval = polling_interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True, name="unschedulable-marker")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.scan_for_unschedulable_pods()
            except Exception:
                logger.exception("unschedulable pod scan failed")

    def scan_for_unschedulable_pods(self) -> None:
        """unschedulablepods.go:93-129.

        A deep pending backlog shares a handful of affinity shapes and
        app sizes, and the verdict is a pure function of (eligible node
        set, zero-usage metadata, app resource triple) — so the scan
        memoizes the empty-cluster metadata per affinity signature and
        the binpack verdict per (signature, app triple) within one
        sweep.  Without this, a 1k-deep backlog rebuilt 10k-node
        Quantity metadata and ran a full pack PER POD every interval
        (tens of seconds of CPU that, on a small host, came straight
        out of live Filter latency)."""
        now = timesource.now()
        meta_cache: dict = {}
        verdict_cache: dict = {}
        for pod in self._pod_informer.list():
            if (
                pod.scheduler_name == L.SPARK_SCHEDULER_NAME
                and pod.node_name == ""
                and pod.meta.deletion_timestamp is None
                and pod.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER
                and pod.creation_timestamp + self._timeout < now
            ):
                try:
                    exceeds = self._pod_exceeds_cached(pod, meta_cache, verdict_cache)
                except AnnotationError:
                    logger.exception("failed to check if pod was unschedulable")
                    return
                if exceeds:
                    logger.info("marking pod %s as exceeds capacity", pod.name)
                self._mark_pod_cluster_capacity_status(pod, exceeds)
                # yield between pods: the scan is a background janitor —
                # a deep backlog must not monopolize a small host's core
                # against live Filter requests for seconds at a stretch
                time.sleep(0.0005)

    @staticmethod
    def _affinity_sig(pod: Pod):
        """Hashable signature of the node-matching constraints (the only
        pod inputs to the eligible-node set)."""
        return (
            tuple(sorted(pod.node_selector.items())),
            tuple(sorted((k, tuple(v)) for k, v in pod.node_affinity.items())),
            tuple(
                tuple((k, op, tuple(vals)) for k, op, vals in term)
                for term in pod.affinity_terms
            ),
        )

    def _pod_exceeds_cached(self, driver: Pod, meta_cache: dict, verdict_cache: dict) -> bool:
        sig = self._affinity_sig(driver)
        app_resources = spark_resources(driver)
        # Quantity is hashable (exact-value eq/hash); the Resources
        # dataclass is not, so the key carries its quantities
        key = (
            sig,
            *(
                (r.cpu, r.memory, r.nvidia_gpu)
                for r in (
                    app_resources.driver_resources,
                    app_resources.executor_resources,
                )
            ),
            app_resources.min_executor_count,
        )
        hit = verdict_cache.get(key)
        if hit is not None:
            return hit
        cached = meta_cache.get(sig)
        if cached is None:
            nodes = self._node_informer.list_with_predicate(
                lambda n: driver.matches_node(n)
            )
            node_names = [n.name for n in nodes]
            zero_usage = {n.name: Resources.zero() for n in nodes}
            overhead = self._overhead.get_non_schedulable_overhead(nodes)
            # chunked: one unbroken 10k-node Quantity build holds the
            # GIL for ~0.5-1s and was the single biggest tail spike
            # live Filters saw from this janitor
            metadata = {}
            for i in range(0, len(nodes), 512):
                chunk = nodes[i : i + 512]
                metadata.update(
                    node_scheduling_metadata_for_nodes(chunk, zero_usage, overhead)
                )
                time.sleep(0.0005)
            cluster = None
            solver = getattr(self._binpacker, "queue_solver", None)
            if solver is not None and hasattr(solver, "feasible_tensor"):
                # the tensor is pod-independent within the signature:
                # build once, then each verdict is one feasibility-only
                # solve on the device/native lane (identical to
                # binpack_func's has_capacity, per the differential
                # suites)
                from ..ops.tensorize import tensorize_cluster

                cluster = tensorize_cluster(metadata, node_names, node_names)
            cached = (node_names, metadata, cluster, solver)
            meta_cache[sig] = cached
        node_names, metadata, cluster, solver = cached
        exceeds = None
        if cluster is not None:
            from ..ops.sparkapp import AppDemand

            feasible = solver.feasible_tensor(
                cluster,
                AppDemand(
                    app_resources.driver_resources,
                    app_resources.executor_resources,
                    app_resources.min_executor_count,
                ),
            )
            if feasible is not None:
                exceeds = not feasible
        if exceeds is None:
            result = self._binpacker.binpack_func(
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
                node_names,
                node_names,
                metadata,
            )
            exceeds = not result.has_capacity
        verdict_cache[key] = exceeds
        return exceeds

    def does_pod_exceed_cluster_capacity(self, driver: Pod) -> bool:
        """unschedulablepods.go:132-166: binpack against zero usage plus
        non-schedulable overhead."""
        return self._pod_exceeds_cached(driver, {}, {})

    def _mark_pod_cluster_capacity_status(self, driver: Pod, exceeds: bool) -> None:
        """unschedulablepods.go:168-180 (condition update only when
        changed)."""
        status = "True" if exceeds else "False"
        current = driver.conditions.get(POD_EXCEEDS_CLUSTER_CAPACITY)
        if current is not None and current.status == status:
            return
        from ..kube.conflict import run_with_conflict_retry

        state = {"fresh": None}

        def refresh() -> bool:
            state["fresh"] = self._api.get(Pod.KIND, driver.namespace, driver.name)
            return True

        def attempt():
            fresh = state["fresh"]
            fresh.conditions[POD_EXCEEDS_CLUSTER_CAPACITY] = PodCondition(
                type=POD_EXCEEDS_CLUSTER_CAPACITY,
                status=status,
                transition_time=timesource.now(),
            )
            return self._api.update(fresh)

        try:
            # the kubelet and other controllers write pod status too, so
            # 409s here are routine — resolve them through the shared
            # conflict-retry discipline instead of swallowing the write
            refresh()
            run_with_conflict_retry(attempt, refresh, kind=Pod.KIND)
        except Exception:
            # per-pod failure (e.g. pod deleted concurrently) must not
            # abort the scan of the remaining drivers
            logger.exception("failed to mark pod cluster capacity status")
