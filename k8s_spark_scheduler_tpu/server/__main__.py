"""CLI entry point (reference main.go + cmd/root.go + cmd/server.go).

    python -m k8s_spark_scheduler_tpu.server [--port P] [--config FILE]
    python -m k8s_spark_scheduler_tpu.server --version
    python -m k8s_spark_scheduler_tpu.server --webhook-only [--port P]

``--config`` takes a JSON file in the reference's install.yml shape
(config/config.go keys).  ``--webhook-only`` serves just the CRD
conversion webhook, mirroring the standalone
spark-scheduler-conversion-webhook module.
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import logging
import signal
import sys

from .. import __version__
from ..config import Install
from ..kube.apiserver import APIServer
from .http import ExtenderHTTPServer
from .wiring import init_server_with_clients


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-gang-scheduler")
    parser.add_argument("--version", action="store_true", help="print version and exit")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--host", type=str, default="", help="bind address (default: all interfaces)")
    parser.add_argument("--config", type=str, default=None, help="install config JSON file")
    parser.add_argument(
        "--webhook-only",
        action="store_true",
        help="serve only the CRD conversion webhook (standalone module)",
    )
    # backend selection (reference cmd/clients.go:37-44: kubeconfig path
    # or in-cluster config; default here is the embedded store for
    # single-process runs and demos)
    parser.add_argument(
        "--kubeconfig",
        type=str,
        default=None,
        help="connect to the cluster in this kubeconfig (real-cluster mode)",
    )
    parser.add_argument(
        "--kube-context",
        type=str,
        default=None,
        help="kubeconfig context override",
    )
    parser.add_argument(
        "--in-cluster",
        action="store_true",
        help="use the pod service account to reach the API server",
    )
    # HTTPS serving: required for the CRD conversion webhook on a real
    # cluster (the apiserver only dials webhooks over TLS) and supported
    # by kube-scheduler's extender tlsConfig
    parser.add_argument("--tls-cert", type=str, default=None, help="PEM server certificate")
    parser.add_argument("--tls-key", type=str, default=None, help="PEM server private key")
    args = parser.parse_args(argv)
    if bool(args.tls_cert) != bool(args.tls_key):
        print("--tls-cert and --tls-key must be given together", file=sys.stderr)
        return 2

    if args.version:
        print(__version__)
        return 0

    class _JsonFormatter(logging.Formatter):
        def format(self, record):
            return json.dumps(
                {
                    "time": self.formatTime(record),
                    "level": record.levelname,
                    "logger": record.name,
                    "message": record.getMessage(),
                }
            )

    handler = logging.StreamHandler()
    handler.setFormatter(_JsonFormatter())
    logging.basicConfig(level=logging.INFO, handlers=[handler])
    # stacktrace-on-signal, as the reference registers in main.go:24-27
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    # Python-level handlers (run in the main thread no matter which
    # thread receives the signal) so SIGTERM reliably takes the
    # graceful-stop path; a SECOND signal restores the default
    # disposition and re-raises, so a wedged shutdown can still be
    # terminated without SIGKILL
    import os
    import threading

    stop_event = threading.Event()

    def _on_signal(signum, frame):
        if stop_event.is_set():
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        stop_event.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)

    if args.webhook_only:
        http = ExtenderHTTPServer(
            None,
            port=args.port,
            webhook_only=True,
            host=args.host,
            tls_cert_file=args.tls_cert,
            tls_key_file=args.tls_key,
        )
        http.start()
        scheme = "https" if http.tls else "http"
        print(f"conversion webhook serving on :{http.port} ({scheme})", flush=True)
        stop_event.wait()
        http.stop()
        return 0

    install = Install()
    if args.config:
        with open(args.config) as f:
            raw = f.read()
        if args.config.endswith((".yml", ".yaml")):
            # the reference's install.yml shape (config/config.go);
            # pyyaml ships as the optional [yaml] extra
            try:
                import yaml
            except ImportError:
                print(
                    "YAML configs need pyyaml (pip install 'tpu-gang-scheduler[yaml]') "
                    "or use a JSON config",
                    file=sys.stderr,
                )
                return 2
            install = Install.from_dict(yaml.safe_load(raw) or {})
        else:
            install = Install.from_dict(json.loads(raw))

    if args.in_cluster or args.kubeconfig:
        # install.qps/burst are applied by the wiring's shared write-back
        # token bucket (clients.go:53-54 analog); the REST client's own
        # bucket stays off so the limit isn't double-counted
        from ..kube.restbackend import RestAPIServer
        from ..kube.restclient import in_cluster_config, load_kubeconfig

        if args.in_cluster:
            cluster = in_cluster_config()
        else:
            cluster = load_kubeconfig(args.kubeconfig, args.kube_context)
        api = RestAPIServer(cluster)
        backend_desc = f"kubernetes {cluster.host}"
    else:
        api = APIServer()
        backend_desc = "embedded"
    scheduler = init_server_with_clients(api, install)
    http = ExtenderHTTPServer(
        scheduler,
        port=args.port,
        host=args.host,
        tls_cert_file=args.tls_cert,
        tls_key_file=args.tls_key,
    )
    http.start()
    print(
        f"extender serving on :{http.port} "
        f"(binpack={install.binpack_algo}, backend={backend_desc}, "
        f"tls={'on' if http.tls else 'off'})",
        flush=True,
    )
    try:
        stop_event.wait()
    finally:
        http.stop()
        scheduler.stop()
        if hasattr(api, "close"):
            api.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
