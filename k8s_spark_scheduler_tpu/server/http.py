"""HTTP surface: the kube-scheduler extender protocol + ops endpoints.

- ``POST /predicates`` — ExtenderArgs JSON in, ExtenderFilterResult out
  (reference cmd/endpoints.go:28-42)
- ``POST /convert`` — CRD ConversionReview webhook
  (internal/conversionwebhook/resource_reservation.go:33-98; also served
  standalone, mirroring the spark-scheduler-conversion-webhook module)
- ``GET /status/liveness`` / ``GET /status/readiness`` — management
  probes (witchcraft server equivalents, examples/extender.yml:142-151)
- ``GET /metrics`` — metrics registry snapshot: JSON by default,
  Prometheus text exposition when the Accept header asks for
  ``text/plain``/openmetrics or ``?format=prometheus`` is passed
- ``GET /traces`` — recent completed span trees (tracing/spans.py ring)
- ``GET /debug/schedule/<pod>`` — human-readable explanation of the
  last scheduling decision for a pod: span tree + correlated events +
  the decision-provenance record when one exists
- ``GET /explain/<pod>`` — the decision-provenance record as JSON:
  snapshot keys, queue slice, verdicts, and for refusals the
  tightest-dimension shortfall + blocker set (provenance/)
- ``GET /debug/contention`` — per-lock wait/hold percentiles, holder
  attribution, and top blockers (contention/locktime.py)
- ``GET /debug/criticalpath`` — per-request latency decomposition:
  gate-queue / lock-wait / serde / solve / write-back / other
  (contention/criticalpath.py)
- ``GET /policy/state`` — policy-engine state: priority bands, tenant
  dominant shares, recent evictions with reasons (policy/engine.py)
- ``GET /status/ha`` — HA fabric state: leadership, fencing epoch,
  lease holder/history, last takeover-reconciliation report (ha/)
- ``GET /slo`` — the scorecard: per-objective multi-window burn-rate
  status + lifecycle summary, same schema as the sim runner's
  scorecard.json (lifecycle/scorecard.py)
- ``GET /lifecycle`` / ``GET /lifecycle/<app>`` — gang lifecycle
  ledger: per-application phase machine with queue-wait/solve-tenure
  durations, eviction causes, and HA epoch continuity (lifecycle/)
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlsplit

from ..resilience import AdmissionShed, deadline as req_deadline
from ..tracing import spans as tracing
from ..types import serde
from .wiring import Server

logger = logging.getLogger(__name__)

# inbound X-Trace-Id must be propagation-safe before it is echoed into
# response headers and log lines: bounded length, trace-id charset only
# (hex/alnum plus the separators zipkin-style ids use).  Anything else —
# control characters, log-injection payloads, unbounded blobs — is
# replaced with a fresh id.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def sanitize_trace_id(raw: Optional[str]) -> str:
    if raw and _TRACE_ID_RE.match(raw):
        return raw
    return tracing.new_trace_id()


class _ExtenderHTTPD(ThreadingHTTPServer):
    # socketserver defaults to a 5-connection listen backlog; a
    # kube-scheduler burst (or parallel probes) overflows that and the
    # kernel resets connections
    request_queue_size = 128


def convert_review(body: dict) -> dict:
    """Handle a ConversionReview: convert every object to the desired
    apiVersion (conversion webhook contract)."""
    request = body.get("request") or {}
    uid = request.get("uid", "")
    desired = request.get("desiredAPIVersion", "")
    converted = []
    try:
        for obj in request.get("objects") or []:
            converted.append(serde.convert_rr(obj, desired))
        result = {"status": "Success"}
    except Exception as err:  # conversion failures are reported, not raised
        logger.exception("conversion failed")
        converted = []
        result = {"status": "Failed", "message": str(err)}
    return {
        "apiVersion": body.get("apiVersion", "apiextensions.k8s.io/v1"),
        "kind": "ConversionReview",
        "response": {"uid": uid, "convertedObjects": converted, "result": result},
    }


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpu-gang-scheduler"
    scheduler: Optional[Server] = None
    webhook_only: bool = False
    # per-connection socket timeout (applied by BaseHTTPRequestHandler.
    # setup): bounds slow reads AND the deferred TLS handshake so a
    # stalled peer only ties up its own worker thread, and only briefly.
    # The kube-scheduler extender client gives up after 30s
    # (examples/extender.yml httpTimeout), so 65s is a safe outer bound.
    timeout = 65

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("http: " + fmt, *args)

    def _send_bytes(self, code: int, data: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        trace = getattr(self, "_trace", None)
        if trace is not None:
            trace_id, t0 = trace
            self.send_header("X-Trace-Id", trace_id)
            logger.info(
                "request traceId=%s path=%s status=%d durationMs=%.1f",
                trace_id,
                self.path,
                code,
                (time.perf_counter() - t0) * 1000.0,
            )
            span = tracing.current_span()
            if span is not None:
                span.tag("status", code)
        # close the root span BEFORE the response bytes go out: a client
        # that sees the response must be able to retrieve the trace from
        # /traces immediately (the do_* finally is only a backstop for
        # handlers that die before responding)
        self._finish_trace()
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, payload: dict) -> None:
        self._send_bytes(code, json.dumps(payload).encode(), "application/json")

    def _send_text(self, code: int, text: str, content_type: str = "text/plain; charset=utf-8") -> None:
        self._send_bytes(code, text.encode(), content_type)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    def _tracer(self):
        return self.scheduler.tracer if self.scheduler is not None else None

    def do_GET(self):
        # GET endpoints (probes, /metrics scrapes, /traces polls) keep
        # the trace-id header + request log line but do NOT open a root
        # span: recording them would churn scheduling decisions out of
        # the bounded trace ring (2 probes/10s evict a predicate trace
        # from a 256-ring in minutes on an idle scheduler)
        self._begin_trace(open_span=False)
        try:
            self._handle_get()
        finally:
            self._finish_trace()

    def _handle_get(self):
        path, query = self._split_path()
        if path == "/status/liveness":
            self._send_json(200, {"status": "up"})
        elif path == "/status/readiness":
            serving = self.webhook_only or (
                self.scheduler is not None
                and self.scheduler.informer_factory.wait_for_cache_sync()
                # solver warmup still compiling: admitting traffic now
                # would put jit latency (and compiler-thread CPU
                # contention) on the first Filter requests
                and self.scheduler.warmup_complete()
                # HA standby: a replica that does not hold the lease
                # must not receive Filter traffic — its fenced write
                # paths would refuse every decision's write-back anyway
                and (
                    getattr(self.scheduler, "ha", None) is None
                    or self.scheduler.ha.is_leader()
                )
            )
            kit = getattr(self.scheduler, "resilience", None)
            if kit is None:
                self._send_json(200 if serving else 503, {"ready": serving})
                return
            # tri-state: unready answers 503 (don't route here yet);
            # degraded still answers 200 — a replica serving correct
            # decisions with reduced machinery must NOT be pulled from
            # rotation (that turns overload into an outage) — with the
            # component breakdown in the body for operators
            report = kit.health.report(serving=serving)
            report["ready"] = serving
            self._send_json(200 if serving else 503, report)
        elif path == "/metrics" and self.scheduler is not None:
            fmt = self._metrics_format(query)
            if fmt == "openmetrics":
                from ..metrics import prometheus as prom

                self._send_text(
                    200,
                    prom.render(self.scheduler.metrics, openmetrics=True),
                    prom.CONTENT_TYPE_OPENMETRICS,
                )
            elif fmt == "prometheus":
                from ..metrics import prometheus as prom

                self._send_text(
                    200, prom.render(self.scheduler.metrics), prom.CONTENT_TYPE
                )
            else:
                self._send_json(200, self.scheduler.metrics.snapshot())
        elif path == "/traces" and self.scheduler is not None:
            tracer = self._tracer()
            if tracer is None:
                self._send_json(404, {"error": "tracing not enabled"})
                return
            limit = None
            try:
                limit = int(query.get("limit", [""])[0])
            except (ValueError, IndexError):
                pass
            self._send_json(200, {"traces": tracer.traces(limit=limit)})
        elif path.startswith("/debug/schedule/") and self.scheduler is not None:
            self._handle_debug_schedule(unquote(path[len("/debug/schedule/"):]))
        elif path.startswith("/explain/") and self.scheduler is not None:
            self._handle_explain(unquote(path[len("/explain/"):]))
        elif path.startswith("/state/capacity") and self.scheduler is not None:
            self._handle_capacity(path, query)
        elif path == "/debug/contention" and self.scheduler is not None:
            self._handle_debug_contention(query)
        elif path == "/debug/criticalpath" and self.scheduler is not None:
            self._handle_debug_criticalpath(query)
        elif path == "/policy/state" and self.scheduler is not None:
            self._handle_policy_state()
        elif path == "/slo" and self.scheduler is not None:
            self._handle_slo()
        elif (
            path == "/lifecycle" or path.startswith("/lifecycle/")
        ) and self.scheduler is not None:
            self._handle_lifecycle(unquote(path[len("/lifecycle"):]).lstrip("/"))
        elif path == "/status/ha" and self.scheduler is not None:
            fabric = getattr(self.scheduler, "ha", None)
            if fabric is None:
                self._send_json(200, {"enabled": False})
                return
            out = {"enabled": True}
            out.update(fabric.status())
            self._send_json(200, out)
        else:
            self._send_json(404, {"error": "not found"})

    def _split_path(self):
        parts = urlsplit(self.path)
        return parts.path, parse_qs(parts.query)

    def _metrics_format(self, query) -> str:
        """"openmetrics" (exemplar-carrying text), "prometheus" (plain
        0.0.4 text, unchanged), or "json" (the default snapshot).

        The exemplar flavour is EXPLICIT opt-in (?format=openmetrics),
        never Accept-negotiated: it is pragmatic rather than strictly
        OpenMetrics-valid (exemplars ride on summary ``_count`` lines;
        counter samples keep their plain-text names), so routing it to
        a client whose Accept demands strict OpenMetrics — including a
        Prometheus configured with ``scrape_protocols:
        [OpenMetricsText1.0.0]`` — would fail its whole scrape.  Any
        Accept mentioning openmetrics or text/plain gets the plain
        0.0.4 text every Prometheus parses."""
        fmt = query.get("format", [""])[0] if query.get("format") else ""
        if fmt:
            if fmt == "openmetrics":
                return "openmetrics"
            return "prometheus" if fmt in ("prometheus", "text") else "json"
        accept = self.headers.get("Accept") or ""
        if "text/plain" in accept or "openmetrics" in accept:
            return "prometheus"
        return "json"

    def _handle_explain(self, pod_name: str) -> None:
        """Why was this pod's last scheduling decision what it was:
        the provenance record — snapshot keys, queue slice, verdicts,
        and for refusals the tightest-dimension shortfall + blocker set
        (provenance/tracker.py).  Accepts a bare pod name (newest match
        across namespaces) or ``<namespace>/<pod>`` to disambiguate."""
        tracker = getattr(self.scheduler, "provenance", None)
        if tracker is None or not getattr(tracker, "enabled", False):
            self._send_json(404, {"error": "provenance not enabled"})
            return
        if not pod_name:
            self._send_json(400, {"error": "usage: /explain/<pod-name>"})
            return
        record = tracker.explain(pod_name)
        if record is None:
            self._send_json(
                404,
                {
                    "error": f"no recorded decision for pod {pod_name!r}",
                    "ringSize": tracker.stats()["ring"]["size"],
                },
            )
            return
        self._send_json(200, record)

    def _handle_slo(self) -> None:
        """The live scorecard: burn-rate status per objective plus the
        lifecycle summary, in the exact schema the sim runner emits
        (lifecycle/scorecard.py) so dashboards and the policy-
        regression gate never fork on source."""
        slo = getattr(self.scheduler, "slo", None)
        ledger = getattr(self.scheduler, "lifecycle", None)
        if slo is None:
            self._send_json(404, {"error": "slo engine not enabled"})
            return
        if ledger is not None:
            # freshen: pull any pending cursor work before reporting
            # (same on-demand pattern as /state/capacity)
            ledger.maybe_drain(trigger="http")
        from ..lifecycle import build_scorecard

        self._send_json(
            200, build_scorecard(ledger, slo, meta={"source": "server"})
        )

    def _handle_lifecycle(self, app_id: str) -> None:
        """``/lifecycle`` — ledger summary + per-gang brief list;
        ``/lifecycle/<app>`` — one gang's full record (phase
        timestamps, queue wait, solve tenure, eviction cause, epochs,
        correlated trace ids)."""
        ledger = getattr(self.scheduler, "lifecycle", None)
        if ledger is None:
            self._send_json(404, {"error": "lifecycle ledger not enabled"})
            return
        ledger.maybe_drain(trigger="http")
        if not app_id:
            self._send_json(
                200,
                {
                    "summary": ledger.summary(),
                    "gangs": ledger.records_brief(),
                },
            )
            return
        record = ledger.record(app_id)
        if record is None:
            self._send_json(
                404, {"error": f"no lifecycle record for app {app_id!r}"}
            )
            return
        self._send_json(200, record)

    def _handle_capacity(self, path: str, query) -> None:
        """Capacity observatory (capacity/observatory.py):

        - ``GET /state/capacity`` — the latest cluster-state sample
          (sampled on demand when the feed moved since the last one).
          ``?group=`` / ``?zone=`` filter the per-group entries,
          ``?ns=`` filters the queued-driver forecasts.
        - ``GET /state/capacity/history?limit=N`` — the timeline ring,
          newest first.
        - ``GET /state/capacity/diff?from=&to=`` — what changed between
          two timeline sequences (exact keys; history lists them)."""
        sampler = getattr(self.scheduler, "capacity", None)
        if sampler is None:
            self._send_json(404, {"error": "capacity observatory not enabled"})
            return

        def q1(key):
            vals = query.get(key)
            return vals[0] if vals else None

        if path == "/state/capacity":
            # serve fresh state without waiting for the background
            # debounce: O(1) when the feed hasn't moved
            sampler.maybe_sample(trigger="http")
            latest = sampler.latest()
            if latest is None:
                self._send_json(
                    200, {"samples": 0, "capacity": None}
                )
                return
            out = latest.to_dict()
            group, zone, ns = q1("group"), q1("zone"), q1("ns")
            if group is not None or zone is not None:
                out["groups"] = {
                    combo: entry
                    for combo, entry in out["groups"].items()
                    if (group is None or combo.split("|")[0] == group)
                    and (zone is None or combo.split("|", 1)[1] == zone)
                }
                if group is not None:
                    out["tenants"] = {
                        g: t for g, t in out["tenants"].items() if g == group
                    }
            if ns is not None:
                out["queue"] = [
                    e for e in out["queue"] if e.get("namespace") == ns
                ]
            self._send_json(200, out)
        elif path == "/state/capacity/history":
            limit = None
            try:
                limit = int(q1("limit") or "")
            except ValueError:
                pass
            history = sampler.history(limit=limit)
            self._send_json(
                200,
                {
                    "samples": [s.to_dict() for s in history],
                    "ring": sampler.stats()["ring"],
                    "ringCapacity": sampler.stats()["ring_capacity"],
                },
            )
        elif path == "/state/capacity/diff":
            try:
                from_seq = int(q1("from") or "")
                to_seq = int(q1("to") or "")
            except ValueError:
                self._send_json(
                    400, {"error": "usage: /state/capacity/diff?from=<seq>&to=<seq>"}
                )
                return
            diff = sampler.diff(from_seq, to_seq)
            if diff is None:
                self._send_json(
                    404,
                    {
                        "error": "sequence not in the timeline ring",
                        "available": [s.seq for s in sampler.history()],
                    },
                )
                return
            self._send_json(200, diff)
        else:
            self._send_json(404, {"error": "not found"})

    def _handle_debug_contention(self, query) -> None:
        """Lock wait/hold telemetry (contention/locktime.py): per-lock
        reservoir percentiles, holder-phase attribution, and the
        top-blocker table.  ``?lock=<name>`` filters to one lock site.
        Reading also drains pending samples into the metrics registry
        so a scrape right after stays fresh."""
        keeper = getattr(self.scheduler, "contention", None)
        if keeper is None:
            self._send_json(200, {"enabled": False, "locks": []})
            return
        name = query.get("lock", [None])[0] if query.get("lock") else None
        keeper.publish(self.scheduler.metrics)
        self._send_json(
            200,
            {
                "enabled": True,
                "locks": keeper.snapshot(name_filter=name),
            },
        )

    def _handle_debug_criticalpath(self, query) -> None:
        """Per-request latency decomposition (contention/
        criticalpath.py): which segment — gate-queue, lock-wait, serde,
        solve, write-back — the milliseconds went to, summarized over
        the recent-request ring.  ``?limit=N`` appends the N newest
        per-request records."""
        analyzer = getattr(self.scheduler, "criticalpath", None)
        if analyzer is None:
            self._send_json(200, {"enabled": False, "requests": 0})
            return
        out = {"enabled": True}
        out.update(analyzer.summary())
        try:
            limit = int(query.get("limit", [""])[0])
        except (ValueError, IndexError):
            limit = 0
        if limit:
            out["recent"] = analyzer.recent(limit=limit)
        self._send_json(200, out)

    def _handle_policy_state(self) -> None:
        """Policy-engine operator surface (policy/engine.py): configured
        bands with observation counts, per-tenant dominant shares, and
        the recent-evictions ring with reasons — the "who got evicted
        and why" entry point (docs/operations.md)."""
        engine = getattr(self.scheduler, "policy", None)
        if engine is None:
            self._send_json(200, {"enabled": False})
            return
        self._send_json(200, engine.state())

    def _handle_debug_schedule(self, pod_name: str) -> None:
        """Explain the last scheduling decision for a pod: the newest
        trace tagged pod=<name> rendered as a text span tree, with the
        event-ring records of the same trace appended, and the decision-
        provenance record (shortfall + blockers) when one exists."""
        tracer = self._tracer()
        if tracer is None or not pod_name:
            self._send_json(404, {"error": "tracing not enabled"})
            return
        trace = tracer.find_by_tag("pod", pod_name)
        if trace is None:
            self._send_text(
                404,
                f"no recorded scheduling decision for pod {pod_name!r} "
                f"(ring holds {len(tracer)} traces)\n",
            )
            return
        events = [
            (e.name, e.values)
            for e in self.scheduler.event_log.by_trace_id(trace["traceId"])
        ]
        text = tracing.render_trace_text(trace, events)
        tracker = getattr(self.scheduler, "provenance", None)
        if tracker is not None and getattr(tracker, "enabled", False):
            record = tracker.explain(pod_name, source="debug")
            if record is not None:
                text += "\nprovenance:\n"
                summary = record.get("summary")
                if summary:
                    text += f"  why: {summary}\n"
                for key in (
                    "outcome", "lane", "policy", "feedSeq", "queueLength",
                    "bundleSeq",
                ):
                    if record.get(key) is not None:
                        text += f"  {key}: {record[key]}\n"
        self._send_text(200, text)

    def _begin_trace(self, open_span: bool = True):
        # request tracing (the reference's witchcraft request log / trc1
        # analog): a trace id per request, echoed in the response header
        # and the request log line with the handler duration.  The
        # inbound header is sanitized before it can reach a header or
        # log line; the root span carries the whole handler.
        trace_id = sanitize_trace_id(self.headers.get("X-Trace-Id"))
        self._trace = (trace_id, time.perf_counter())
        tracer = self._tracer()
        self._root_span = None
        if open_span and tracer is not None and tracer.enabled:
            self._root_span = tracer.span(
                "http.request", {"path": self.path}, trace_id=trace_id
            )
            self._root_span.__enter__()

    def _finish_trace(self):
        span = getattr(self, "_root_span", None)
        if span is not None:
            span.__exit__(None, None, None)
            self._root_span = None

    def do_POST(self):
        self._begin_trace()
        try:
            self._handle_post()
        finally:
            self._finish_trace()

    def _handle_post(self):
        try:
            # body read + JSON parse under its own span: it is part of
            # the serde segment in the critical-path decomposition
            with tracing.child_span("http.read"):
                body = self._read_json()
        except (ValueError, json.JSONDecodeError) as err:
            self._send_json(400, {"error": f"bad json: {err}"})
            return
        if not isinstance(body, dict):
            self._send_json(400, {"error": "body must be a JSON object"})
            return

        if self.path == "/predicates" and not self.webhook_only:
            if self.scheduler is None:
                self._send_json(503, {"error": "scheduler not ready"})
                return
            try:
                # serde is a first-class segment of the request critical
                # path (contention/criticalpath.py): at the 10k-node
                # shape the ExtenderArgs parse and the FailedNodes
                # encode, not the solver, dominate the handler
                with tracing.child_span("serde.decode"):
                    args = serde.extender_args_from_dict(body)
            except Exception as err:
                self._send_json(400, {"error": f"bad ExtenderArgs: {err}"})
                return
            result = self._predicate_guarded(args)
            # encoded uniform failures come from a reusable buffer pool
            # (serde.encode_extender_filter_result) — the 10k-entry
            # FailedNodes map serializes once per (candidates, message)
            with tracing.child_span("serde.encode"):
                encoded = serde.encode_extender_filter_result(result)
            self._send_bytes(200, encoded, "application/json")
        elif self.path == "/convert":
            self._send_json(200, convert_review(body))
        else:
            self._send_json(404, {"error": "not found"})

    def _predicate_guarded(self, args):
        """Run the Filter under overload protection: a request deadline
        derived from kube-scheduler's httpTimeout (checked at phase
        boundaries inside the extender) and the bounded admission gate.
        Shed requests answer immediately with a retriable all-nodes
        failure — an extender protocol failure would abort the whole
        scheduling cycle, a failed-nodes response just requeues the pod."""
        from ..types.extenderapi import ExtenderFilterResult

        # the concurrent admission engine (concurrent/engine.py) is a
        # drop-in for extender.predicate: speculative solve on THIS
        # request thread, then a FIFO-ordered commit through the serial
        # extender — decisions stay byte-identical to serial operation
        engine = getattr(self.scheduler, "concurrent", None)
        predicate = (
            engine.predicate
            if engine is not None
            else self.scheduler.extender.predicate
        )
        kit = getattr(self.scheduler, "resilience", None)
        if kit is None:
            return predicate(args)
        try:
            # admission-gate queueing is a named critical-path segment;
            # today's gate is non-blocking (admit-or-shed) so this is
            # ~0, but the tag keeps the decomposition honest if the
            # gate ever grows a wait queue
            t_gate = time.perf_counter()
            with kit.gate.admit():
                span = tracing.current_span()
                if span is not None:
                    span.tags["gateWaitMs"] = round(
                        (time.perf_counter() - t_gate) * 1000.0, 4
                    )
                with req_deadline.bind(kit.request_timeout):
                    return predicate(args)
        except AdmissionShed:
            span = tracing.current_span()
            if span is not None:
                # the extender never ran, so nothing else stamps the
                # pod identity — without these tags the shed trace is
                # unfindable via /debug/schedule/<pod>
                span.tag("pod", args.pod.name)
                span.tag("namespace", args.pod.namespace)
                span.tag("outcome", "shed")
            # a shed is a real terminal verdict for this Filter attempt:
            # it must leave the same audit trail a refusal does — a
            # provenance DecisionRecord (`/explain` answers "why did my
            # app not start?" for sheds too) and a lifecycle `shed`
            # phase mark, not just a counter bump
            tracker = getattr(self.scheduler, "provenance", None)
            if tracker is not None:
                tracker.record_shed(args.pod)
            ledger = getattr(self.scheduler, "lifecycle", None)
            if ledger is not None:
                ledger.mark_shed(args.pod)
            message = "scheduler overloaded; retry"
            return ExtenderFilterResult(
                failed_nodes={n: message for n in args.node_names},
                uniform_failure=(args.node_names, message),
            )


class ExtenderHTTPServer:
    """The serving process: extender endpoints on the main port."""

    def __init__(
        self,
        scheduler: Optional[Server],
        port: int = 0,
        webhook_only: bool = False,
        host: str = "",
        tls_cert_file: Optional[str] = None,
        tls_key_file: Optional[str] = None,
    ):
        # host="" binds all interfaces: kube-scheduler and the apiserver
        # webhook dial the pod IP, not loopback
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"scheduler": scheduler, "webhook_only": webhook_only},
        )
        self._httpd = _ExtenderHTTPD((host, port), handler)
        if tls_cert_file:
            # the apiserver only calls conversion webhooks over HTTPS
            # with a CA it trusts (ref conversionwebhook/resource_
            # reservation.go:44-98); kube-scheduler extenders support
            # enableHTTPS + tlsConfig the same way
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_file, tls_key_file)
            # do_handshake_on_connect=False: the handshake must NOT run
            # inside accept() in the single serve_forever thread — a peer
            # that connects and never sends a ClientHello (port scanner,
            # TCP probe) would wedge the whole server.  Deferred, the
            # handshake happens on first read inside the per-connection
            # worker thread, bounded by the handler's socket timeout.
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True, do_handshake_on_connect=False
            )
        self.tls = bool(tls_cert_file)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="extender-http"
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
