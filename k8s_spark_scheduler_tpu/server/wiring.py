"""Server wiring: constructs every component bottom-up
(reference ``cmd/server.go:65-237`` InitServerWithClients).

Exported for tests and the HTTP server alike — the Harness builds on
this exactly as the reference's extendertest harness builds on
InitServerWithClients.
"""

from __future__ import annotations

from dataclasses import dataclass
from ..config import Install
from ..demands.manager import DemandManager
from ..events.events import EventLog
from ..kube import crd
from ..kube.apiserver import APIServer
from ..kube.informer import Informer, InformerFactory
from ..metrics.registry import MetricsRegistry
from ..metrics.reporters import ReporterSet
from ..metrics.waste import WasteMetricsReporter
from ..ops.nodesort import NodeSorter
from ..ops.registry import select_binpacker
from ..resilience import ResilienceKit, build_kit
from ..scheduler.demand_gc import start_demand_gc
from ..scheduler.extender import SparkSchedulerExtender
from ..scheduler.overhead import OverheadComputer
from ..scheduler.reservations_manager import ResourceReservationManager
from ..scheduler.sparkpods import SparkPodLister
from ..scheduler.unschedulable import UnschedulablePodMarker
from ..state.softreservations import SoftReservationStore
from ..state.tensor_snapshot import TensorSnapshotCache
from ..state.typed_caches import (
    LazyDemandInformer,
    ResourceReservationCache,
    SafeDemandCache,
)
from ..tracing import Tracer
from ..tracing import profiling as kernel_profiling
from ..types.objects import Node, Pod, ResourceReservation


@dataclass
class Server:
    """Everything InitServerWithClients wires up."""

    api: APIServer
    install: Install
    informer_factory: InformerFactory
    pod_informer: Informer
    node_informer: Informer
    rr_informer: Informer
    resource_reservation_cache: ResourceReservationCache
    lazy_demand_informer: LazyDemandInformer
    demand_cache: SafeDemandCache
    demand_manager: DemandManager
    soft_reservation_store: SoftReservationStore
    pod_lister: SparkPodLister
    resource_reservation_manager: ResourceReservationManager
    overhead_computer: OverheadComputer
    extender: SparkSchedulerExtender
    tensor_snapshot: TensorSnapshotCache
    unschedulable_marker: UnschedulablePodMarker
    metrics: MetricsRegistry
    event_log: EventLog
    tracer: Tracer = None
    reporters: "ReporterSet" = None
    waste_reporter: "WasteMetricsReporter" = None
    resilience: ResilienceKit = None
    provenance: object = None  # ProvenanceTracker (provenance/tracker.py)
    capacity: object = None  # CapacitySampler (capacity/observatory.py)
    contention: object = None  # LockTimekeeper (contention/locktime.py)
    criticalpath: object = None  # CriticalPathAnalyzer (contention/criticalpath.py)
    policy: object = None  # PolicyEngine (policy/engine.py)
    ha: object = None  # HAFabric (ha/__init__.py)
    lifecycle: object = None  # LifecycleLedger (lifecycle/ledger.py)
    slo: object = None  # SloEngine (lifecycle/slo.py)
    concurrent: object = None  # ConcurrentAdmissionEngine (concurrent/engine.py)

    def start_background(self) -> None:
        """Start async writers + periodic loops (cmd/server.go:221-230)."""
        self.resource_reservation_cache.run()
        self.lazy_demand_informer.start()
        self.unschedulable_marker.start()
        if self.reporters is not None:
            self.reporters.start()
        if self.capacity is not None:
            self.capacity.start()
        if self.lifecycle is not None:
            self.lifecycle.start()
        if self.ha is not None and self.install.ha.background:
            self.ha.start()
        self._warm_solver_async()

    def warmup_complete(self) -> bool:
        """True once the background solver warmup has finished (or never
        started).  Readiness gates on this: traffic admitted before the
        kernels are compiled pays jit latency on the request path, and —
        worse on a small host — the warmup's compiler threads compete
        with live Filter requests for cores."""
        ev = getattr(self, "_warm_done", None)
        return ev is None or ev.is_set()

    def wait_ready(self, timeout: float = 120.0) -> bool:
        """Block until caches are synced AND the solver warmup finished
        (the readiness condition) — what a deployment's readiness probe
        polls for before kube-scheduler sends the first Filter."""
        import time as _time

        deadline = _time.monotonic() + timeout  # schedlint: disable=TS002 -- readiness-probe wait bounds real wall time for a live kubelet
        if not self.informer_factory.wait_for_cache_sync():
            return False
        ev = getattr(self, "_warm_done", None)
        if ev is not None and not ev.wait(max(0.0, deadline - _time.monotonic())):  # schedlint: disable=TS002 -- remaining budget of the same real-time probe deadline
            return False
        return True

    def _warm_solver_async(self) -> None:
        """Pre-compile the device solver kernels for the common shape
        buckets in the background so the first Filter request doesn't
        pay jit latency (first compile is seconds on TPU).

        The thread is joined (bounded) in stop(): a daemon thread killed
        mid-XLA-compile at interpreter shutdown aborts the whole process
        ("FATAL: exception not rethrown" from pthread teardown inside
        the compiler).  It stays a daemon thread so a compile wedged on
        a dead device can never block process exit outright."""
        import threading

        self._warm_done = threading.Event()
        if not self.extender.binpacker.name.startswith("tpu-batch"):
            self._warm_done.set()
            return

        def warm():
            try:
                import numpy as _np

                import jax.numpy as jnp

                from ..ops.batch_solver import (
                    solve_queue,
                    solve_queue_min_frag,
                    solve_queue_single_az,
                    solve_single,
                    solve_zones_jit,
                )
                from ..ops.fifo_solver import _pallas_selected
                from ..ops.tensorize import APP_BUCKETS, NODE_BUCKETS

                # warm the kernels the configured policy's PRODUCTION
                # path actually dispatches — on TPU the plain FIFO pass
                # runs the pallas queue kernel, the single-AZ policies
                # dispatch solve_zones / the fused single-AZ scan, and
                # min-frag its own queue scan; evenly and with_placements
                # are static jit argnames, so warming the wrong variant
                # leaves the production one uncompiled
                name = self.extender.binpacker.name
                minfrag = name == "tpu-batch-minimal-fragmentation"
                evenly = name.endswith("distribute-evenly")
                single_az = "single-az" in name or name.endswith("az-aware")
                saz_minfrag = name == "tpu-batch-single-az-minimal-fragmentation"
                use_pallas = _pallas_selected("auto")

                # on accelerator-less hosts the native C++ lane serves the
                # queue pass, so compiling the device kernels here would
                # burn the serving core for minutes (the compiler threads
                # ran concurrently with live Filters before this guard)
                # for code the deployment never dispatches.  Build/load
                # the native library instead; the plain policies then
                # need no XLA at all (fallbacks compile on demand), and
                # the single-AZ policies keep only the kernels their
                # host-math path actually calls (solve_single +
                # solve_zones_jit for the current-app pack).
                native_lane = False
                if not use_pallas:
                    try:
                        from ..ops.fifo_solver import _native_selected

                        solver_backend = getattr(
                            self.extender.binpacker.queue_solver,
                            "backend", "auto",
                        )
                        native_lane = _native_selected(solver_backend)
                    except Exception:
                        native_lane = False
                if native_lane and not single_az:
                    return
                warm_zones = 3  # zone count is a compile shape; 3 AZs is typical
                for nb in NODE_BUCKETS[:3]:  # the shapes real clusters hit first
                    if self._warm_stop.is_set():
                        return
                    avail = jnp.zeros((nb, 3), jnp.int32)
                    rank = jnp.full((nb,), 2**31 - 1, jnp.int32)
                    eok = jnp.zeros((nb,), bool)
                    row = jnp.zeros((3,), jnp.int32)
                    solve_single(avail, rank, eok, row, row, jnp.int32(0))
                    ab = APP_BUCKETS[0]
                    apps = (
                        jnp.zeros((ab, 3), jnp.int32),
                        jnp.zeros((ab, 3), jnp.int32),
                        jnp.zeros((ab,), jnp.int32),
                        jnp.zeros((ab,), bool),
                    )
                    if single_az:
                        # per-driver vmapped zone solves (host zone-choice
                        # lane; the only queue lane for single-az min-frag)
                        solve_zones_jit(
                            avail, rank, eok,
                            jnp.zeros((warm_zones, nb), bool),
                            row, row, jnp.int32(0),
                        )
                    if native_lane:
                        # single-AZ native: the C++ lane runs the queue
                        # scan; only the host-math kernels above are hit
                        continue
                    if single_az and saz_minfrag:
                        # the fused min-frag single-AZ scan (XLA only);
                        # strict is a static jit argname, so warm the
                        # configured compat mode
                        strict = getattr(
                            self.extender.binpacker.queue_solver,
                            "strict_reference_parity",
                            True,
                        )
                        solve_queue_single_az(
                            avail, rank, eok,
                            jnp.zeros((warm_zones, nb), bool),
                            *apps,
                            jnp.zeros((nb,), jnp.int32),
                            jnp.zeros((nb,), jnp.int32),
                            jnp.zeros((nb,), jnp.float32),
                            jnp.zeros((nb,), jnp.int32),
                            jnp.int32(1),
                            jnp.int32(1),
                            az_aware=False,
                            minfrag=True,
                            strict=strict,
                        )
                    elif single_az:
                        az_aware = name.endswith("az-aware")
                        if use_pallas:
                            from ..ops.pallas_queue import (
                                pallas_solve_queue_single_az,
                            )

                            pallas_solve_queue_single_az(
                                avail, rank, eok,
                                jnp.full((nb,), -1, jnp.int32),
                                *apps,
                                jnp.zeros((nb,), jnp.int32),
                                jnp.zeros((nb,), jnp.int32),
                                jnp.zeros((nb,), jnp.float32),
                                jnp.zeros((nb,), jnp.int32),
                                jnp.asarray(_np.array([1], _np.int32)),
                                jnp.asarray(_np.array([1], _np.int32)),
                                n_zones=warm_zones,
                                az_aware=az_aware,
                            )
                        else:
                            solve_queue_single_az(
                                avail, rank, eok,
                                jnp.zeros((warm_zones, nb), bool),
                                *apps,
                                jnp.zeros((nb,), jnp.int32),
                                jnp.zeros((nb,), jnp.int32),
                                jnp.zeros((nb,), jnp.float32),
                                jnp.zeros((nb,), jnp.int32),
                                jnp.int32(1),
                                jnp.int32(1),
                                az_aware=az_aware,
                            )
                    elif minfrag:
                        solve_queue_min_frag(
                            avail, rank, eok, *apps, with_placements=False
                        )
                    elif use_pallas:
                        from ..ops.pallas_queue import pallas_solve_queue

                        pallas_solve_queue(avail, rank, eok, *apps, evenly=evenly)
                    else:
                        solve_queue(
                            avail, rank, eok, *apps,
                            evenly=evenly, with_placements=False,
                        )
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "solver warmup failed; first request will compile",
                    exc_info=True,
                )
            finally:
                self._warm_done.set()

        self._warm_stop = threading.Event()
        self._warm_thread = threading.Thread(
            target=warm, daemon=True, name="solver-warmup"
        )
        self._warm_thread.start()

    def stop(self) -> None:
        import time as _time

        deadline = _time.monotonic() + 20.0  # headroom inside the k8s  # schedlint: disable=TS002 -- shutdown grace period is real wall time granted by the kubelet
        # default 30s termination grace period, measured from stop() entry
        warm_thread = getattr(self, "_warm_thread", None)
        if warm_thread is not None:
            self._warm_stop.set()  # signal first; join after the other stops
        if self.reporters is not None:
            self.reporters.stop()
        if self.capacity is not None:
            self.capacity.stop()
        if self.lifecycle is not None:
            self.lifecycle.stop()
        if self.ha is not None:
            self.ha.stop()
            try:
                # graceful handoff: expire our own lease so the standby
                # takes over in one step instead of waiting out the TTL
                self.ha.elector.step_down()
            except Exception:
                pass
        self.unschedulable_marker.stop()
        self.resource_reservation_cache.stop()
        self.demand_cache.stop()
        if self.resilience is not None:
            # the journal keeps its pending (unlanded) intents on disk
            # for the next instance's failover replay
            self.resilience.journal.close()
        if self.policy is not None:
            # same contract for the evict journal
            self.policy.close()
        if warm_thread is not None:
            # a healthy compile finishes in seconds; a wedged device must
            # not stall shutdown past the grace period, so give up at the
            # deadline (the daemon flag then lets the process exit, at
            # worst uncleanly)
            warm_thread.join(timeout=max(0.0, deadline - _time.monotonic()))  # schedlint: disable=TS002 -- remaining real-time budget of the shutdown grace period
            if warm_thread.is_alive():
                import logging

                logging.getLogger(__name__).warning(
                    "solver warmup still compiling at shutdown deadline; abandoning it"
                )


def init_server_with_clients(
    api: APIServer,
    install: Install,
    start_background: bool = True,
    demand_poll_interval: float = 1.0,
    unschedulable_polling_interval: float = 60.0,
) -> Server:
    """cmd/server.go:65-237, bottom-up."""
    # contention observatory switchboard FIRST: the guarded singletons
    # constructed below get their sampling stride from it, and enabling
    # before construction means their very first acquires record
    contention_keeper = None
    if install.contention.enabled:
        from ..contention import locktime

        locktime.set_default_sample_every(install.contention.sample_every)
        contention_keeper = locktime.enable()
    metrics = MetricsRegistry()
    event_log = EventLog()
    # request tracing + kernel profiling sinks.  The profiler is a
    # module-level singleton (solvers are built without wiring access);
    # rebinding it here points kernel metrics/spans at THIS server —
    # correct for the one-server-per-process production shape.
    tracer = Tracer(capacity=256, metrics=metrics)
    kernel_profiling.default_profiler.configure(metrics=metrics, tracer=tracer)
    # critical-path extraction rides trace completion: every finished
    # request tree decomposes into gate-queue / lock-wait / serde /
    # solve / write-back segments (contention/criticalpath.py)
    criticalpath_analyzer = None
    if install.contention.enabled:
        from ..contention import CriticalPathAnalyzer

        criticalpath_analyzer = CriticalPathAnalyzer(
            metrics=metrics, capacity=install.contention.ring_size
        )
        tracer.add_observer(criticalpath_analyzer.on_trace)
    # node-name interning counters land in THIS server's registry (the
    # interner is module-level for the same reason the profiler is)
    from ..types import serde as _serde

    _serde.names_interner.metrics = metrics

    # CRD ensure (cmd/server.go:83-85)
    crd.ensure_resource_reservations_crd(
        api,
        install.resource_reservation_crd_annotations,
        conversion_webhook=install.conversion_webhook,
    )

    # informer factories + sync (cmd/server.go:91-127)
    factory = InformerFactory(api)
    pod_informer = factory.informer(
        Pod.KIND, index_labels=("spark-app-id", "spark-role")
    )
    node_informer = factory.informer(Node.KIND)
    rr_informer = factory.informer(ResourceReservation.KIND)
    factory.start()

    # caches (cmd/server.go:129-155); one shared write-rate bucket per
    # process, like the kube clientsets' QPS/Burst (cmd/clients.go:53-54)
    from ..kube.ratelimit import TokenBucket

    # overload protection: admission gate, write-back breaker + intent
    # journal, kernel-lane health, tri-state readiness (resilience/)
    resilience_kit = build_kit(install.resilience, metrics=metrics)

    rate_bucket = TokenBucket(install.qps, install.burst) if install.qps > 0 else None
    rr_cache = ResourceReservationCache(
        api,
        rr_informer,
        install.async_client.max_retry_count,
        rate_bucket=rate_bucket,
        breaker=resilience_kit.breaker,
        journal=resilience_kit.journal,
        registry=metrics,
    )
    # failover: intents journaled by a previous instance (durable
    # journal-path) replay through the idempotent write path before any
    # scheduling decision reads the cache
    rr_cache.recover_from_journal()
    lazy_demand_informer = LazyDemandInformer(api, factory, poll_interval=demand_poll_interval)
    binpacker = select_binpacker(
        install.binpack_algo, strict_reference_parity=install.strict_reference_parity
    )
    demand_cache = SafeDemandCache(
        lazy_demand_informer,
        api,
        install.async_client.max_retry_count,
        rate_bucket=rate_bucket,
        registry=metrics,
    )
    demand_manager = DemandManager(
        demand_cache, binpacker, install.instance_group_label, event_log
    )
    start_demand_gc(pod_informer, demand_manager)

    # stores + managers (cmd/server.go:157-167)
    soft_store = SoftReservationStore(pod_informer)
    pod_lister = SparkPodLister(pod_informer, install.instance_group_label)
    rrm = ResourceReservationManager(
        rr_cache, soft_store, pod_lister, pod_informer, metrics=metrics, tracer=tracer
    )
    overhead = OverheadComputer(pod_informer, rrm)

    # event-driven integer snapshot for the tpu-batch fast path
    tensor_snapshot = TensorSnapshotCache(node_informer, pod_informer, rr_cache, soft_store)

    # waste reporter (cmd/server.go:171-191 NewWasteMetricsReporter)
    waste_reporter = WasteMetricsReporter(metrics, install.instance_group_label)
    waste_reporter.start(pod_informer, lazy_demand_informer)

    # decision provenance: unschedulability explainer + shortfall
    # telemetry + anomaly flight recorder (provenance/)
    provenance_tracker = None
    if install.provenance.enabled:
        from ..provenance.tracker import ProvenanceTracker

        provenance_tracker = ProvenanceTracker(
            enabled=True,
            ring_size=install.provenance.ring_size,
            recorder_size=install.provenance.recorder_size,
            bundle_dir=install.provenance.bundle_dir,
            max_bundle_nodes=install.provenance.max_bundle_nodes,
            metrics=metrics,
            trigger_min_interval=install.provenance.trigger_min_interval_seconds,
        )
        # write-back breaker opening is a flight-recorder trigger: the
        # recent decisions leading into an open breaker are exactly the
        # forensic record an operator wants
        resilience_kit.breaker.on_open = (
            lambda name: provenance_tracker.on_trigger(
                "breaker-open", f"breaker {name} opened"
            )
        )

    # capacity observatory: fragmentation/headroom analytics + the
    # /state/capacity timeline, sampled off-lock on ChangeFeed triggers
    capacity_sampler = None
    if install.capacity.enabled:
        from ..capacity import CapacitySampler

        capacity_sampler = CapacitySampler(
            tensor_snapshot,
            pod_lister=pod_lister,
            waste_reporter=waste_reporter,
            metrics=metrics,
            instance_group_label=install.instance_group_label,
            ring_size=install.capacity.ring_size,
            debounce_seconds=install.capacity.debounce_seconds,
            interval_seconds=install.capacity.interval_seconds,
            max_shapes=install.capacity.max_shapes,
            max_group_zones=install.capacity.max_group_zones,
            max_queue=install.capacity.max_queue,
        )

    # scheduling-policy engine (policy/): priority ordering, backfill,
    # gang-aware preemption, DRF.  None when disabled — the extender's
    # hooks then cost one attribute check and decisions are
    # byte-identical to pre-policy behavior.
    policy_engine = None
    if install.policy.enabled:
        from ..policy import PolicyEngine

        policy_engine = PolicyEngine(
            install.policy,
            pod_lister=pod_lister,
            tensor_snapshot=tensor_snapshot,
            rr_cache=rr_cache,
            api=api,
            journal_path=install.resilience.journal_path,
            metrics=metrics,
            provenance=provenance_tracker,
        )
        # failover: evict intents journaled by a previous instance
        # replay exactly-once before any scheduling decision runs
        # (mirrors rr_cache.recover_from_journal above)
        policy_engine.recover()

    # gang lifecycle ledger + SLO engine (lifecycle/): per-application
    # state machine fed off informer threads and drain cursors — never
    # under the predicate lock.  The waste reporter's slo_sink makes
    # WasteMetricsReporter the single source of truth for the
    # eviction_waste objective.
    lifecycle_ledger = None
    slo_engine = None
    if install.lifecycle.enabled:
        from ..lifecycle import LifecycleLedger, SloEngine

        slo_engine = SloEngine(
            metrics=metrics,
            window_scale=install.lifecycle.window_scale,
            sample_cap=install.lifecycle.sample_cap,
            overrides=install.lifecycle.objectives,
        )
        waste_reporter.slo_sink = slo_engine.waste_sample
        lifecycle_ledger = LifecycleLedger(
            event_log=event_log,
            tracer=tracer,
            feed=tensor_snapshot.feed,
            policy=policy_engine,
            slo=slo_engine,
            metrics=metrics,
            ring_size=install.lifecycle.ring_size,
            debounce_seconds=install.lifecycle.debounce_seconds,
            interval_seconds=install.lifecycle.interval_seconds,
        )
        lifecycle_ledger.wire_informers(
            pod_informer=pod_informer, rr_informer=rr_informer
        )

    # extender (cmd/server.go:171-191)
    node_sorter = NodeSorter(
        install.driver_prioritized_node_label, install.executor_prioritized_node_label
    )
    extender = SparkSchedulerExtender(
        node_informer=node_informer,
        pod_lister=pod_lister,
        resource_reservation_cache=rr_cache,
        soft_reservation_store=soft_store,
        resource_reservation_manager=rrm,
        demands_manager=demand_manager,
        is_fifo=install.fifo,
        fifo_config=install.fifo_config,
        binpacker=binpacker,
        should_schedule_dynamically_allocated_executors_in_same_az=(
            install.should_schedule_dynamically_allocated_executors_in_same_az
        ),
        overhead_computer=overhead,
        instance_group_label=install.instance_group_label,
        node_sorter=node_sorter,
        metrics=metrics,
        event_log=event_log,
        waste_reporter=waste_reporter,
        tensor_snapshot_cache=tensor_snapshot,
        strict_reference_parity=install.strict_reference_parity,
        tracer=tracer,
        resilience=resilience_kit,
        delta_solve=install.delta_solve,
        provenance=provenance_tracker,
        policy=policy_engine,
    )
    if policy_engine is not None:
        # what-if victim validation rides the extender's warm
        # delta-solve sessions (ops/deltasolve.py latest_basis)
        policy_engine._delta_engine = extender.delta_engine
    if slo_engine is not None:
        # decision traces carry the active SLO alert states (one
        # precomputed-attribute read; never a burn-rate computation on
        # the Filter path — evaluate() runs at ledger drain time)
        extender.slo_alert_source = lambda: slo_engine.alert_tag
    if provenance_tracker is not None and extender.delta_engine is not None:
        # warm≠cold parity guard: every Nth warm hit re-proves the
        # session verdicts against the stateless cold solver and fires
        # the flight recorder on divergence (0 = off)
        extender.delta_engine.parity_interval = (
            install.provenance.parity_check_interval
        )
        extender.delta_engine.parity_hooks = (
            provenance_tracker.on_parity_ok,
            provenance_tracker.on_parity_mismatch,
        )
    if extender.delta_engine is not None:
        # equivalence-class aggregation (Install.classes): the O(1)
        # digest warm tier + class-compressed native solves at scale
        extender.delta_engine.classes_enabled = install.classes.enabled
        extender.delta_engine.classes_min_nodes = install.classes.min_nodes
    marker = UnschedulablePodMarker(
        api,
        node_informer,
        pod_informer,
        overhead,
        binpacker,
        timeout_seconds=install.unschedulable_pod_timeout_seconds,
        polling_interval_seconds=unschedulable_polling_interval,
    )

    server = Server(
        api=api,
        install=install,
        informer_factory=factory,
        pod_informer=pod_informer,
        node_informer=node_informer,
        rr_informer=rr_informer,
        resource_reservation_cache=rr_cache,
        lazy_demand_informer=lazy_demand_informer,
        demand_cache=demand_cache,
        demand_manager=demand_manager,
        soft_reservation_store=soft_store,
        pod_lister=pod_lister,
        resource_reservation_manager=rrm,
        overhead_computer=overhead,
        extender=extender,
        tensor_snapshot=tensor_snapshot,
        unschedulable_marker=marker,
        metrics=metrics,
        event_log=event_log,
        tracer=tracer,
        waste_reporter=waste_reporter,
        resilience=resilience_kit,
        provenance=provenance_tracker,
        capacity=capacity_sampler,
        contention=contention_keeper,
        criticalpath=criticalpath_analyzer,
        policy=policy_engine,
        lifecycle=lifecycle_ledger,
        slo=slo_engine,
    )
    server.reporters = ReporterSet(server)

    # HA failover fabric (ha/): lease election + fencing + takeover
    # reconciliation.  Built AFTER the boot-time journal recovery above
    # on purpose: a cold replica's own replay must not be fenced (the
    # gates are installed here, so everything before this line runs
    # unfenced; everything after is epoch-checked).
    if install.ha.enabled:
        import os as _os
        import socket as _socket

        from ..ha import FencedWriter, FenceState, HAFabric
        from ..ha.lease import LeaderElector
        from ..ha.reconcile import Reconciler

        identity = install.ha.identity or (
            f"{_socket.gethostname()}-{_os.getpid()}"
        )
        fence = FenceState(metrics=metrics)
        elector = LeaderElector(
            api,
            identity,
            fence,
            namespace=install.ha.lease_namespace,
            name=install.ha.lease_name,
            duration_seconds=install.ha.lease_duration_seconds,
        )
        # read-through gate: every fenced write re-reads the lease, so a
        # deposed leader's first post-pause write refuses deterministically
        gate = FencedWriter(fence, lease_reader=elector.peek, metrics=metrics)
        # decision traces carry the epoch they were served under (one
        # lock-free-ish counter read; never a lease fetch on the Filter
        # path)
        extender.epoch_source = fence.epoch
        if lifecycle_ledger is not None:
            # lifecycle records stamp the epoch each transition was
            # observed under (epoch continuity across failover)
            lifecycle_ledger.epoch_source = fence.epoch
        rr_cache.install_fence(gate)
        demand_cache.install_fence(gate)
        if policy_engine is not None and policy_engine.coordinator is not None:
            policy_engine.coordinator.install_fence(gate)
        server.ha = HAFabric(
            elector,
            fence,
            reconciler=Reconciler(server, metrics=metrics),
            metrics=metrics,
            renew_interval_seconds=install.ha.renew_interval_seconds,
            writer=gate,
        )

    # concurrent admission engine (concurrent/): speculative solves in
    # parallel, commits through the FIFO gate.  Built AFTER the HA block
    # so multi-active intents are stamped with the live fencing epoch;
    # before the invariants wrapper is fine — commits run the serial
    # extender, so the wrapped _predicate_locked still fires per commit.
    if install.concurrent.enabled:
        from ..concurrent import ConcurrentAdmissionEngine

        epoch_source = None
        if server.ha is not None:
            epoch_source = server.ha.fence.epoch
        server.concurrent = ConcurrentAdmissionEngine(
            extender,
            install.concurrent,
            metrics=metrics,
            epoch_source=epoch_source,
        )

    from ..scheduler import invariants

    if invariants.enabled():
        # wrap INSIDE the predicate lock so the check always sees
        # quiesced post-predicate state (no races with a concurrent
        # Filter call mid-mutation)
        original = extender._predicate_locked

        def checked_predicate_locked(args):
            result = original(args)
            invariants.check(server, raise_on_violation=False)
            return result

        extender._predicate_locked = checked_predicate_locked
    if start_background:
        server.start_background()
    return server
