"""Deterministic discrete-event cluster simulator.

Drives the REAL scheduler wiring (embedded API server, full
``server/wiring.py`` Server, real solver lanes) on a virtual clock:

- :mod:`.clock` — event heap + controllable time source (installed into
  :mod:`..timesource` so GC/failover/FIFO/unschedulable timers fire at
  simulated instants);
- :mod:`.workload` — seeded arrival/size/lifetime generators and JSONL
  trace replay;
- :mod:`.scenario` — declarative spec composing cluster shape, workload,
  autoscaler behavior, and injected faults;
- :mod:`.auditor` — per-event invariant auditing through
  ``scheduler/invariants.py`` plus FIFO-order and demand-hygiene checks;
- :mod:`.runner` — the engine + replayable event log with a content
  digest (same seed ⇒ identical digest) and a summary JSON.

CLI: ``python -m k8s_spark_scheduler_tpu.sim --scenario examples/sim/chaos.json --seed 42``
"""

from .clock import VirtualClock
from .scenario import Scenario
from .runner import Simulation, SimulationResult

__all__ = ["VirtualClock", "Scenario", "Simulation", "SimulationResult"]
