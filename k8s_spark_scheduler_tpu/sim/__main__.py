"""CLI: run a scenario, write the replayable event log + summary.

    JAX_PLATFORMS=cpu python -m k8s_spark_scheduler_tpu.sim \\
        --scenario examples/sim/chaos.json --seed 42 --out /tmp/sim-chaos

Same scenario + same seed ⇒ byte-identical event-log digest (printed as
``digest=...`` and embedded in summary.json), so a sim run is a
reviewable, diffable artifact: re-run a reported digest to reproduce,
diff two event logs to bisect a behavior change.

``--dump-trace`` writes the generated workload as JSONL; a scenario
whose ``workload`` is ``{"trace": "path.jsonl"}`` replays it verbatim.

``--replay-bundle <path.jsonl>`` replays a flight-recorder bundle file
(provenance/recorder.py): every recorded decision re-runs through the
stateless cold native solver AND a fresh persistent session (warm lane,
twice), asserting byte-identical verdicts.  Exit 0 = every bundle
reproduced exactly; a mismatch prints the diverging lane and exits 1.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

from .manifest import write_run_manifest
from .runner import Simulation
from .scenario import Scenario
from .workload import WorkloadGenerator, dump_trace


def _replay_bundles(path: str, quiet: bool = False) -> int:
    from ..provenance.recorder import replay_bundle_file

    results = replay_bundle_file(path)
    failed = [r for r in results if not r["ok"]]
    if not quiet:
        for r in results:
            status = "ok" if r["ok"] else "MISMATCH"
            lanes = ",".join(f"{k}={v}" for k, v in sorted(r["lanes"].items()))
            print(
                f"bundle seq={r['seq']} pod={r['pod']} policy={r['policy']} "
                f"nEarlier={r['nEarlier']} [{lanes}] {status}"
            )
            for m in r["mismatches"]:
                print(f"  MISMATCH: {m}", file=sys.stderr)
    print(
        f"replayed {len(results)} bundles: "
        f"{len(results) - len(failed)} byte-identical, {len(failed)} diverged"
    )
    return 1 if failed or not results else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_spark_scheduler_tpu.sim",
        description="deterministic discrete-event cluster simulator",
    )
    parser.add_argument("--scenario", default=None, help="scenario JSON path")
    parser.add_argument("--seed", type=int, default=None, help="override the scenario seed")
    parser.add_argument("--out", default=None, help="output directory (events.jsonl, summary.json)")
    parser.add_argument(
        "--dump-trace", default=None, metavar="PATH",
        help="write the generated workload trace as JSONL and exit",
    )
    parser.add_argument(
        "--replay-bundle", default=None, metavar="PATH",
        help="replay a flight-recorder bundle file and assert "
        "byte-identical verdicts (no scenario needed)",
    )
    parser.add_argument(
        "--override-nodes", type=int, default=None, metavar="N",
        help="override scenario.cluster.nodes — CI runs the 100k-node "
        "class-churn scenario scaled down; digests are only comparable "
        "at the same node count",
    )
    parser.add_argument("--quiet", action="store_true", help="suppress the summary dump")
    args = parser.parse_args(argv)

    if args.replay_bundle:
        return _replay_bundles(args.replay_bundle, quiet=args.quiet)
    if not args.scenario:
        parser.error("--scenario is required (unless --replay-bundle)")

    scenario = Scenario.from_file(args.scenario)
    if args.seed is not None:
        scenario.seed = args.seed
    if args.override_nodes is not None:
        scenario.cluster.nodes = args.override_nodes

    if args.dump_trace:
        apps = WorkloadGenerator(scenario.workload, scenario.seed).generate(scenario.duration)
        dump_trace(apps, args.dump_trace)
        print(f"wrote {len(apps)} apps to {args.dump_trace}")
        return 0

    bundle_dir = os.path.join(args.out, "bundles") if args.out else None
    result = Simulation(scenario, bundle_dir=bundle_dir).run()

    if args.out:
        os.makedirs(args.out, exist_ok=True)
        with open(os.path.join(args.out, "events.jsonl"), "w") as f:
            for entry in result.event_log:
                f.write(json.dumps(entry, sort_keys=True, separators=(",", ":")) + "\n")
        with open(os.path.join(args.out, "summary.json"), "w") as f:
            json.dump(result.summary, f, indent=2, sort_keys=True)
        # capacity-observatory timeline (one sample per state-changing
        # event): the chaos-CI artifact alongside flight-recorder bundles
        with open(os.path.join(args.out, "capacity.jsonl"), "w") as f:
            for sample in result.capacity_timeline:
                f.write(
                    json.dumps(sample, sort_keys=True, separators=(",", ":"))
                    + "\n"
                )
        # contention report (predicate-lock wait/hold + critical-path
        # decomposition): the "is the lock or the solver the bottleneck"
        # artifact for the chaos-CI job
        with open(os.path.join(args.out, "contention.json"), "w") as f:
            json.dump(
                result.summary.get("contention"), f, indent=2, sort_keys=True
            )
        # SLO scorecard (same schema as a live GET /slo): the input to
        # the policy-regression gate (tools/policy_regression.py)
        if result.summary.get("slo") is not None:
            with open(os.path.join(args.out, "scorecard.json"), "w") as f:
                json.dump(result.summary["slo"], f, indent=2, sort_keys=True)
        # self-describing manifest: seed, scenario digest, and a
        # sha256-addressed list of every artifact written above
        scenario_blob = json.dumps(
            scenario.to_dict(), sort_keys=True, separators=(",", ":")
        )
        write_run_manifest(
            args.out,
            kind="sim-run",
            seed=scenario.seed,
            digests={
                "events": result.digest,
                "scenario": hashlib.sha256(scenario_blob.encode()).hexdigest(),
            },
            extra={"scenario": scenario.name},
        )

    if not args.quiet:
        json.dump(result.summary, sys.stdout, indent=2, sort_keys=True)
        print()
    for v in result.violations:
        print(f"VIOLATION: {v}", file=sys.stderr)
    print(f"digest={result.digest}")
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
