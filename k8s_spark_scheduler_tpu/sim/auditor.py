"""Per-event invariant auditing.

After every simulated event (post-quiesce, so async write-back has
drained and the local caches agree with the API server) the auditor
runs:

1. the full ``scheduler/invariants.py`` suite (I1–I5: reservation⇄pod
   consistency, no double-binding, soft-reservation hygiene, **no node
   over-commit**, tensor-mirror exactness);
2. FIFO-order checks over the scheduling round's decisions: the runner
   attempts pending drivers in strict (creation, app_id) order — the
   order kube-scheduler's queue would present them — and a round where
   an earlier same-instance-group driver was refused with
   ``failure-earlier-driver`` while a LATER driver succeeded is an
   order inversion (a later driver succeeding after an earlier one
   fails ``failure-fit`` is legitimate: the FIFO feasibility pass
   reserves the earlier gang's space, it doesn't hard-block the queue);
3. demand hygiene: after quiesce, every Demand's owner pod must still
   exist and still be unscheduled — a demand surviving its pod's
   scheduling means the inline delete AND DemandGC both missed it, a
   demand for a deleted pod means owner GC missed it (the "demands
   created/deleted exactly when the reference would" check in
   observable terms).

Violations accumulate in ``violations`` (the run fails its acceptance
bar when non-empty) and are counted into the PR 1 metrics registry
under ``sim.audit.violations``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..demands.manager import pod_name_from_demand
from ..scheduler import invariants
from ..scheduler.extender import FAILURE_EARLIER_DRIVER
from ..types.objects import Demand, Pod, ResourceReservation


@dataclass
class Decision:
    """One predicate outcome inside a scheduling round."""

    pod_name: str
    role: str  # "driver" | "executor"
    instance_group: str
    created: float
    outcome: str  # success | the failure-* outcomes
    node: str = ""


class Auditor:
    def __init__(self, server, metrics=None):
        self._server = server
        self._metrics = metrics if metrics is not None else server.metrics
        self.violations: List[str] = []
        self.events_audited = 0

    # -- per-round decision checks -------------------------------------------

    def check_round(self, decisions: List[Decision], label: str) -> None:
        """FIFO-order audit over one scheduling round's driver decisions."""
        drivers = [d for d in decisions if d.role == "driver"]
        # the runner must present drivers oldest-first (per group the
        # arrival order IS the FIFO order); a mis-sorted round would
        # make every downstream FIFO conclusion vacuous, so audit it
        by_group: dict = {}
        for d in drivers:
            by_group.setdefault(d.instance_group, []).append(d)
        for group, ds in by_group.items():
            keys = [(d.created, d.pod_name) for d in ds]
            if keys != sorted(keys):
                self._violate(
                    f"F0[{label}]: round attempted {group} drivers out of arrival order: {keys}"
                )
            blocked_behind_earlier = None
            for d in ds:
                if d.outcome == FAILURE_EARLIER_DRIVER and blocked_behind_earlier is None:
                    blocked_behind_earlier = d
                elif blocked_behind_earlier is not None and d.outcome == "success":
                    self._violate(
                        f"F1[{label}]: driver {d.pod_name} succeeded after earlier "
                        f"driver {blocked_behind_earlier.pod_name} (same group "
                        f"{group}) was refused with failure-earlier-driver"
                    )

    # -- per-event state checks ----------------------------------------------

    def check_state(self, label: str) -> None:
        """Invariants I1–I5 + demand hygiene against quiesced state."""
        self.events_audited += 1
        for v in invariants.check(self._server, raise_on_violation=False):
            self._violate(f"{v} [{label}]")
        self._check_demand_hygiene(label)
        self._check_lost_intents(label)
        self._metrics.gauge("sim.audit.events", float(self.events_audited))

    def _check_demand_hygiene(self, label: str) -> None:
        api = self._server.api
        pods = {(p.namespace, p.name): p for p in api.list(Pod.KIND)}
        for demand in api.list(Demand.KIND):
            pod_name = pod_name_from_demand(demand)
            pod = pods.get((demand.namespace, pod_name))
            if pod is None:
                self._violate(
                    f"D1[{label}]: demand {demand.name} outlives its pod {pod_name}"
                )
            elif pod.node_name:
                self._violate(
                    f"D2[{label}]: demand {demand.name} still present after pod "
                    f"{pod_name} was scheduled to {pod.node_name}"
                )

    def _check_lost_intents(self, label: str) -> None:
        """Zero-lost-reservation-intents (resilience/): after quiesce,
        every reservation the scheduler admitted against must either be
        at the API server or be covered by a pending intent-journal
        entry (J1) — and symmetrically, a reservation the scheduler
        deleted locally must be gone from the API server or have its
        delete journaled (J2).  A key in neither place is an intent the
        write-back layer silently lost."""
        server = self._server
        kit = getattr(server, "resilience", None)
        pending = kit.journal.pending_keys() if kit is not None else set()
        api_keys = {
            (rr.namespace, rr.name)
            for rr in server.api.list(ResourceReservation.KIND)
        }
        local_keys = {
            (rr.namespace, rr.name)
            for rr in server.resource_reservation_cache.list()
        }
        for key in sorted(local_keys - api_keys - pending):
            self._violate(
                f"J1[{label}]: reservation {key} admitted locally but neither "
                f"written to the API server nor journaled (lost intent)"
            )
        for key in sorted(api_keys - local_keys - pending):
            self._violate(
                f"J2[{label}]: reservation {key} deleted locally but still at "
                f"the API server with no journaled delete (lost intent)"
            )

    def _violate(self, message: str) -> None:
        self.violations.append(message)
        self._metrics.counter("sim.audit.violations")
