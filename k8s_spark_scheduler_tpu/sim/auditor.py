"""Per-event invariant auditing.

After every simulated event (post-quiesce, so async write-back has
drained and the local caches agree with the API server) the auditor
runs:

1. the full ``scheduler/invariants.py`` suite (I1–I5: reservation⇄pod
   consistency, no double-binding, soft-reservation hygiene, **no node
   over-commit**, tensor-mirror exactness);
2. FIFO-order checks over the scheduling round's decisions: the runner
   attempts pending drivers in strict (creation, app_id) order — the
   order kube-scheduler's queue would present them — and a round where
   an earlier same-instance-group driver was refused with
   ``failure-earlier-driver`` while a LATER driver succeeded is an
   order inversion (a later driver succeeding after an earlier one
   fails ``failure-fit`` is legitimate: the FIFO feasibility pass
   reserves the earlier gang's space, it doesn't hard-block the queue);
3. demand hygiene: after quiesce, every Demand's owner pod must still
   exist and still be unscheduled — a demand surviving its pod's
   scheduling means the inline delete AND DemandGC both missed it, a
   demand for a deleted pod means owner GC missed it (the "demands
   created/deleted exactly when the reference would" check in
   observable terms).

When the server carries a policy engine (``Install.policy.enabled``)
the audit widens to the policy invariants:

- **I-P1** — no partial-gang eviction: an app the preemption
  coordinator reports evicted must hold no ResourceReservation and no
  still-bound pod (the victim unit is the whole application);
- **I-P2** — bounded priority inversion: with a priority ordering and
  backfill disabled, a lower-band driver never succeeds in a round
  after a higher-band driver was refused ``failure-earlier-driver``;
- **I-P3** — starvation freedom: backfill never jumps past a refused
  driver older than ``starvation_age_seconds``;
- **I-P4** — every eviction journaled: the evict journal is empty
  post-quiesce (each committed eviction was journaled, executed, and
  acked — a pending intent after quiesce is a lost/unacked eviction);

and the FIFO F1 check becomes band-aware: within a band the queue is
still FIFO, across bands priority order replaces arrival order.

When the server carries an HA fabric (``Install.ha.enabled``) the
audit widens again:

- **I-H1** — at most one fenced writer per epoch: the lease history's
  epochs are strictly increasing with one holder each, the live fence
  never holds an epoch above the lease's (a self-granted token), and a
  replica claiming leadership is the lease's recorded holder;
- **I-H2** — no acked intent lost across takeover: journaled intents
  never carry an epoch the fabric has not observed (a future-stamped
  record would replay against the wrong leadership term; the
  exactly-once replay itself is J1/J2 plus the crash matrix);
- **I-H3** — no write committed with a stale epoch: the fence's
  stale-commit witness counter is zero (by construction; nonzero means
  a fenced write landed after a newer epoch was observed).

Violations accumulate in ``violations`` (the run fails its acceptance
bar when non-empty) and are counted into the PR 1 metrics registry
under the catalog name ``…tpu.sim.audit.violations.count``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from .. import timesource
from ..demands.manager import pod_name_from_demand
from ..metrics import names as mnames
from ..scheduler import invariants
from ..scheduler import labels as L
from ..scheduler.extender import FAILURE_EARLIER_DRIVER
from ..types.objects import Demand, Pod, ResourceReservation


@dataclass
class Decision:
    """One predicate outcome inside a scheduling round."""

    pod_name: str
    role: str  # "driver" | "executor"
    instance_group: str
    created: float
    outcome: str  # success | the failure-* outcomes
    node: str = ""
    # policy runs only: the driver's priority band (policy/classes.py)
    band: str = ""
    band_rank: int = 0


class Auditor:
    def __init__(self, server, metrics=None):
        self._server = server
        self._metrics = metrics if metrics is not None else server.metrics
        self._policy = getattr(server, "policy", None)
        self.violations: List[str] = []
        self.events_audited = 0

    # -- per-round decision checks -------------------------------------------

    def check_round(self, decisions: List[Decision], label: str) -> None:
        """FIFO-order audit over one scheduling round's driver decisions."""
        drivers = [d for d in decisions if d.role == "driver"]
        # the runner must present drivers oldest-first (per group the
        # arrival order IS the FIFO order); a mis-sorted round would
        # make every downstream FIFO conclusion vacuous, so audit it
        by_group: dict = {}
        for d in drivers:
            by_group.setdefault(d.instance_group, []).append(d)
        for group, ds in by_group.items():
            keys = [(d.created, d.pod_name) for d in ds]
            if keys != sorted(keys):
                self._violate(
                    f"F0[{label}]: round attempted {group} drivers out of arrival order: {keys}"
                )
            ordering = (
                self._policy.config.ordering if self._policy is not None else "fifo"
            )
            if ordering != "fifo":
                self._check_policy_round(group, ds, label)
                continue
            blocked_behind_earlier = None
            for d in ds:
                if d.outcome == FAILURE_EARLIER_DRIVER and blocked_behind_earlier is None:
                    blocked_behind_earlier = d
                elif blocked_behind_earlier is not None and d.outcome == "success":
                    self._violate(
                        f"F1[{label}]: driver {d.pod_name} succeeded after earlier "
                        f"driver {blocked_behind_earlier.pod_name} (same group "
                        f"{group}) was refused with failure-earlier-driver"
                    )

    def _check_policy_round(self, group: str, ds: List[Decision], label: str) -> None:
        """Band-aware ordering audit for non-FIFO policy orderings.
        Within a band the queue is still FIFO; across bands a
        higher-band success after a lower-band refusal is the POINT of
        priority ordering, while the reverse is an inversion — legal
        only through the conservative backfill probe (I-P2), and never
        past the refused driver's starvation age (I-P3)."""
        cfg = self._policy.config
        refused: List[Decision] = []
        for d in ds:
            if d.outcome == FAILURE_EARLIER_DRIVER:
                refused.append(d)
                continue
            if d.outcome != "success":
                continue
            for r in refused:
                if d.band_rank > r.band_rank:
                    continue  # priority order doing its job
                if not cfg.backfill:
                    kind = "F1" if d.band_rank == r.band_rank else "I-P2"
                    self._violate(
                        f"{kind}[{label}]: driver {d.pod_name} (band {d.band}) "
                        f"succeeded after driver {r.pod_name} (band {r.band}, "
                        f"group {group}) was refused failure-earlier-driver "
                        f"with backfill disabled"
                    )
                elif timesource.now() - r.created >= cfg.starvation_age_seconds:
                    self._violate(
                        f"I-P3[{label}]: backfill admitted {d.pod_name} (band "
                        f"{d.band}) past {r.pod_name} (band {r.band}), which has "
                        f"been starving for >= {cfg.starvation_age_seconds}s"
                    )

    # -- per-event state checks ----------------------------------------------

    def check_state(self, label: str) -> None:
        """Invariants I1–I5 + demand hygiene against quiesced state."""
        self.events_audited += 1
        for v in invariants.check(self._server, raise_on_violation=False):
            self._violate(f"{v} [{label}]")
        self._check_demand_hygiene(label)
        self._check_lost_intents(label)
        self._check_policy_state(label)
        self._check_ha(label)
        self._metrics.gauge(mnames.SIM_AUDIT_EVENTS, float(self.events_audited))

    def _check_demand_hygiene(self, label: str) -> None:
        api = self._server.api
        pods = {(p.namespace, p.name): p for p in api.list(Pod.KIND)}
        for demand in api.list(Demand.KIND):
            pod_name = pod_name_from_demand(demand)
            pod = pods.get((demand.namespace, pod_name))
            if pod is None:
                self._violate(
                    f"D1[{label}]: demand {demand.name} outlives its pod {pod_name}"
                )
            elif pod.node_name:
                self._violate(
                    f"D2[{label}]: demand {demand.name} still present after pod "
                    f"{pod_name} was scheduled to {pod.node_name}"
                )

    def _check_lost_intents(self, label: str) -> None:
        """Zero-lost-reservation-intents (resilience/): after quiesce,
        every reservation the scheduler admitted against must either be
        at the API server or be covered by a pending intent-journal
        entry (J1) — and symmetrically, a reservation the scheduler
        deleted locally must be gone from the API server or have its
        delete journaled (J2).  A key in neither place is an intent the
        write-back layer silently lost."""
        server = self._server
        kit = getattr(server, "resilience", None)
        pending = kit.journal.pending_keys() if kit is not None else set()
        api_keys = {
            (rr.namespace, rr.name)
            for rr in server.api.list(ResourceReservation.KIND)
        }
        local_keys = {
            (rr.namespace, rr.name)
            for rr in server.resource_reservation_cache.list()
        }
        for key in sorted(local_keys - api_keys - pending):
            self._violate(
                f"J1[{label}]: reservation {key} admitted locally but neither "
                f"written to the API server nor journaled (lost intent)"
            )
        for key in sorted(api_keys - local_keys - pending):
            self._violate(
                f"J2[{label}]: reservation {key} deleted locally but still at "
                f"the API server with no journaled delete (lost intent)"
            )

    def _check_policy_state(self, label: str) -> None:
        """I-P1 + I-P4 against quiesced state.  Runs BEFORE the
        runner's eviction reap, so a partial eviction cannot be masked
        by the sim's own cleanup."""
        engine = self._policy
        if engine is None or engine.coordinator is None:
            return
        st = engine.coordinator.state()
        if st["journalDepth"] != 0:
            self._violate(
                f"I-P4[{label}]: {st['journalDepth']} evict intents still "
                f"pending post-quiesce (eviction executed without ack, or "
                f"journaled and never executed)"
            )
        evicted = {(e["namespace"], e["app"]) for e in st["recent"]}
        if not evicted:
            return
        rr_keys = {
            (rr.namespace, rr.name)
            for rr in self._server.resource_reservation_cache.list()
        }
        for key in sorted(evicted & rr_keys):
            self._violate(
                f"I-P1[{label}]: evicted app {key} still holds a "
                f"ResourceReservation (partial-gang eviction)"
            )
        evicted_apps = {app for _, app in evicted}
        for pod in self._server.api.list(Pod.KIND):
            app = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
            if app in evicted_apps and pod.node_name:
                self._violate(
                    f"I-P1[{label}]: pod {pod.name} of evicted app {app} is "
                    f"still bound to {pod.node_name} (partial-gang eviction)"
                )

    def _check_ha(self, label: str) -> None:
        """I-H1..I-H3 against the HA fabric (see module docstring)."""
        fabric = getattr(self._server, "ha", None)
        if fabric is None:
            return
        lease = fabric.elector.peek()
        if lease is not None:
            epochs = [h[0] for h in lease.history]
            if any(b <= a for a, b in zip(epochs, epochs[1:])):
                self._violate(
                    f"I-H1[{label}]: lease history epochs not strictly "
                    f"increasing: {epochs}"
                )
            if epochs and lease.epoch != epochs[-1]:
                self._violate(
                    f"I-H1[{label}]: lease epoch {lease.epoch} != last "
                    f"history epoch {epochs[-1]}"
                )
            if fabric.fence.epoch() > lease.epoch:
                self._violate(
                    f"I-H1[{label}]: fence holds epoch {fabric.fence.epoch()} "
                    f"above the lease's {lease.epoch} (self-granted token)"
                )
            if fabric.is_leader() and lease.holder != fabric.elector.identity:
                self._violate(
                    f"I-H1[{label}]: replica {fabric.elector.identity!r} "
                    f"claims leadership but the lease is held by "
                    f"{lease.holder!r}"
                )
        kit = getattr(self._server, "resilience", None)
        if kit is not None:
            highest = fabric.fence.highest_observed()
            for rec in kit.journal.pending():
                epoch = rec.get("epoch")
                if epoch is not None and epoch > highest:
                    self._violate(
                        f"I-H2[{label}]: journaled intent {rec['ns']}/"
                        f"{rec['name']} stamped epoch {epoch} above any "
                        f"observed epoch ({highest})"
                    )
        stale = fabric.fence.stale_commits()
        if stale:
            self._violate(
                f"I-H3[{label}]: {stale} write(s) committed with a stale "
                f"epoch"
            )

    def _violate(self, message: str) -> None:
        self.violations.append(message)
        self._metrics.counter(mnames.SIM_AUDIT_VIOLATIONS)
