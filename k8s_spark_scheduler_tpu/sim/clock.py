"""Virtual clock + event heap for discrete-event simulation.

The clock is a plain float the runner advances to each popped event's
instant; ``now`` is installed as the process-wide
:mod:`..timesource` so every semantic clock read in the control plane
(object creation timestamps, the failover idle trigger, FIFO
enforce-after ages, demand-waste attribution, the unschedulable-pod
timeout) observes simulated time.

Events are ``(time, seq, label, callback)``; ``seq`` is a monotone
tiebreaker so same-instant events fire in scheduling order — a
requirement for byte-identical event-log digests.  The heap is
lock-protected because watch handlers (which may enqueue follow-up
events) run on async write-back threads.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Callable, List, Optional, Tuple

from ..analysis.guarded import guarded_by


@guarded_by("_lock", "_heap", "_now")
class VirtualClock:
    def __init__(self, start: float = 0.0):
        self._now = start
        self._seq = itertools.count()
        self._heap: List[Tuple[float, int, str, Callable[[], None]]] = []
        self._lock = threading.Lock()

    # -- time source ----------------------------------------------------------

    def now(self) -> float:
        return self._now

    # -- event heap -----------------------------------------------------------

    def schedule(self, at: float, label: str, fn: Callable[[], None]) -> None:
        """Enqueue ``fn`` to run at virtual instant ``at``.  Scheduling
        in the past is clamped to now (the event fires next)."""
        with self._lock:
            heapq.heappush(self._heap, (max(at, self._now), next(self._seq), label, fn))

    def schedule_in(self, delay: float, label: str, fn: Callable[[], None]) -> None:
        self.schedule(self._now + delay, label, fn)

    def peek_time(self) -> Optional[float]:
        with self._lock:
            return self._heap[0][0] if self._heap else None

    def pending(self) -> int:
        with self._lock:
            return len(self._heap)

    def run_next(self) -> Optional[Tuple[float, str]]:
        """Pop the earliest event, advance virtual time to it, run its
        callback.  Returns (time, label), or None when the heap is
        empty.  Callbacks may schedule further events."""
        with self._lock:
            if not self._heap:
                return None
            at, _, label, fn = heapq.heappop(self._heap)
            # never move backwards (events scheduled "in the past" were
            # clamped at insert, but be safe against float edge cases)
            self._now = max(self._now, at)
        fn()
        return at, label

    def advance_to(self, t: float) -> None:
        """Advance the clock to ``t`` without running events (the runner
        uses run_next(); this is for tests that only need time to pass,
        e.g. aging a driver past a FIFO enforce-after threshold)."""
        with self._lock:
            self._now = max(self._now, t)
