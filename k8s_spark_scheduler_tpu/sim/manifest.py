"""Self-describing run manifests (shared by sim runs and lab cells).

Every artifact directory gets a ``run_manifest.json`` recording what
produced it (seed, scenario/spec digests) and a sha256-addressed list
of the sibling artifacts — so a directory of simulation output can be
audited, diffed, or re-verified without the command line that made it.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

MANIFEST_SCHEMA = "tpu-gang-scheduler-run-manifest"
MANIFEST_VERSION = 1
MANIFEST_NAME = "run_manifest.json"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def build_run_manifest(
    out_dir: str,
    *,
    kind: str,
    seed: Optional[int] = None,
    digests: Optional[Dict[str, str]] = None,
    extra: Optional[Dict] = None,
) -> Dict:
    """Hash every artifact already present in ``out_dir`` (except the
    manifest itself) and assemble the manifest document."""
    artifacts = []
    for name in sorted(os.listdir(out_dir)):
        path = os.path.join(out_dir, name)
        if name == MANIFEST_NAME or not os.path.isfile(path):
            continue
        artifacts.append(
            {
                "name": name,
                "sha256": _sha256_file(path),
                "bytes": os.path.getsize(path),
            }
        )
    doc: Dict = {
        "schema": MANIFEST_SCHEMA,
        "version": MANIFEST_VERSION,
        "kind": kind,
        "artifacts": artifacts,
    }
    if seed is not None:
        doc["seed"] = seed
    if digests:
        doc["digests"] = dict(sorted(digests.items()))
    if extra:
        doc.update(extra)
    return doc


def write_run_manifest(out_dir: str, **kwargs) -> Dict:
    """Build and write ``run_manifest.json`` into ``out_dir``."""
    doc = build_run_manifest(out_dir, **kwargs)
    path = os.path.join(out_dir, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc
