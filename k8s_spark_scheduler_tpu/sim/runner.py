"""The discrete-event simulation engine.

Builds the REAL wiring (testing/harness.py → server/wiring.py → embedded
API server + full extender stack), installs a :class:`~.clock.VirtualClock`
as the process time source, and replays a :class:`~.scenario.Scenario`:
app arrivals from the workload generator, retry ticks (the
kube-scheduler requeue analog), fault injections, delayed autoscaler
fulfillment, and app completions — auditing invariants after every
event and appending each event to a replayable log whose SHA-256 digest
is byte-identical for identical (scenario, seed).

Determinism contract (what the digest covers and why it is stable):

- virtual times only — wall-clock never enters the log (latencies go to
  the summary, which is NOT digested);
- object names from per-instance counters (harness/autoscaler) and the
  seeded workload;
- every event quiesces the async write-back queues before the state
  fingerprint is taken, so thread interleavings inside an event window
  cannot reorder observable state;
- the fingerprint excludes uids and resourceVersions (assigned in
  write-back-thread arrival order) but covers every scheduling-relevant
  field: bindings, reservations (hard + soft), demands, node state.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import timesource
from ..analysis import racecheck
from ..metrics import names as mnames
from ..scheduler import labels as L
from ..scheduler.failover import sync_resource_reservations_and_demands
from ..testing.fake_autoscaler import FakeAutoscaler
from ..testing.harness import Harness
from ..types.objects import Demand, Node, Pod, ResourceReservation
from ..types.resources import Resources, usage_for_nodes
from .auditor import Auditor, Decision
from .clock import VirtualClock
from .scenario import FaultSpec, Scenario
from .workload import AppSpec, WorkloadGenerator

# virtual epoch: away from 0 so no timestamp is falsy (ensure_identity
# treats 0.0 as unset) and clearly not a real epoch in logs
SIM_EPOCH = 1_000_000.0


@dataclass
class _App:
    spec: AppSpec
    state: str = "pending"  # pending | running | done | dead
    driver_name: str = ""
    executor_template: Optional[Pod] = None
    next_exec_idx: int = 1
    executor_names: List[str] = field(default_factory=list)
    completion_scheduled: bool = False


@dataclass
class SimulationResult:
    digest: str
    summary: Dict
    event_log: List[Dict]
    violations: List[str]
    # capacity-observatory timeline (oldest first, JSON dicts) — the
    # chaos-CI artifact written as capacity.jsonl next to events.jsonl
    capacity_timeline: List[Dict] = field(default_factory=list)


class Simulation:
    def __init__(self, scenario: Scenario, bundle_dir: Optional[str] = None):
        self.scenario = scenario
        # where the extender's flight recorder persists decision bundles
        # when a sim trigger fires (invariant violation); None keeps the
        # bundle ring in memory only
        self.bundle_dir = bundle_dir
        self._violations_seen = 0
        self.clock = VirtualClock(start=SIM_EPOCH)
        self._rng = random.Random(scenario.seed ^ 0xFA17)
        self._apps: Dict[str, _App] = {}
        self._log: List[Dict] = []
        self._latencies: List[float] = []
        self._queue_depths: List[int] = []
        self._efficiencies: List[float] = []
        self._seq = 0
        self._killed_nodes = 0
        self._scaler: Optional[FakeAutoscaler] = None
        self._capacity_samples: List = []
        self._pumps_scheduled: set = set()
        self.harness: Optional[Harness] = None
        self.auditor: Optional[Auditor] = None
        # policy engine bookkeeping (sc.policy non-empty): resolved
        # config, storm-app counter, evictions mirrored into _App
        # state, per-band driver decision counts
        self._policy_cfg = None
        self._storm_idx = 0
        self._evictions_reaped = 0
        self._band_outcomes: Dict[str, Dict[str, int]] = {}
        # SLO scorecard snapshotted at end-of-run while the virtual
        # clock is still installed (None when lifecycle is disabled)
        self._scorecard: Optional[Dict] = None

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> SimulationResult:
        sc = self.scenario
        t_wall0 = time.perf_counter()
        timesource.set_source(self.clock.now)
        # span durations too: a sim trace is virtual end to end (the
        # clock never advances inside a handler, so sim span durations
        # are exactly 0 unless an event fires mid-span)
        timesource.set_perf_source(self.clock.now)
        try:
            self._build()
            self._seed_events()
            horizon = SIM_EPOCH + sc.duration
            while True:
                nxt = self.clock.peek_time()
                if nxt is None or nxt > horizon:
                    break
                self.clock.run_next()
            # drain: one final round + audit so the log always ends on
            # quiesced, audited state
            self._process("end", self._round("end"))
            self._snapshot_scorecard()
        finally:
            try:
                if self.harness is not None:
                    # disarm chaos hooks before teardown: they must never
                    # leak into the next in-process simulation/test
                    from ..ops import registry as ops_registry

                    ops_registry.set_kernel_fault_hook(None)
                    self.harness.api.set_write_fault(None)
                    self.harness.close()
            finally:
                timesource.reset()
        wall_s = time.perf_counter() - t_wall0
        return self._result(wall_s)

    def _build(self) -> None:
        sc = self.scenario
        # under SCHEDLINT_RACECHECK=1 the sim doubles as a race hunt:
        # the harness enables the detector before wiring the server, and
        # chaos tests assert zero reports after the run
        racecheck.enable_if_env()
        extra_install = None
        if sc.policy or sc.ha or sc.concurrent or sc.classes:
            # thread the scenario's policy/ha/concurrent blocks into the
            # REAL wiring: the harness builds the same Install it would
            # by default, plus the policy engine / HA fabric /
            # concurrent admission engine (server/wiring.py)
            from ..config import (
                ClassesConfig,
                ConcurrentConfig,
                FifoConfig,
                HAConfig,
                Install,
                PolicyConfig,
            )

            kwargs = {}
            if sc.policy:
                self._policy_cfg = PolicyConfig.from_dict(sc.policy)
                kwargs["policy"] = self._policy_cfg
            if sc.ha:
                ha_cfg = HAConfig.from_dict(sc.ha)
                # presence of the block is the opt-in, and the sim owns
                # the election cadence: a wall-clock renewal thread
                # would race the virtual event stream
                ha_cfg.enabled = True
                ha_cfg.background = False
                kwargs["ha"] = ha_cfg
            if sc.concurrent:
                conc_cfg = ConcurrentConfig.from_dict(sc.concurrent)
                # presence of the block is the opt-in, mirroring ha
                conc_cfg.enabled = True
                kwargs["concurrent"] = conc_cfg
            if sc.classes:
                kwargs["classes"] = ClassesConfig.from_dict(sc.classes)
            extra_install = Install(
                fifo=sc.fifo,
                fifo_config=FifoConfig(),
                binpack_algo=sc.binpack_algo,
                **kwargs,
            )
        self.harness = Harness(
            binpack_algo=sc.binpack_algo,
            is_fifo=sc.fifo,
            extra_install=extra_install,
            # the marker thread would mutate pod conditions at wall-clock
            # instants (nondeterministic vs the event stream); scans are
            # sim-driven via unschedulable_scan_interval instead
            unschedulable_polling_interval=1e9,
        )
        sampler = getattr(self.harness.server, "capacity", None)
        if sampler is not None:
            # stopped BEFORE the first node event lands: capacity
            # sampling is driven by the event loop (post-quiesce,
            # seq-gated), never by the wall-clock background thread —
            # the summary's capacity columns and the timeline ring must
            # be a pure function of (scenario, seed)
            sampler.stop()
        ledger = getattr(self.harness.server, "lifecycle", None)
        if ledger is not None:
            # same contract as the capacity sampler: the lifecycle
            # ledger drains per sim event (seq-gated), never from its
            # wall-clock background thread
            ledger.stop()
        for i in range(sc.cluster.nodes):
            zone = sc.cluster.zones[i % len(sc.cluster.zones)]
            self.harness.new_node(
                f"node-{i + 1:03d}",
                cpu=sc.cluster.cpu,
                memory=sc.cluster.memory,
                gpu=sc.cluster.gpu,
                zone=zone,
                instance_group=sc.cluster.instance_group,
            )
        if sc.autoscaler.enabled:
            informer = self.harness.server.lazy_demand_informer.informer()
            self._scaler = FakeAutoscaler(
                self.harness.api,
                informer,
                node_cpu=sc.autoscaler.node_cpu,
                node_memory=sc.autoscaler.node_memory,
                node_gpu=sc.autoscaler.node_gpu,
                default_zone=sc.cluster.zones[0],
                fulfillment_delay=sc.autoscaler.delay,
                max_nodes=sc.autoscaler.max_nodes,
                deferred=True,  # determinism: fulfill only at virtual pumps
            )
        self.auditor = Auditor(self.harness.server)
        # first election at t0: prod wiring elects on its renewal thread
        # before traffic arrives; the sim's single replica must likewise
        # hold the lease (epoch 1) before the first write-back, or every
        # fenced write would refuse as never-elected
        self._step_ha()
        tracker = getattr(self.harness.server, "provenance", None)
        if tracker is not None and self.bundle_dir:
            tracker.recorder.out_dir = self.bundle_dir

    def _seed_events(self) -> None:
        sc = self.scenario
        apps = WorkloadGenerator(sc.workload, sc.seed).generate(sc.duration)
        self.workload = apps
        for app in apps:
            self.clock.schedule(
                SIM_EPOCH + app.arrival,
                f"arrival:{app.app_id}",
                lambda a=app: self._on_arrival(a),
            )
        for fault in sc.faults:
            self.clock.schedule(
                SIM_EPOCH + fault.at,
                f"fault:{fault.kind}",
                lambda f=fault: self._on_fault(f),
            )
        interval = max(sc.retry_interval, 0.5)
        t = interval
        while t < sc.duration:
            self.clock.schedule(SIM_EPOCH + t, "tick", self._on_tick)
            t += interval
        if sc.unschedulable_scan_interval > 0:
            t = sc.unschedulable_scan_interval
            while t < sc.duration:
                self.clock.schedule(SIM_EPOCH + t, "unschedulable-scan", self._on_unschedulable_scan)
                t += sc.unschedulable_scan_interval

    # -- event handlers -------------------------------------------------------

    def _on_arrival(self, spec: AppSpec) -> None:
        self._submit_app(spec)
        self._process(f"arrival:{spec.app_id}", self._round(f"arrival:{spec.app_id}"))

    def _submit_app(self, spec: AppSpec) -> None:
        h = self.harness
        if spec.dynamic:
            pods = h.dynamic_allocation_spark_pods(
                spec.app_id,
                spec.min_executor_count,
                spec.executor_count,
                driver_cpu=spec.driver_cpu,
                driver_mem=spec.driver_mem,
                executor_cpu=spec.executor_cpu,
                executor_mem=spec.executor_mem,
                instance_group=spec.instance_group,
                namespace=spec.namespace,
                creation_timestamp=self.clock.now(),
            )
        else:
            pods = h.static_allocation_spark_pods(
                spec.app_id,
                spec.executor_count,
                driver_cpu=spec.driver_cpu,
                driver_mem=spec.driver_mem,
                executor_cpu=spec.executor_cpu,
                executor_mem=spec.executor_mem,
                instance_group=spec.instance_group,
                namespace=spec.namespace,
                creation_timestamp=self.clock.now(),
            )
        driver, executors = pods[0], pods[1:]
        if self._policy_cfg is not None:
            # policy inputs ride on labels, exactly as production pods
            # would carry them (executor template keeps them so
            # replacements stay attributable)
            for pod in pods:
                pod.labels[self._policy_cfg.band_label] = spec.band
                if spec.tenant:
                    pod.labels[self._policy_cfg.tenant_label] = spec.tenant
        app = _App(spec=spec, driver_name=driver.name)
        app.executor_template = executors[0].deepcopy() if executors else None
        self._apps[spec.app_id] = app
        h.create_pod(driver)

    def _on_tick(self) -> None:
        # lease renewal rides the tick cadence (the sim's stand-in for
        # the prod renewal thread, on the virtual clock)
        self._step_ha()
        fulfilled = self._pump_autoscaler()
        decisions = self._round("tick")
        # empty ticks (no decisions, no scale-up) are audited but not
        # logged: the log stays a record of activity, and an idle tail
        # can't pad the digest
        if decisions or fulfilled:
            self._process("tick", decisions)
        else:
            self._audit_only("tick")

    def _on_scaler_pump(self, due: float) -> None:
        # NOTE: due stays in _pumps_scheduled — a capped demand keeps its
        # (now past) due time forever, and re-scheduling it would replay
        # the same instant endlessly (a virtual-time livelock).  Capped
        # demands are retried by the regular tick pump instead.
        fulfilled = self._pump_autoscaler()
        decisions = self._round("scale-up")
        if decisions or fulfilled:
            self._process("scale-up", decisions)

    def _on_unschedulable_scan(self) -> None:
        self.harness.server.unschedulable_marker.scan_for_unschedulable_pods()
        self._process("unschedulable-scan", [])

    def _on_complete(self, app_id: str) -> None:
        app = self._apps.get(app_id)
        if app is None or app.state != "running":
            return
        h = self.harness
        # executors terminate first, driver last (Spark teardown order);
        # deleting the driver cascades the RR + demands via owner GC
        names = [n for n in app.executor_names] + [app.driver_name]
        for name in names:
            pod = h.server.pod_informer.get(app.spec.namespace, name)
            if pod is None:
                continue
            if pod.node_name:
                h.terminate_pod(pod)
            h.api.delete(Pod.KIND, pod.namespace, pod.name)
        app.state = "done"
        self._process(f"complete:{app_id}", self._round(f"complete:{app_id}"))

    # -- faults ---------------------------------------------------------------

    def _on_fault(self, fault: FaultSpec) -> None:
        label = f"fault:{fault.kind}"
        if fault.kind == "node_kill":
            self._fault_node_kill(fault)
        elif fault.kind == "node_cordon":
            self._fault_cordon(fault, cordon=True)
        elif fault.kind == "node_uncordon":
            self._fault_cordon(fault, cordon=False)
        elif fault.kind == "executor_storm":
            self._fault_executor_storm(fault)
        elif fault.kind == "failover":
            self._fault_failover()
        elif fault.kind == "apiserver_outage":
            self._fault_apiserver(fault, mode="outage")
        elif fault.kind == "apiserver_latency":
            self._fault_apiserver(fault, mode="latency")
        elif fault.kind == "kernel_fault":
            self._fault_kernel(fault)
        elif fault.kind == "priority_storm":
            self._fault_priority_storm(fault)
        elif fault.kind == "leader_crash":
            self._fault_leader_crash(fault)
        elif fault.kind == "lease_partition":
            self._fault_lease_partition(fault)
        self._process(label, self._round(label))

    def _fault_node_kill(self, fault: FaultSpec) -> None:
        h = self.harness
        names = sorted(n.name for n in h.api.list(Node.KIND))
        victims = self._rng.sample(names, min(fault.count, len(names)))
        for victim in sorted(victims):
            # driver deaths tear whole apps down first
            for pod in sorted(h.api.list(Pod.KIND), key=lambda p: p.name):
                if pod.node_name != victim:
                    continue
                if pod.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER:
                    self._kill_app(pod.labels.get(L.SPARK_APP_ID_LABEL, ""))
            # surviving pods on the node are executor deaths
            for pod in sorted(h.api.list(Pod.KIND), key=lambda p: p.name):
                if pod.node_name != victim:
                    continue
                if pod.labels.get(L.SPARK_ROLE_LABEL) == L.EXECUTOR:
                    self._kill_executor(pod, replace=True)
            h.api.delete(Node.KIND, "default", victim)
            self._killed_nodes += 1

    def _fault_cordon(self, fault: FaultSpec, cordon: bool) -> None:
        h = self.harness
        candidates = sorted(
            n.name for n in h.api.list(Node.KIND) if n.unschedulable != cordon
        )
        victims = self._rng.sample(candidates, min(fault.count, len(candidates)))
        for name in sorted(victims):
            fresh = h.api.get(Node.KIND, "default", name)
            fresh.unschedulable = cordon
            h.api.update(fresh)

    def _fault_executor_storm(self, fault: FaultSpec) -> None:
        h = self.harness
        running = sorted(
            app_id for app_id, a in self._apps.items() if a.state == "running"
        )
        targets = self._rng.sample(running, min(fault.apps, len(running)))
        for app_id in sorted(targets):
            app = self._apps[app_id]
            bound = [
                p
                for name in sorted(app.executor_names)
                if (p := h.server.pod_informer.get(app.spec.namespace, name)) is not None
                and p.node_name
            ]
            if not bound:
                continue
            k = max(1, int(len(bound) * fault.fraction))
            victims = self._rng.sample([p.name for p in bound], k)
            # simultaneous deaths, then simultaneous replacements — the
            # tombstone race shape in state/softreservations.py
            for name in sorted(victims):
                pod = h.server.pod_informer.get(app.spec.namespace, name)
                if pod is not None:
                    self._kill_executor(pod, replace=False)
            for _ in sorted(victims):
                self._spawn_replacement_executor(app)

    def _fault_failover(self) -> None:
        """A leader change: the in-memory (intentionally unpersisted)
        soft-reservation state is lost; the new leader's first act is
        failover reconciliation rebuilding it from cluster state."""
        server = self.harness.server
        extender = server.extender
        soft = server.soft_reservation_store
        for app_id in sorted(soft.get_all_soft_reservations_copy()):
            soft.remove_driver_reservation(app_id)
        with extender._predicate_lock:
            sync_resource_reservations_and_demands(extender)

    # faulted kinds: the scheduler's OWN write-back traffic (CRDs).  The
    # runner's Node/Pod mutations and server-side owner GC stay up — the
    # fault models the scheduler's client losing the API server, not the
    # cluster's control plane disappearing wholesale
    _FAULTED_KINDS = frozenset({"ResourceReservation", "Demand"})

    def _fault_apiserver(self, fault: FaultSpec, mode: str) -> None:
        """Start an API-server write-fault window; the clearing event is
        a scheduled clock event so recovery is deterministic."""
        from ..kube.errors import APIError

        kinds = self._FAULTED_KINDS
        if mode == "outage":

            def inject(op, kind, ns, name):
                if kind in kinds:
                    return APIError(f"injected apiserver outage ({op} {kind} {ns}/{name})")
                return None

        else:
            # latency spike as the client observes it: every key's FIRST
            # write attempt times out, the retry lands.  Per-key (not a
            # global counter) so the failing set is independent of worker
            # thread interleaving — the digest stays reproducible.
            seen: set = set()

            def inject(op, kind, ns, name):
                if kind in kinds and (op, kind, ns, name) not in seen:
                    seen.add((op, kind, ns, name))
                    return APIError(
                        f"injected apiserver latency: client timeout ({op} {kind})"
                    )
                return None

        self.harness.api.set_write_fault(inject)
        self.clock.schedule(
            self.clock.now() + fault.duration,
            f"fault-clear:apiserver_{mode}",
            lambda m=mode: self._on_apiserver_fault_clear(m),
        )

    def _on_apiserver_fault_clear(self, mode: str) -> None:
        self.harness.api.set_write_fault(None)
        self._recover_writeback()
        label = f"fault-clear:apiserver_{mode}"
        self._process(label, self._round(label))

    def _recover_writeback(self) -> None:
        """Deterministic recovery: force the breaker's probe window open
        and replay the intent journal until it drains (the first probe's
        success closes the breaker, which re-enqueues the rest)."""
        cache = self.harness.server.resource_reservation_cache
        h = self.harness
        for _ in range(6):
            if cache.journal_depth() == 0:
                break
            cache.nudge_recovery(force=True)
            h.wait_for_api(
                lambda: not any(cache.inflight_queue_lengths()), timeout=10.0
            )

    def _fault_kernel(self, fault: FaultSpec) -> None:
        """Arm the kernel chaos hook for the window: every device-lane
        dispatch raises through the extender's real fallback path, so
        lane demotion (and the post-cooloff re-probe) is exercised."""
        from ..ops import registry as ops_registry

        until = self.clock.now() + fault.duration

        def inject(lane):
            if self.clock.now() < until:
                return RuntimeError(f"injected kernel fault ({lane})")
            return None

        ops_registry.set_kernel_fault_hook(inject)
        self.clock.schedule(
            until,
            "fault-clear:kernel_fault",
            lambda: ops_registry.set_kernel_fault_hook(None),
        )

    def _fault_priority_storm(self, fault: FaultSpec) -> None:
        """Burst of ``count`` fresh applications in the fault's band at
        the fault instant: on a saturated cluster, the queue-jump +
        gang-atomic-preemption pressure shape the policy engine exists
        for.  Shapes draw from the scenario's workload ranges off the
        fault rng, so the storm is deterministic under the seed."""
        sc = self.scenario
        wl = sc.workload
        exec_lo = int(wl.get("executors", {}).get("min", 1))
        exec_hi = int(wl.get("executors", {}).get("max", 4))
        life_lo = float(wl.get("lifetime", {}).get("min", 60.0))
        life_hi = float(wl.get("lifetime", {}).get("max", 600.0))
        for _ in range(max(fault.count, 1)):
            self._storm_idx += 1
            count = self._rng.randint(exec_lo, exec_hi)
            spec = AppSpec(
                app_id=f"storm-{self._storm_idx:03d}",
                arrival=self.clock.now() - SIM_EPOCH,
                executor_count=count,
                min_executor_count=count,
                lifetime=round(self._rng.uniform(life_lo, life_hi), 3),
                instance_group=wl.get("instance_group", sc.cluster.instance_group),
                band=fault.band,
            )
            self._submit_app(spec)

    # -- HA faults (ha/) ------------------------------------------------------

    def _step_ha(self) -> None:
        """One election/renewal round on the virtual clock (no-op when
        the scenario carries no ``ha`` block)."""
        fabric = getattr(self.harness.server, "ha", None)
        if fabric is not None:
            fabric.step()

    def _fault_leader_crash(self, fault: FaultSpec) -> None:
        """A rival replica CAS-steals the lease at epoch+1: the resident
        fabric observes its deposition on the next step and every fenced
        write refuses (intents divert to the journal, unacked).  The
        rival's lease runs for ``duration``; at the clearing event it
        has expired, the resident re-acquires at epoch+2 — running full
        takeover reconciliation — and the diverted intents replay."""
        from ..ha.lease import HISTORY_LIMIT

        fabric = getattr(self.harness.server, "ha", None)
        if fabric is None:
            return
        lease = fabric.elector.peek()
        if lease is None:
            return
        now = self.clock.now()
        rival = lease.deepcopy()
        rival.holder = "chaos-rival"
        rival.epoch = lease.epoch + 1
        rival.acquired_at = now
        rival.renewed_at = now
        rival.duration_seconds = fault.duration
        rival.history.append([rival.epoch, rival.holder, now])
        del rival.history[:-HISTORY_LIMIT]
        self.harness.api.update(rival)
        # deposition is observed here, not at the next tick: the crash
        # instant and the refusal window start at the same virtual time
        self._step_ha()
        self.clock.schedule(
            now + fault.duration + 1.0,
            "fault-clear:leader_crash",
            self._on_leader_crash_clear,
        )

    def _on_leader_crash_clear(self) -> None:
        # the rival's lease has expired: this step re-acquires at
        # epoch+2, which runs takeover reconciliation (journal replay +
        # CRD/pod diff) via the fabric's on_elected hook — then the
        # write-back drain replays whatever the fenced window diverted
        self._step_ha()
        self._recover_writeback()
        label = "fault-clear:leader_crash"
        self._process(label, self._round(label))

    def _fault_lease_partition(self, fault: FaultSpec) -> None:
        """The replica loses the coordination API for ``duration``:
        every Lease write fails, so renewals lapse and ``is_leader()``
        self-demotes on TTL (readiness drops) before any rival is even
        observed.  Fenced writes still read-through the (unchanged)
        lease and keep landing at the held epoch — fencing, not the TTL,
        is the split-brain guard.  Heals at the window's end."""
        from ..kube.errors import APIError

        if getattr(self.harness.server, "ha", None) is None:
            return

        def inject(op, kind, ns, name):
            if kind == "Lease":
                return APIError(f"injected lease partition ({op} {ns}/{name})")
            return None

        self.harness.api.set_write_fault(inject)
        self.clock.schedule(
            self.clock.now() + fault.duration,
            "fault-clear:lease_partition",
            self._on_lease_partition_clear,
        )

    def _on_lease_partition_clear(self) -> None:
        self.harness.api.set_write_fault(None)
        # renewal works again: re-assert leadership at the same epoch
        # (no rival ran, so no takeover) and drain any diverted intents
        self._step_ha()
        self._recover_writeback()
        label = "fault-clear:lease_partition"
        self._process(label, self._round(label))

    def _kill_app(self, app_id: str) -> None:
        app = self._apps.get(app_id)
        h = self.harness
        if app is None:
            return
        for name in [app.driver_name] + list(app.executor_names):
            pod = h.server.pod_informer.get(app.spec.namespace, name)
            if pod is not None:
                try:
                    h.api.delete(Pod.KIND, pod.namespace, pod.name)
                except Exception:
                    pass
        app.state = "dead"

    def _kill_executor(self, pod: Pod, replace: bool) -> None:
        h = self.harness
        app = self._apps.get(pod.labels.get(L.SPARK_APP_ID_LABEL, ""))
        try:
            h.api.delete(Pod.KIND, pod.namespace, pod.name)
        except Exception:
            return
        if app is not None:
            if pod.name in app.executor_names:
                app.executor_names.remove(pod.name)
            if replace and app.state == "running":
                self._spawn_replacement_executor(app)

    def _spawn_replacement_executor(self, app: _App) -> None:
        """Spark submits a fresh executor pod (new name) to replace a
        dead one; the extender must re-claim the now-unbound reservation
        (or a soft spot for DA extras)."""
        if app.executor_template is None:
            return
        idx = app.spec.executor_count + app.next_exec_idx
        app.next_exec_idx += 1
        pod = app.executor_template.deepcopy()
        pod.meta.name = f"{app.spec.app_id}-exec-{idx}"
        pod.meta.creation_timestamp = self.clock.now()
        pod.meta.resource_version = 0
        pod.meta.uid = ""
        pod.node_name = ""
        self.harness.create_pod(pod)
        app.executor_names.append(pod.meta.name)

    # -- scheduling rounds ----------------------------------------------------

    def _pump_autoscaler(self) -> int:
        if self._scaler is None:
            return 0
        return self._scaler.process_due(self.clock.now())

    def _round(self, label: str) -> List[Decision]:
        """One kube-scheduler requeue pass: pending drivers oldest-first
        (the queue order FIFO assumes), then pending executors."""
        h = self.harness
        decisions: List[Decision] = []
        node_names = sorted(n.name for n in h.api.list(Node.KIND))
        if not node_names:
            return decisions

        ig_label = h.server.install.instance_group_label

        def attempt(pod: Pod, role: str) -> str:
            t0 = time.perf_counter()
            result = h.schedule(pod, node_names)
            dt = time.perf_counter() - t0
            self._latencies.append(dt)
            h.server.metrics.histogram(mnames.SIM_DECISION_LATENCY, dt)
            outcome = "success" if result.node_names else "failure"
            if not result.node_names and result.failed_nodes:
                # all failed_nodes share one message; surface its outcome class
                msg = next(iter(result.failed_nodes.values()))
                outcome = self._classify_failure(msg)
            group = pod.node_affinity.get(ig_label) or [""]
            band, band_rank = "", 0
            if self._policy_cfg is not None and role == "driver":
                band = pod.labels.get(
                    self._policy_cfg.band_label, self._policy_cfg.default_band
                )
                band_rank = self._policy_cfg.bands.get(band, 0)
                bucket = self._band_outcomes.setdefault(
                    band, {"success": 0, "refused": 0}
                )
                bucket["success" if outcome == "success" else "refused"] += 1
            decisions.append(
                Decision(
                    pod_name=pod.name,
                    role=role,
                    instance_group=group[0],
                    created=pod.creation_timestamp,
                    outcome=outcome,
                    node=result.node_names[0] if result.node_names else "",
                    band=band,
                    band_rank=band_rank,
                )
            )
            return outcome

        pending_drivers = sorted(
            (
                p
                for p in h.api.list(Pod.KIND)
                if p.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER
                and not p.node_name
                and p.meta.deletion_timestamp is None
            ),
            key=lambda p: (p.creation_timestamp, p.name),
        )
        for driver in pending_drivers:
            outcome = attempt(driver, "driver")
            app = self._apps.get(driver.labels.get(L.SPARK_APP_ID_LABEL, ""))
            if outcome == "success" and app is not None and app.state == "pending":
                self._materialize_executors(app)

        pending_executors = sorted(
            (
                p
                for p in h.api.list(Pod.KIND)
                if p.labels.get(L.SPARK_ROLE_LABEL) == L.EXECUTOR
                and not p.node_name
                and p.meta.deletion_timestamp is None
            ),
            key=lambda p: (p.creation_timestamp, p.name),
        )
        for executor in pending_executors:
            attempt(executor, "executor")

        self._check_completions()
        return decisions

    def _materialize_executors(self, app: _App) -> None:
        """Driver bound → Spark starts requesting executors (fresh pods
        stamped at the bind instant, not app arrival)."""
        h = self.harness
        app.state = "running"
        spec = app.spec
        count = spec.executor_count
        if app.executor_template is None:
            return
        for i in range(count):
            pod = app.executor_template.deepcopy()
            pod.meta.name = f"{spec.app_id}-exec-{i + 1}"
            pod.meta.creation_timestamp = self.clock.now()
            pod.meta.resource_version = 0
            pod.meta.uid = ""
            pod.node_name = ""
            h.create_pod(pod)
            app.executor_names.append(pod.meta.name)

    def _check_completions(self) -> None:
        h = self.harness
        for app_id in sorted(self._apps):
            app = self._apps[app_id]
            if app.state != "running" or app.completion_scheduled:
                continue
            driver = h.server.pod_informer.get(app.spec.namespace, app.driver_name)
            if driver is None or not driver.node_name:
                continue
            bound = sum(
                1
                for name in app.executor_names
                if (p := h.server.pod_informer.get(app.spec.namespace, name)) is not None
                and p.node_name
            )
            need = app.spec.min_executor_count if app.spec.dynamic else app.spec.executor_count
            if bound >= need:
                app.completion_scheduled = True
                self.clock.schedule_in(
                    app.spec.lifetime,
                    f"complete:{app_id}",
                    lambda a=app_id: self._on_complete(a),
                )

    @staticmethod
    def _classify_failure(message: str) -> str:
        m = message.lower()
        if "earlier" in m:
            from ..scheduler.extender import FAILURE_EARLIER_DRIVER

            return FAILURE_EARLIER_DRIVER
        if "fit" in m or "capacity" in m or "reserve" in m:
            return "failure-fit"
        return "failure"

    # -- audit + log ----------------------------------------------------------

    def _process(self, label: str, decisions: List[Decision]) -> None:
        """Quiesce → audit → append one event-log entry."""
        self._quiesce(label)
        self.auditor.check_round(decisions, label)
        self.auditor.check_state(label)
        self._reap_evictions()
        self._fire_invariant_trigger(label)
        self._schedule_scaler_pumps()
        self._sample_capacity(label)
        self._drain_ledger(label)
        # one API listing per kind per event, shared by the depth gauge,
        # the log entry, and the fingerprint (APIServer.list deepcopies
        # every object — repeating it per consumer multiplied the sim's
        # dominant per-event cost)
        pods = self.harness.api.list(Pod.KIND)
        nodes = self.harness.api.list(Node.KIND)
        depth = sum(
            1
            for p in pods
            if p.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER and not p.node_name
        )
        self._queue_depths.append(depth)
        self.harness.server.metrics.gauge(mnames.SIM_QUEUE_DEPTH, float(depth))
        eff = self._packing_efficiency()
        if eff is not None:
            self._efficiencies.append(eff)
        entry = {
            "seq": self._seq,
            "t": round(self.clock.now() - SIM_EPOCH, 6),
            "event": label,
            "decisions": [
                {"pod": d.pod_name, "role": d.role, "outcome": d.outcome, "node": d.node}
                for d in decisions
            ],
            "queue_depth": depth,
            "nodes": len(nodes),
            "state": self._state_fingerprint(pods, nodes),
        }
        if eff is not None:
            entry["packing_efficiency"] = round(eff, 6)
        self._seq += 1
        self._log.append(entry)

    def _reap_evictions(self) -> None:
        """Mirror policy evictions into the sim's app bookkeeping: the
        coordinator already deleted the victim's bound pods + RR; clean
        up its still-pending pods and mark the app evicted so
        completions and later rounds track post-eviction truth.  Runs
        AFTER the auditor's policy checks — the reap must never mask a
        partial-gang eviction from I-P1."""
        engine = getattr(self.harness.server, "policy", None)
        if engine is None or engine.coordinator is None:
            return
        st = engine.coordinator.state()
        fresh = st["evictionsTotal"] - self._evictions_reaped
        if fresh <= 0:
            return
        self._evictions_reaped = st["evictionsTotal"]
        for ev in list(st["recent"])[-fresh:]:
            app_id = ev["app"]
            self._kill_app(app_id)
            app = self._apps.get(app_id)
            if app is not None:
                app.state = "evicted"

    def _audit_only(self, label: str) -> None:
        self._quiesce(label)
        self.auditor.check_state(label)
        self._fire_invariant_trigger(label)
        self._schedule_scaler_pumps()
        self._sample_capacity(label)
        self._drain_ledger(label)

    def _drain_ledger(self, label: str) -> None:
        """One lifecycle-ledger drain per state-changing event
        (seq-gated inside the ledger, so idle events are O(1)) —
        always post-quiesce and never under the predicate lock."""
        ledger = getattr(self.harness.server, "lifecycle", None)
        if ledger is None:
            return
        ledger.maybe_drain(trigger=f"sim:{label}")

    def _snapshot_scorecard(self) -> None:
        """Build the SLO scorecard at end-of-run, while the virtual
        clock is still the process time source — ``_result`` runs after
        ``timesource.reset()``, when burn-rate windows would evaluate
        against wall-clock and every virtual sample would look ancient."""
        ledger = getattr(self.harness.server, "lifecycle", None)
        slo = getattr(self.harness.server, "slo", None)
        if ledger is None or slo is None:
            return
        from ..lifecycle import build_scorecard

        ledger.maybe_drain(trigger="sim:scorecard")
        self._scorecard = build_scorecard(
            ledger,
            slo,
            meta={
                "source": "sim",
                "scenario": self.scenario.name,
                "seed": self.scenario.seed,
            },
            now=self.clock.now(),
        )

    def _sample_capacity(self, label: str) -> None:
        """One capacity-observatory sample per state-changing event
        (seq-gated inside the sampler, so idle events are O(1)) —
        always post-quiesce and never under the predicate lock."""
        sampler = getattr(self.harness.server, "capacity", None)
        if sampler is None:
            return
        sample = sampler.maybe_sample(trigger=f"sim:{label}")
        if sample is not None:
            self._capacity_samples.append(sample)

    def _fire_invariant_trigger(self, label: str) -> None:
        """An invariant violation is a flight-recorder trigger: persist
        the recent decision bundles so the violating decision replays
        outside the sim (provenance/recorder.py)."""
        n = len(self.auditor.violations)
        if n <= self._violations_seen:
            return
        fresh = self.auditor.violations[self._violations_seen:n]
        self._violations_seen = n
        tracker = getattr(self.harness.server, "provenance", None)
        if tracker is not None:
            tracker.on_trigger("sim-invariant", f"{label}: {fresh[0]}")

    def _quiesce(self, label: str) -> None:
        h = self.harness
        ok = h.wait_quiesced(timeout=30.0)
        demand_cache = h.server.demand_cache
        ok2 = h.wait_for_api(
            lambda: not any(demand_cache.inflight_queue_lengths()), timeout=30.0
        )
        if not (ok and ok2):
            self.auditor.violations.append(
                f"Q0[{label}]: async write-back failed to quiesce"
            )

    def _schedule_scaler_pumps(self) -> None:
        """Turn pending delayed demands into clock events at their due
        instants (checked post-quiesce so the pending set is stable)."""
        if self._scaler is None:
            return
        for due in self._scaler.due_times():
            # each due instant gets exactly ONE pump event, ever (the set
            # is never drained): zero-delay demands fire as the very next
            # event (clock.schedule clamps past instants to now), and a
            # capped demand whose due has passed waits for the next tick
            # pump rather than respinning the same virtual instant
            if due not in self._pumps_scheduled:
                self._pumps_scheduled.add(due)
                self.clock.schedule(due, "scale-up", lambda d=due: self._on_scaler_pump(d))

    def _packing_efficiency(self) -> Optional[float]:
        """Mean over occupied nodes of the max-dimension
        reserved/allocatable ratio (hard + soft reservations) — the
        sim-level packing signal the summary reports."""
        h = self.harness
        usage = usage_for_nodes(h.server.resource_reservation_cache.list())
        for node, res in h.server.soft_reservation_store.used_soft_reservation_resources().items():
            usage[node] = usage.get(node, Resources.zero()).add(res)
        nodes = {n.name: n for n in h.server.node_informer.list()}
        ratios = []
        for name, used in sorted(usage.items()):
            node = nodes.get(name)
            if node is None:
                continue
            dims = []
            for dim in ("cpu", "memory", "nvidia_gpu"):
                alloc = getattr(node.allocatable, dim).exact
                if alloc > 0:
                    dims.append(float(getattr(used, dim).exact / alloc))
            if dims:
                ratios.append(max(dims))
        if not ratios:
            return None
        return sum(ratios) / len(ratios)

    def _state_fingerprint(self, pods: List[Pod], nodes: List[Node]) -> str:
        """SHA-256 over the canonical serialization of every
        scheduling-relevant field of quiesced cluster state."""
        api = self.harness.api
        soft = self.harness.server.soft_reservation_store.get_all_soft_reservations_copy()
        state = {
            "nodes": sorted(
                [
                    n.name,
                    sorted(n.labels.items()),
                    [str(n.allocatable.cpu.exact), str(n.allocatable.memory.exact), str(n.allocatable.nvidia_gpu.exact)],
                    bool(n.unschedulable),
                    bool(n.ready),
                ]
                for n in nodes
            ),
            "pods": sorted(
                [p.namespace, p.name, p.labels.get(L.SPARK_ROLE_LABEL, ""), p.node_name, p.phase]
                for p in pods
            ),
            "reservations": sorted(
                [
                    rr.namespace,
                    rr.name,
                    sorted((k, v.node) for k, v in rr.spec.reservations.items()),
                    sorted(rr.status.pods.items()),
                ]
                for rr in api.list(ResourceReservation.KIND)
            ),
            "soft": sorted(
                [app_id, sorted((name, r.node) for name, r in sr.reservations.items()),
                 sorted(sr.status.items())]
                for app_id, sr in soft.items()
            ),
            "demands": sorted(
                [
                    d.namespace,
                    d.name,
                    d.status.phase,
                    [[str(u.resources.cpu.exact), str(u.resources.memory.exact), u.count] for u in d.spec.units],
                ]
                for d in api.list(Demand.KIND)
            ),
        }
        blob = json.dumps(state, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # -- results --------------------------------------------------------------

    def _result(self, wall_s: float) -> SimulationResult:
        blob = "\n".join(
            json.dumps(e, sort_keys=True, separators=(",", ":")) for e in self._log
        )
        digest = hashlib.sha256(blob.encode()).hexdigest()
        lat = sorted(self._latencies)

        def pct(q: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(q * len(lat)))] * 1000.0

        states = [a.state for a in self._apps.values()]
        summary = {
            "scenario": self.scenario.name,
            "seed": self.scenario.seed,
            "sim_duration_s": self.scenario.duration,
            "wall_duration_s": round(wall_s, 3),
            "sim_speedup": round(self.scenario.duration / wall_s, 1) if wall_s > 0 else None,
            "events_logged": len(self._log),
            "events_audited": self.auditor.events_audited if self.auditor else 0,
            "decisions": len(self._latencies),
            "decisions_per_sec_wall": round(len(self._latencies) / wall_s, 1) if wall_s > 0 else None,
            "decision_latency_ms": {
                "p50": round(pct(0.50), 3),
                "p95": round(pct(0.95), 3),
                "p99": round(pct(0.99), 3),
                "max": round(lat[-1] * 1000.0, 3) if lat else 0.0,
            },
            "apps": {
                "arrived": len(self._apps),
                "completed": states.count("done"),
                "running_at_end": states.count("running"),
                "pending_at_end": states.count("pending"),
                "killed": states.count("dead"),
                "evicted": states.count("evicted"),
            },
            "queue_depth": {
                "max": max(self._queue_depths, default=0),
                "mean": round(sum(self._queue_depths) / len(self._queue_depths), 2)
                if self._queue_depths
                else 0.0,
                "final": self._queue_depths[-1] if self._queue_depths else 0,
            },
            "packing_efficiency": {
                "mean": round(sum(self._efficiencies) / len(self._efficiencies), 4)
                if self._efficiencies
                else None,
                "final": round(self._efficiencies[-1], 4) if self._efficiencies else None,
            },
            "nodes": {
                "initial": self.scenario.cluster.nodes,
                "scaled_up": self._scaler.created_nodes if self._scaler else 0,
                "killed": self._killed_nodes,
                "capped_demands": len(self._scaler.capped) if self._scaler else 0,
            },
            "invariant_violations": len(self.auditor.violations) if self.auditor else -1,
            "digest": digest,
        }
        summary["capacity"] = self._capacity_summary()
        summary["waste_phases"] = self._waste_summary()
        summary["contention"] = self._contention_summary()
        policy = self._policy_summary()
        if policy is not None:
            summary["policy"] = policy
        ha = self._ha_summary()
        if ha is not None:
            summary["ha"] = ha
        if self._scorecard is not None:
            summary["slo"] = self._scorecard
        sampler = getattr(self.harness.server, "capacity", None) if self.harness else None
        timeline = (
            [s.to_dict() for s in sampler.timeline()] if sampler is not None else []
        )
        return SimulationResult(
            digest=digest,
            summary=summary,
            event_log=self._log,
            violations=list(self.auditor.violations) if self.auditor else [],
            capacity_timeline=timeline,
        )

    def _ha_summary(self) -> Optional[Dict]:
        """Failover scorecard: the ``/status/ha`` payload at quiesce
        (terminal epoch, fence refusal/stale-commit counters, full lease
        succession history).  Summary-only, like the policy scorecard."""
        fabric = (
            getattr(self.harness.server, "ha", None)
            if self.harness is not None
            else None
        )
        if fabric is None:
            return None
        return fabric.status()

    def _policy_summary(self) -> Optional[Dict]:
        """Eviction scorecard: who got evicted and why, per-band driver
        decision counts, DRF tenant shares — the policy/ columns of the
        sim summary.  Summary-only; the digest never sees it (whatif
        timings are wall-clock in production runs)."""
        engine = (
            getattr(self.harness.server, "policy", None)
            if self.harness is not None
            else None
        )
        if engine is None:
            return None
        st = engine.state()
        out: Dict = {
            "ordering": st["ordering"],
            "backfill": st["backfill"],
            "preemption_enabled": st["preemptionEnabled"],
            "bands": st["bands"],
            "band_outcomes": {
                b: dict(c) for b, c in sorted(self._band_outcomes.items())
            },
            "tenants": st["tenants"],
        }
        pre = st.get("preemption")
        if pre is not None:
            out["evictions"] = {
                "total": pre["evictionsTotal"],
                "victims": pre["victimsTotal"],
                "journal_depth": pre["journalDepth"],
                "whatif": pre.get("whatif", {}),
                "scorecard": [
                    {
                        "app": ev["app"],
                        "band": ev["band"],
                        "tenant": ev["tenant"],
                        "pods": ev["pods"],
                        "reason": ev["reason"],
                        "replayed": ev["replayed"],
                        "at": round(ev["at"] - SIM_EPOCH, 3),
                    }
                    for ev in pre["recent"]
                ],
            }
        return out

    def _contention_summary(self) -> Optional[Dict]:
        """Contention scorecard columns: the extender predicate lock's
        wait/hold distributions plus the per-request critical-path ring.
        Read straight off the harness server's own instances (never the
        process-global lock registry — parallel tests would cross-bleed).
        Wait/hold numbers are real wall-clock, so they live in the
        summary only — the digest never sees them."""
        if self.harness is None:
            return None
        lock = getattr(self.harness.server.extender, "_predicate_lock", None)
        analyzer = getattr(self.harness.server, "criticalpath", None)
        if lock is None and analyzer is None:
            return None
        out: Dict = {}
        if lock is not None and hasattr(lock, "snapshot"):
            snap = lock.snapshot()
            out["predicate_lock"] = {
                "acquisitions": snap["acquisitions"],
                "contended": snap["contended"],
                "wait_ms_p95": snap["waitMs"]["p95"],
                "wait_ms_max": snap["waitMs"]["max"],
                "hold_ms_p95": snap["holdMs"]["p95"],
                "top_blockers": snap["topBlockers"][:3],
            }
        if analyzer is not None:
            out["criticalpath"] = analyzer.summary()
        return out or None

    def _capacity_summary(self) -> Optional[Dict]:
        """Fragmentation / headroom / queue-pressure percentiles over the
        event-driven capacity samples — the first ROADMAP-5 scorecard
        columns.  Virtual-time-deterministic: every input is integer
        state math on post-quiesce snapshots."""
        samples = self._capacity_samples
        if not samples:
            return None

        def pct(values, q):
            if not values:
                return 0.0
            ordered = sorted(values)
            return ordered[min(len(ordered) - 1, int(q * len(ordered)))]

        frag = [max(s.frag_index) for s in samples]
        headroom = [
            max((i["headroom"] for i in s.headroom.values()), default=0)
            for s in samples
        ]
        pressure = [s.pressure for s in samples]
        sampler = getattr(self.harness.server, "capacity", None)
        stats = sampler.stats() if sampler is not None else {}
        return {
            "samples": len(samples),
            "probe_lane": samples[-1].probe_lane,
            "probe_solves": sum(s.probe_solves for s in samples),
            "lock_violations": stats.get("lock_violations", 0),
            "timeline_ring": stats.get("ring", len(samples)),
            "fragmentation_max_dim": {
                "p50": round(pct(frag, 0.50), 6),
                "p95": round(pct(frag, 0.95), 6),
                "max": round(max(frag), 6),
                "final": round(frag[-1], 6),
            },
            "headroom_executors": {
                "p50": pct(headroom, 0.50),
                "p95": pct(headroom, 0.95),
                "min": min(headroom),
                "final": headroom[-1],
            },
            "queue_pressure": {
                "p50": pct(pressure, 0.50),
                "max": max(pressure),
                "final": pressure[-1],
            },
        }

    def _waste_summary(self) -> Dict:
        """WasteMetricsReporter phase durations (virtual-time seconds)
        folded in next to the capacity columns."""
        from ..metrics import names as mnames

        registry = self.harness.server.metrics
        out = {}
        for waste_type in (
            "before-demand-creation",
            "after-demand-fulfilled",
            "total-time-no-demand",
        ):
            snap = registry.get_histogram(
                mnames.SCHEDULING_WASTE, {mnames.TAG_WASTE_TYPE: waste_type}
            )
            if snap["count"]:
                out[waste_type] = {
                    "count": snap["count"],
                    "mean_s": round(snap["mean"], 6),
                    "p50_s": round(snap["p50"], 6),
                    "max_s": round(snap["max"], 6),
                }
        return out
