"""Declarative scenario spec: cluster shape + workload + faults.

A scenario is a plain dict (usually a JSON file under ``examples/sim/``)
so runs are reviewable, diffable artifacts:

.. code-block:: json

    {
      "name": "chaos",
      "seed": 42,
      "duration": 1800,
      "retry_interval": 15,
      "cluster": {"nodes": 8, "cpu": "16", "memory": "32Gi",
                  "zones": ["zone1", "zone2"]},
      "binpack_algo": "tightly-pack",
      "fifo": true,
      "workload": {"process": "poisson", "rate_per_min": 2,
                   "executors": {"min": 1, "max": 6},
                   "dynamic_fraction": 0.3,
                   "lifetime": {"min": 120, "max": 600}},
      "autoscaler": {"enabled": true, "delay": 45, "max_nodes": 24},
      "faults": [
        {"at": 600, "kind": "node_kill", "count": 2},
        {"at": 800, "kind": "node_cordon", "count": 1},
        {"at": 1000, "kind": "executor_storm", "apps": 2},
        {"at": 1200, "kind": "failover"}
      ]
    }

Fault catalog (all deterministic under the scenario seed):

- ``node_kill``: delete ``count`` nodes (oldest scaled-up last); pods
  bound there die — the driver's death tears the whole app down via
  owner GC, executor deaths leave unbound reservations that replacement
  executors must re-claim;
- ``node_cordon`` / ``node_uncordon``: flip ``unschedulable`` on
  ``count`` nodes;
- ``executor_storm``: kill ``fraction`` of bound executors across up to
  ``apps`` applications simultaneously and submit replacements — the
  soft-reservation tombstone race;
- ``failover``: wipe the (intentionally unpersisted) soft-reservation
  store and run ``scheduler/failover.py`` reconciliation, as a fresh
  leader would;
- ``apiserver_outage``: for ``duration`` virtual seconds every CRD
  write from the scheduler's async client fails — the write-back
  breaker opens and reservation intents divert to the journal; at the
  window's end the runner injects the recovery signal and the journal
  replays (resilience/);
- ``apiserver_latency``: for ``duration`` virtual seconds every CRD
  write's FIRST attempt per key fails with a retriable timeout (the
  client-observed shape of a latency spike); retries land, so the
  breaker sees interleaved failures without a hard outage;
- ``kernel_fault``: for ``duration`` virtual seconds every device
  kernel lane dispatch raises, driving lane demotion to the host path
  and, after the window + cooloff, re-probe and promotion
  (resilience/lanehealth.py);
- ``priority_storm``: submit ``count`` fresh applications in the
  fault's ``band`` (default ``high``) at the fault instant — on a
  saturated cluster this exercises the policy engine's queue-jumping
  and gang-atomic preemption path (policy/);
- ``leader_crash``: a rival replica steals the leadership lease at
  epoch+1 — the resident fabric observes its deposition, every fenced
  write path starts refusing (diverting intents to the journal), and
  when the rival's lease expires at the window's end the resident
  re-acquires at epoch+2 and runs full takeover reconciliation (ha/);
- ``lease_partition``: for ``duration`` virtual seconds every Lease
  write fails (the leader is partitioned from the coordination API) —
  renewals lapse, ``is_leader()`` self-demotes on TTL, and the fabric
  re-elects once the partition heals.

A scenario may also carry a ``policy`` dict (the ``Install.policy``
kebab-case keys from ``config.PolicyConfig.from_dict``); when present
the simulator wires the full policy engine into the harness and the
auditor arms the I-P1..I-P4 policy invariants.  An ``ha`` dict (the
``Install.ha`` kebab-case keys from ``config.HAConfig.from_dict``)
wires the HA fabric — lease election + fencing + takeover
reconciliation — stepped deterministically on the virtual clock
(``background`` is forced off), and arms the I-H1..I-H3 audits.
A ``classes`` dict (the ``Install.classes`` kebab-case keys from
``config.ClassesConfig.from_dict``) overrides the equivalence-class
aggregation config — the class-churn scenarios force ``min-nodes: 0``
so cordon/uncordon faults exercise live class-membership flips at any
fleet size.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_KINDS = {
    "node_kill",
    "node_cordon",
    "node_uncordon",
    "executor_storm",
    "failover",
    "apiserver_outage",
    "apiserver_latency",
    "kernel_fault",
    "priority_storm",
    "leader_crash",
    "lease_partition",
}


class ScenarioError(ValueError):
    """Actionable scenario validation failure (raised up front by
    ``Scenario.from_dict`` instead of a deep runner traceback)."""


_SCENARIO_KEYS = {
    "name", "seed", "duration", "retry_interval", "binpack_algo",
    "fifo", "cluster", "workload", "autoscaler", "faults",
    "unschedulable_scan_interval", "policy", "ha", "concurrent",
    "classes",
}
_CLUSTER_KEYS = {"nodes", "cpu", "memory", "gpu", "zones", "instance_group"}
_AUTOSCALER_KEYS = {
    "enabled", "delay", "max_nodes", "node_cpu", "node_memory", "node_gpu",
}
_FAULT_KEYS = {"at", "kind", "count", "apps", "fraction", "duration", "band"}
_WORKLOAD_KEYS = {
    "trace", "process", "rate_per_min", "executors", "dynamic_fraction",
    "lifetime", "instance_group", "band_weights", "tenants", "band",
    "tenant", "burst_interval", "burst_size", "burst_offset",
    "peak_rate_per_min", "period",
}
_WORKLOAD_PROCESSES = {"poisson", "burst", "diurnal"}


def _check_block(path: str, block, known: set) -> Dict:
    if not isinstance(block, dict):
        raise ScenarioError(
            f"{path}: expected an object, got {type(block).__name__}"
        )
    unknown = set(block) - known
    if unknown:
        raise ScenarioError(
            f"{path}: unknown keys {sorted(unknown)} (known: {sorted(known)})"
        )
    return block


def _check_number(path: str, value, lo=None) -> None:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ScenarioError(f"{path}: expected a number, got {value!r}")
    if lo is not None and value < lo:
        raise ScenarioError(f"{path}: must be >= {lo}, got {value!r}")


def _validate_workload(block: Dict) -> None:
    _check_block("scenario.workload", block, _WORKLOAD_KEYS)
    if block.get("trace") is not None and not isinstance(block["trace"], str):
        raise ScenarioError(
            f"scenario.workload.trace: expected a path string, got {block['trace']!r}"
        )
    process = block.get("process", "poisson")
    if process not in _WORKLOAD_PROCESSES:
        raise ScenarioError(
            f"scenario.workload.process: unknown process {process!r} "
            f"(known: {sorted(_WORKLOAD_PROCESSES)})"
        )
    for key, bounds in (("executors", (1, None)), ("lifetime", (0, None))):
        sub = block.get(key)
        if sub is None:
            continue
        sub = _check_block(f"scenario.workload.{key}", sub, {"min", "max"})
        for edge in ("min", "max"):
            if edge in sub:
                _check_number(f"scenario.workload.{key}.{edge}", sub[edge], lo=bounds[0] if edge == "min" else None)
        if "min" in sub and "max" in sub and sub["max"] < sub["min"]:
            raise ScenarioError(
                f"scenario.workload.{key}: max {sub['max']} < min {sub['min']}"
            )
    if "dynamic_fraction" in block:
        _check_number("scenario.workload.dynamic_fraction", block["dynamic_fraction"], lo=0.0)
        if block["dynamic_fraction"] > 1.0:
            raise ScenarioError(
                f"scenario.workload.dynamic_fraction: must be <= 1.0, "
                f"got {block['dynamic_fraction']!r}"
            )


def _validate_faults(faults) -> None:
    if not isinstance(faults, list):
        raise ScenarioError(
            f"scenario.faults: expected a list, got {type(faults).__name__}"
        )
    for i, f in enumerate(faults):
        if not isinstance(f, dict):
            raise ScenarioError(
                f"scenario.faults[{i}]: expected an object, got {type(f).__name__}"
            )
        unknown = set(f) - _FAULT_KEYS
        if unknown:
            raise ScenarioError(
                f"scenario.faults[{i}]: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_FAULT_KEYS)})"
            )
        if "kind" not in f:
            raise ScenarioError(f"scenario.faults[{i}]: missing required key 'kind'")
        if f["kind"] not in FAULT_KINDS:
            raise ScenarioError(
                f"scenario.faults[{i}].kind: unknown fault kind {f['kind']!r} "
                f"(known: {sorted(FAULT_KINDS)})"
            )
        if "at" not in f:
            raise ScenarioError(f"scenario.faults[{i}]: missing required key 'at'")
        _check_number(f"scenario.faults[{i}].at", f["at"], lo=0)


@dataclass
class ClusterSpec:
    nodes: int = 4
    cpu: str = "16"
    memory: str = "32Gi"
    gpu: str = "0"
    zones: List[str] = field(default_factory=lambda: ["zone1"])
    instance_group: str = "batch-medium-priority"


@dataclass
class AutoscalerSpec:
    enabled: bool = False
    delay: float = 0.0
    max_nodes: Optional[int] = None
    node_cpu: str = "16"
    node_memory: str = "32Gi"
    node_gpu: str = "0"


@dataclass
class FaultSpec:
    at: float
    kind: str
    count: int = 1
    apps: int = 1
    fraction: float = 0.5
    # window length (virtual seconds) for the windowed faults:
    # apiserver_outage / apiserver_latency / kernel_fault
    duration: float = 60.0
    # priority band stamped onto priority_storm submissions
    band: str = "high"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; known: {sorted(FAULT_KINDS)}")


@dataclass
class Scenario:
    name: str = "scenario"
    seed: int = 0
    duration: float = 600.0
    # how often pending pods are retried (kube-scheduler's backoff
    # analog) and the autoscaler pump granularity, virtual seconds
    retry_interval: float = 15.0
    binpack_algo: str = "tightly-pack"
    fifo: bool = True
    cluster: ClusterSpec = field(default_factory=ClusterSpec)
    workload: Dict = field(default_factory=dict)
    autoscaler: AutoscalerSpec = field(default_factory=AutoscalerSpec)
    faults: List[FaultSpec] = field(default_factory=list)
    # deterministic unschedulable-marker sweeps (0 disables)
    unschedulable_scan_interval: float = 0.0
    # Install.policy overrides (kebab-case, PolicyConfig.from_dict);
    # empty = policy engine disabled, byte-identical FIFO
    policy: Dict = field(default_factory=dict)
    # Install.ha overrides (kebab-case, HAConfig.from_dict); empty =
    # no fabric.  background is forced off — the sim steps elections
    # on the virtual clock
    ha: Dict = field(default_factory=dict)
    # Install.concurrent overrides (kebab-case,
    # ConcurrentConfig.from_dict); empty = serial admission.  When
    # enabled, every sim Filter routes through the concurrent engine's
    # speculate→FIFO-commit path — decisions must stay byte-identical
    # to the serial run of the same scenario
    concurrent: Dict = field(default_factory=dict)
    # Install.classes overrides (kebab-case, ClassesConfig.from_dict);
    # empty = the Install defaults (enabled, min-nodes 20000).  Set
    # {"enabled": true, "min-nodes": 0} to force class-compressed
    # solves regardless of fleet size — the class-churn scenarios do,
    # so cordon/uncordon faults flip live class memberships
    classes: Dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict) -> "Scenario":
        if not isinstance(d, dict):
            raise ScenarioError(
                f"scenario: expected an object, got {type(d).__name__}"
            )
        d = dict(d)
        unknown = set(d) - _SCENARIO_KEYS
        if unknown:
            raise ScenarioError(
                f"scenario: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_SCENARIO_KEYS)})"
            )
        for key in ("duration", "retry_interval", "seed"):
            if key in d:
                _check_number(f"scenario.{key}", d[key], lo=0)
        cluster_d = _check_block("scenario.cluster", d.pop("cluster", {}), _CLUSTER_KEYS)
        if "nodes" in cluster_d:
            _check_number("scenario.cluster.nodes", cluster_d["nodes"], lo=0)
        autoscaler_d = _check_block(
            "scenario.autoscaler", d.pop("autoscaler", {}), _AUTOSCALER_KEYS
        )
        faults_d = d.pop("faults", [])
        _validate_faults(faults_d)
        _validate_workload(d.get("workload", {}))
        for key in ("policy", "ha", "concurrent", "classes"):
            if key in d and not isinstance(d[key], dict):
                raise ScenarioError(
                    f"scenario.{key}: expected an object, got {type(d[key]).__name__}"
                )
        cluster = ClusterSpec(**cluster_d)
        autoscaler = AutoscalerSpec(**autoscaler_d)
        faults = [FaultSpec(**f) for f in faults_d]
        faults.sort(key=lambda f: (f.at, f.kind))
        return Scenario(cluster=cluster, autoscaler=autoscaler, faults=faults, **d)

    @staticmethod
    def from_file(path: str) -> "Scenario":
        with open(path) as f:
            return Scenario.from_dict(json.load(f))

    def to_dict(self) -> Dict:
        from dataclasses import asdict

        return asdict(self)
