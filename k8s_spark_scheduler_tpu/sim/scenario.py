"""Declarative scenario spec: cluster shape + workload + faults.

A scenario is a plain dict (usually a JSON file under ``examples/sim/``)
so runs are reviewable, diffable artifacts:

.. code-block:: json

    {
      "name": "chaos",
      "seed": 42,
      "duration": 1800,
      "retry_interval": 15,
      "cluster": {"nodes": 8, "cpu": "16", "memory": "32Gi",
                  "zones": ["zone1", "zone2"]},
      "binpack_algo": "tightly-pack",
      "fifo": true,
      "workload": {"process": "poisson", "rate_per_min": 2,
                   "executors": {"min": 1, "max": 6},
                   "dynamic_fraction": 0.3,
                   "lifetime": {"min": 120, "max": 600}},
      "autoscaler": {"enabled": true, "delay": 45, "max_nodes": 24},
      "faults": [
        {"at": 600, "kind": "node_kill", "count": 2},
        {"at": 800, "kind": "node_cordon", "count": 1},
        {"at": 1000, "kind": "executor_storm", "apps": 2},
        {"at": 1200, "kind": "failover"}
      ]
    }

Fault catalog (all deterministic under the scenario seed):

- ``node_kill``: delete ``count`` nodes (oldest scaled-up last); pods
  bound there die — the driver's death tears the whole app down via
  owner GC, executor deaths leave unbound reservations that replacement
  executors must re-claim;
- ``node_cordon`` / ``node_uncordon``: flip ``unschedulable`` on
  ``count`` nodes;
- ``executor_storm``: kill ``fraction`` of bound executors across up to
  ``apps`` applications simultaneously and submit replacements — the
  soft-reservation tombstone race;
- ``failover``: wipe the (intentionally unpersisted) soft-reservation
  store and run ``scheduler/failover.py`` reconciliation, as a fresh
  leader would;
- ``apiserver_outage``: for ``duration`` virtual seconds every CRD
  write from the scheduler's async client fails — the write-back
  breaker opens and reservation intents divert to the journal; at the
  window's end the runner injects the recovery signal and the journal
  replays (resilience/);
- ``apiserver_latency``: for ``duration`` virtual seconds every CRD
  write's FIRST attempt per key fails with a retriable timeout (the
  client-observed shape of a latency spike); retries land, so the
  breaker sees interleaved failures without a hard outage;
- ``kernel_fault``: for ``duration`` virtual seconds every device
  kernel lane dispatch raises, driving lane demotion to the host path
  and, after the window + cooloff, re-probe and promotion
  (resilience/lanehealth.py);
- ``priority_storm``: submit ``count`` fresh applications in the
  fault's ``band`` (default ``high``) at the fault instant — on a
  saturated cluster this exercises the policy engine's queue-jumping
  and gang-atomic preemption path (policy/);
- ``leader_crash``: a rival replica steals the leadership lease at
  epoch+1 — the resident fabric observes its deposition, every fenced
  write path starts refusing (diverting intents to the journal), and
  when the rival's lease expires at the window's end the resident
  re-acquires at epoch+2 and runs full takeover reconciliation (ha/);
- ``lease_partition``: for ``duration`` virtual seconds every Lease
  write fails (the leader is partitioned from the coordination API) —
  renewals lapse, ``is_leader()`` self-demotes on TTL, and the fabric
  re-elects once the partition heals.

A scenario may also carry a ``policy`` dict (the ``Install.policy``
kebab-case keys from ``config.PolicyConfig.from_dict``); when present
the simulator wires the full policy engine into the harness and the
auditor arms the I-P1..I-P4 policy invariants.  An ``ha`` dict (the
``Install.ha`` kebab-case keys from ``config.HAConfig.from_dict``)
wires the HA fabric — lease election + fencing + takeover
reconciliation — stepped deterministically on the virtual clock
(``background`` is forced off), and arms the I-H1..I-H3 audits.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_KINDS = {
    "node_kill",
    "node_cordon",
    "node_uncordon",
    "executor_storm",
    "failover",
    "apiserver_outage",
    "apiserver_latency",
    "kernel_fault",
    "priority_storm",
    "leader_crash",
    "lease_partition",
}


@dataclass
class ClusterSpec:
    nodes: int = 4
    cpu: str = "16"
    memory: str = "32Gi"
    gpu: str = "0"
    zones: List[str] = field(default_factory=lambda: ["zone1"])
    instance_group: str = "batch-medium-priority"


@dataclass
class AutoscalerSpec:
    enabled: bool = False
    delay: float = 0.0
    max_nodes: Optional[int] = None
    node_cpu: str = "16"
    node_memory: str = "32Gi"
    node_gpu: str = "0"


@dataclass
class FaultSpec:
    at: float
    kind: str
    count: int = 1
    apps: int = 1
    fraction: float = 0.5
    # window length (virtual seconds) for the windowed faults:
    # apiserver_outage / apiserver_latency / kernel_fault
    duration: float = 60.0
    # priority band stamped onto priority_storm submissions
    band: str = "high"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; known: {sorted(FAULT_KINDS)}")


@dataclass
class Scenario:
    name: str = "scenario"
    seed: int = 0
    duration: float = 600.0
    # how often pending pods are retried (kube-scheduler's backoff
    # analog) and the autoscaler pump granularity, virtual seconds
    retry_interval: float = 15.0
    binpack_algo: str = "tightly-pack"
    fifo: bool = True
    cluster: ClusterSpec = field(default_factory=ClusterSpec)
    workload: Dict = field(default_factory=dict)
    autoscaler: AutoscalerSpec = field(default_factory=AutoscalerSpec)
    faults: List[FaultSpec] = field(default_factory=list)
    # deterministic unschedulable-marker sweeps (0 disables)
    unschedulable_scan_interval: float = 0.0
    # Install.policy overrides (kebab-case, PolicyConfig.from_dict);
    # empty = policy engine disabled, byte-identical FIFO
    policy: Dict = field(default_factory=dict)
    # Install.ha overrides (kebab-case, HAConfig.from_dict); empty =
    # no fabric.  background is forced off — the sim steps elections
    # on the virtual clock
    ha: Dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict) -> "Scenario":
        d = dict(d)
        unknown = set(d) - {
            "name", "seed", "duration", "retry_interval", "binpack_algo",
            "fifo", "cluster", "workload", "autoscaler", "faults",
            "unschedulable_scan_interval", "policy", "ha",
        }
        if unknown:
            raise ValueError(f"unknown scenario keys: {sorted(unknown)}")
        cluster = ClusterSpec(**d.pop("cluster", {}))
        autoscaler = AutoscalerSpec(**d.pop("autoscaler", {}))
        faults = [FaultSpec(**f) for f in d.pop("faults", [])]
        faults.sort(key=lambda f: (f.at, f.kind))
        return Scenario(cluster=cluster, autoscaler=autoscaler, faults=faults, **d)

    @staticmethod
    def from_file(path: str) -> "Scenario":
        with open(path) as f:
            return Scenario.from_dict(json.load(f))

    def to_dict(self) -> Dict:
        from dataclasses import asdict

        return asdict(self)
