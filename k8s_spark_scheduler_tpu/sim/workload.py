"""Seeded workload generation + JSONL trace replay.

An app spec is everything the simulator needs to play one Spark
application against the extender: arrival instant, gang shape (executor
count, static vs dynamic allocation), per-pod resources, and lifetime
(virtual seconds between the gang becoming fully bound and the app
terminating).

Arrival processes (all driven by one ``random.Random(seed)`` so a seed
fully determines the workload):

- ``poisson``: exponential inter-arrivals at ``rate_per_min``;
- ``burst``: ``burst_size`` simultaneous arrivals every
  ``burst_interval`` seconds (thundering-herd onboarding);
- ``diurnal``: inhomogeneous Poisson via thinning, rate swinging
  sinusoidally between ``rate_per_min`` and ``peak_rate_per_min`` with
  period ``period`` (daily load curve compressed into the sim horizon).

Traces dump/load as JSONL (one app per line) so a generated workload —
or one distilled from production — replays bit-identically.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import asdict, dataclass
from typing import Dict, List


@dataclass
class AppSpec:
    app_id: str
    arrival: float
    executor_count: int
    lifetime: float
    dynamic: bool = False
    min_executor_count: int = 0  # dynamic only; == executor_count when static
    driver_cpu: str = "1"
    driver_mem: str = "1Gi"
    executor_cpu: str = "1"
    executor_mem: str = "1Gi"
    instance_group: str = "batch-medium-priority"
    namespace: str = "default"
    # policy-engine inputs (policy/): priority band + fair-share tenant.
    # Defaults keep pre-policy traces replaying bit-identically.
    band: str = "normal"
    tenant: str = ""

    def to_dict(self) -> Dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: Dict) -> "AppSpec":
        return AppSpec(**d)


# resource shapes drawn for generated apps: (driver_cpu, driver_mem,
# executor_cpu, executor_mem) — small menu so packing stays interesting
# without exploding the tensorizer's shape buckets
_SIZE_MENU = [
    ("1", "1Gi", "1", "1Gi"),
    ("1", "2Gi", "2", "2Gi"),
    ("2", "2Gi", "1", "4Gi"),
    ("1", "1Gi", "4", "4Gi"),
]


class WorkloadGenerator:
    """Seeded generator; ``spec`` is the scenario's ``workload`` dict."""

    def __init__(self, spec: Dict, seed: int):
        self.spec = dict(spec)
        self.seed = seed

    def generate(self, duration: float) -> List[AppSpec]:
        spec = self.spec
        if spec.get("trace"):
            return load_trace(spec["trace"])
        rng = random.Random(self.seed)
        arrivals = self._arrivals(rng, duration, spec)
        exec_lo = int(spec.get("executors", {}).get("min", 1))
        exec_hi = int(spec.get("executors", {}).get("max", 4))
        dyn_frac = float(spec.get("dynamic_fraction", 0.0))
        life_lo = float(spec.get("lifetime", {}).get("min", 60.0))
        life_hi = float(spec.get("lifetime", {}).get("max", 600.0))
        instance_group = spec.get("instance_group", "batch-medium-priority")
        # optional policy-shape knobs: "band_weights" {band: weight}
        # draws a band per app, "tenants" [name, ...] draws a tenant —
        # both off the same seeded rng so the trace stays deterministic
        band_weights = dict(spec.get("band_weights", {}))
        band_names = sorted(band_weights)
        tenants = list(spec.get("tenants", []))
        apps: List[AppSpec] = []
        for i, t in enumerate(arrivals):
            count = rng.randint(exec_lo, exec_hi)
            dynamic = rng.random() < dyn_frac
            min_count = rng.randint(max(1, count // 2), count) if dynamic else count
            sizes = rng.choice(_SIZE_MENU)
            band = spec.get("band", "normal")
            if band_names:
                band = rng.choices(
                    band_names, weights=[band_weights[b] for b in band_names]
                )[0]
            tenant = rng.choice(tenants) if tenants else spec.get("tenant", "")
            apps.append(
                AppSpec(
                    app_id=f"app-{i:04d}",
                    arrival=round(t, 3),
                    executor_count=count,
                    min_executor_count=min_count if dynamic else count,
                    dynamic=dynamic,
                    lifetime=round(rng.uniform(life_lo, life_hi), 3),
                    driver_cpu=sizes[0],
                    driver_mem=sizes[1],
                    executor_cpu=sizes[2],
                    executor_mem=sizes[3],
                    instance_group=instance_group,
                    band=band,
                    tenant=tenant,
                )
            )
        return apps

    @staticmethod
    def _arrivals(rng: random.Random, duration: float, spec: Dict) -> List[float]:
        process = spec.get("process", "poisson")
        rate = float(spec.get("rate_per_min", 2.0)) / 60.0  # per second
        out: List[float] = []
        if process == "poisson":
            t = 0.0
            while True:
                t += rng.expovariate(rate) if rate > 0 else duration + 1
                if t >= duration:
                    break
                out.append(t)
        elif process == "burst":
            interval = float(spec.get("burst_interval", 300.0))
            size = int(spec.get("burst_size", 5))
            t = float(spec.get("burst_offset", 1.0))
            while t < duration:
                out.extend([t] * size)
                t += interval
        elif process == "diurnal":
            peak = float(spec.get("peak_rate_per_min", 6.0)) / 60.0
            period = float(spec.get("period", duration or 1.0))
            lam_max = max(rate, peak)
            t = 0.0
            while True:  # Lewis-Shedler thinning
                t += rng.expovariate(lam_max) if lam_max > 0 else duration + 1
                if t >= duration:
                    break
                lam_t = rate + (peak - rate) * 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period))
                if rng.random() <= lam_t / lam_max:
                    out.append(t)
        else:
            raise ValueError(f"unknown arrival process {process!r}")
        return out


# -- trace (de)serialization --------------------------------------------------


def dump_trace(apps: List[AppSpec], path: str) -> None:
    with open(path, "w") as f:
        for app in apps:
            f.write(json.dumps(app.to_dict(), sort_keys=True) + "\n")


def load_trace(path: str) -> List[AppSpec]:
    apps: List[AppSpec] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                apps.append(AppSpec.from_dict(json.loads(line)))
    apps.sort(key=lambda a: (a.arrival, a.app_id))
    return apps
