"""Generic single-writer write-back cache + async client.

The write path of the reference (internal/cache/cache.go + async.go):
mutations hit the local store synchronously and enqueue a write; N
worker threads per cached type drain the sharded queue and replay the
writes against the API server with bounded retries, inline 409-conflict
resolution, and namespace-terminating detection.  Informer events only
fold resourceVersions back in (external creates/updates are ignored —
this process is the sole writer) and deletes remove from the store.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)

from ..ha import crashpoint
from ..ha.fencing import StaleEpochError
from ..kube import conflict as kconflict
from ..kube import errors as kerrors
from ..kube.apiserver import APIServer
from ..kube.informer import Informer
from ..tracing import spans as tracing
from ..types.objects import APIObject
from . import store as _store
from .store import (
    CREATE,
    DELETE,
    ObjectStore,
    Request,
    ShardedUniqueQueue,
    UPDATE,
    create_request,
    delete_request,
    key_of,
    update_request,
)


class AlreadyExistsInCacheError(Exception):
    pass


class NotInCacheError(Exception):
    pass


class WriteBackCache:
    """cache.go:32-125."""

    def __init__(self, queue: ShardedUniqueQueue, object_store: ObjectStore, informer: Informer):
        self._queue = queue
        self._store = object_store
        informer.add_event_handler(
            on_add=self._try_override_rv,
            on_update=lambda old, new: self._try_override_rv(new),
            on_delete=self._on_delete,
        )

    def create(self, obj: APIObject) -> None:
        with tracing.child_span(
            "state.writeback.enqueue", {"op": "create", "kind": obj.KIND}
        ):
            if not self._store.put_if_absent(obj):
                raise AlreadyExistsInCacheError(f"object {key_of(obj)} already exists")
            self._queue.add_if_absent(create_request(obj))

    def get(self, namespace: str, name: str) -> Optional[APIObject]:
        return self._store.get((namespace, name))

    def update(self, obj: APIObject) -> None:
        with tracing.child_span(
            "state.writeback.enqueue", {"op": "update", "kind": obj.KIND}
        ):
            if self._store.get(key_of(obj)) is None:
                raise NotInCacheError(f"object {key_of(obj)} does not exist")
            self._store.put(obj)
            self._queue.add_if_absent(update_request(obj))

    def delete(self, namespace: str, name: str) -> None:
        with tracing.child_span("state.writeback.enqueue", {"op": "delete"}):
            key = (namespace, name)
            self._store.delete(key)
            self._queue.add_if_absent(delete_request(key))

    def list(self) -> List[APIObject]:
        return self._store.list()

    def _try_override_rv(self, obj: APIObject) -> None:
        self._store.override_resource_version_if_newer(obj)

    def _on_delete(self, obj: APIObject) -> None:
        self._store.delete(key_of(obj))


class TypedClient:
    """cache.Client (async.go:38-44): kind-scoped CRUD against the API
    server (or any backend with the same surface)."""

    def __init__(self, api: APIServer, kind: str):
        self._api = api
        self._kind = kind

    def create(self, obj: APIObject) -> APIObject:
        return self._api.create(obj)

    def update(self, obj: APIObject) -> APIObject:
        return self._api.update(obj)

    def delete(self, namespace: str, name: str) -> None:
        self._api.delete(self._kind, namespace, name)

    def get(self, namespace: str, name: str) -> APIObject:
        return self._api.get(self._kind, namespace, name)


class AsyncClient:
    """async.go:44-163: per-shard worker threads draining the queue.

    With a circuit ``breaker`` + intent ``journal`` attached (the
    resilience layer; reservation cache only), repeated write failures
    open the breaker and requests are *diverted* to the journal instead
    of burning retries against a dead API server — and, critically,
    instead of being dropped at max retries.  The journal is replayed
    through this same queue when a probe write succeeds (breaker closes)
    or a recovery nudge arrives.
    """

    def __init__(
        self,
        client: TypedClient,
        queue: ShardedUniqueQueue,
        object_store: ObjectStore,
        max_retry_count: int = 5,
        metrics=None,
        breaker=None,
        journal=None,
        kind: str = "",
        to_wire=None,
        registry=None,
    ):
        self._client = client
        self._queue = queue
        self._store = object_store
        self._max_retry_count = max_retry_count
        self._metrics = metrics
        self._breaker = breaker
        self._journal = journal
        self._kind = kind
        self._to_wire = to_wire
        # full metrics registry (conflict-retry counter); the `metrics`
        # param above is the per-request outcome marker, kept separate
        # for reference parity
        self._registry = registry
        # HA fencing gate (ha/fencing.FencedWriter), installed by server
        # wiring when the fabric is enabled: every API mutation is
        # refused with StaleEpochError once this replica is deposed
        self.fence_gate = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def run(self) -> None:
        for i, q in enumerate(self._queue.get_consumers()):
            t = threading.Thread(target=self._run_worker, args=(q,), daemon=True, name=f"async-{i}")
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    def _run_worker(self, q) -> None:
        import queue as pyqueue

        while not self._stop.is_set():
            try:
                request_getter = q.get(timeout=0.05)
            except pyqueue.Empty:
                continue
            r: Request = request_getter()
            try:
                if self._breaker is not None and not self._breaker.allow():
                    # breaker open and no probe due: don't touch the API
                    # server at all — preserve the intent and move on
                    self._divert(r, "journaled_breaker_open")
                    continue
                if r.type == CREATE:
                    self._do_create(r)
                elif r.type == UPDATE:
                    self._do_update(r)
                elif r.type == DELETE:
                    self._do_delete(r)
            except StaleEpochError as fe:
                # deposed leader: the write is refused, never dropped —
                # divert the intent to the journal so the successor's
                # takeover replay owns it.  Not a breaker signal (the
                # server was never touched).
                logger.warning(
                    "fenced write refused: %s %s (%s)", r.type, r.key, fe
                )
                self._release_probe()
                self._divert(r, "journaled_fenced")
            except Exception:
                # worker must survive anything, but a failure reaching here
                # is a programming error (client errors are handled in the
                # per-request handlers) — surface it
                logger.exception("async write-back worker failed on %s %s", r.type, r.key)
                try:
                    self._release_probe()  # never wedge recovery on a bug
                    self._mark(r, "worker_error")
                except Exception:
                    pass

    # -- request handlers (async.go:77-137) ---------------------------------

    def _pre_commit(self, r: Request) -> None:
        """HA fence + crash-injection gate before any API mutation.
        Raises StaleEpochError (worker loop diverts the intent to the
        journal) or SimulatedCrash (BaseException — the crash matrix's
        kill -9).  Disabled cost: two attribute reads."""
        gate = self.fence_gate
        if gate is not None:
            gate.check(f"writeback.{r.type}")
        crashpoint.maybe_crash(crashpoint.WRITEBACK_PRE_COMMIT)

    def _post_commit(self) -> None:
        gate = self.fence_gate
        if gate is not None:
            gate.commit()
        crashpoint.maybe_crash(crashpoint.WRITEBACK_POST_COMMIT)

    def _do_create(self, r: Request) -> None:
        obj = self._store.get(r.key)
        if obj is None:
            self._release_probe()  # deleted while queued: no write happened
            return
        self._mark(r, "request")
        self._pre_commit(r)
        try:
            result = self._client.create(obj)
        except kerrors.AlreadyExistsError:
            # idempotent replay: the create already landed (a journaled
            # intent re-applied after failover, or a write that succeeded
            # just as its response was lost) — fold the server copy's RV
            # and treat as success, never as a duplicate write
            try:
                current = self._client.get(r.key[0], r.key[1])
            except Exception as get_err:
                self._on_write_failure(r, get_err)
                return
            self._store.fold_resource_version(current)
            self._on_write_ok(r)
            return
        except Exception as err:
            if kerrors.is_namespace_terminating(err):
                self._store.delete(r.key)
                self._ack_journal(r)
                return
            if not self._on_write_failure(r, err) and self._journal is None:
                self._store.delete(r.key)
            return
        # fold the result's RV in atomically, never resurrecting a key
        # deleted (e.g. by owner GC) while the create was in flight
        self._store.fold_resource_version(result)
        self._post_commit()
        self._on_write_ok(r)

    def _do_update(self, r: Request) -> None:
        obj = self._store.get(r.key)
        if obj is None:
            self._release_probe()  # deleted while queued: no write happened
            return
        self._mark(r, "request")
        self._pre_commit(r)

        def attempt():
            current = self._store.get(r.key)
            if current is None:
                return None  # deleted locally mid-retry: intent is moot
            return self._client.update(current)

        def refresh() -> bool:
            # refresh RV from the server and rebase (async.go:111-120);
            # a conflict means the server is alive — never a breaker
            # signal.  False (key folded away locally) aborts the loop.
            new_obj = self._client.get(r.key[0], r.key[1])
            return self._store.fold_resource_version(new_obj)

        try:
            result = kconflict.run_with_conflict_retry(
                attempt, refresh, kind=self._kind, metrics=self._registry
            )
        except kerrors.NotFoundError:
            if (
                self._journal is not None
                and r.key in self._journal.pending_keys()
            ):
                # journaled replay: the object's create was collapsed
                # into this update intent while diverted (latest-wins per
                # key) and never landed — upsert it.  The store holds the
                # full newest content; _do_create acks the pending intent
                # (create and update share the upsert ack class).
                self._do_create(Request(r.key, CREATE, r.retry_count))
                return
            # the server authoritatively lacks the object (owner GC beat
            # this update): a response from a LIVE server, so never a
            # breaker signal, and not a journalable intent either —
            # resurrecting a GC'd object would undo a deliberate delete.
            # Bounded retry while the informer's delete catches up
            # locally, then drop (the pre-resilience semantics).
            self._release_probe()
            if r.retry_count >= self._max_retry_count:
                self._mark(r, "dropped_not_found")
            else:
                self._mark(r, "retry")
                self._queue.try_add_if_absent(r.with_incremented_retry_count())
            return
        except Exception as err:
            # includes a ConflictError re-raised after the retry budget:
            # route through the normal failure taxonomy (journal/retry)
            self._on_write_failure(r, err)
            return
        if result is None:
            self._release_probe()  # vanished locally: no write landed
            return
        self._store.fold_resource_version(result)
        self._post_commit()
        self._on_write_ok(r)

    def _do_delete(self, r: Request) -> None:
        self._mark(r, "request")
        self._pre_commit(r)
        try:
            self._client.delete(r.key[0], r.key[1])
        except kerrors.NotFoundError:
            self._on_write_ok(r)  # already deleted: the intent is satisfied
            return
        except Exception as err:
            self._on_write_failure(r, err)
            return
        self._post_commit()
        self._on_write_ok(r)

    # -- resilience hooks ----------------------------------------------------

    def _release_probe(self) -> None:
        """A request granted by breaker.allow() ended without any write
        reaching the server — free the (possible) half-open probe slot so
        recovery can't wedge on an aborted probe."""
        if self._breaker is not None:
            self._breaker.release_probe()

    def _on_write_ok(self, r: Request) -> None:
        self._ack_journal(r)
        if self._breaker is not None and self._breaker.record_success():
            # a probe write just closed the breaker: replay everything
            # that was diverted while it was open
            self.replay_journal()

    def _on_write_failure(self, r: Request, err: Exception) -> bool:
        """Route a failed write: breaker accounting, then divert-or-retry.
        Returns True when the intent is preserved (retrying or journaled),
        False when it was dropped."""
        if self._breaker is not None:
            self._breaker.record_failure()
            if not self._breaker.probe_due() and self._breaker.state != "closed":
                # open with no probe window: stop hammering the server
                self._divert(r, "journaled_write_failed")
                return self._journal is not None
        return self._maybe_retry(r, err)

    def _divert(self, r: Request, what: str) -> None:
        """Preserve the intent in the journal instead of writing.  With
        no journal configured this degrades to the historical drop
        semantics (creates leave the local store so reads stay honest
        with what was admitted; reconciliation repairs later)."""
        if self._journal is None:
            self._mark(r, "dropped_no_journal")
            if r.type == CREATE:
                self._store.delete(r.key)
            return
        obj = self._store.get(r.key)
        if r.type in (CREATE, UPDATE) and obj is None:
            return  # deleted while queued: intent is moot
        wire = None
        if obj is not None and self._to_wire is not None:
            try:
                wire = self._to_wire(obj)
            except Exception:
                logger.exception("failed to serialize %s for the intent journal", r.key)
        self._journal.record(r.type, self._kind, r.key[0], r.key[1], wire)
        self._mark(r, what)

    def _ack_journal(self, r: Request) -> None:
        if self._journal is not None:
            try:
                self._journal.ack(r.type, r.key[0], r.key[1])
            except StaleEpochError:
                # deposed between the write landing and the ack: leave
                # the intent pending — the successor's replay is
                # idempotent, losing the ack is safe; losing the intent
                # would not be
                logger.warning("fenced journal ack refused for %s", r.key)

    def replay_journal(self) -> int:
        """Re-enqueue every pending journaled intent through the normal
        write path.  Idempotent: creates that already landed fold via
        AlreadyExists, deletes via NotFound; intents whose object was
        GC'd locally are acked as moot.  Returns the number enqueued."""
        if self._journal is None:
            return 0
        enqueued = 0
        for intent in self._journal.pending():
            key = (intent["ns"], intent["name"])
            op = intent["op"]
            if op in (CREATE, UPDATE) and self._store.get(key) is None:
                self._journal.ack(op, key[0], key[1])
                continue
            if self._queue.try_add_if_absent(Request(key, op)):
                enqueued += 1
            else:
                break  # shard full: the next nudge picks the rest up
        return enqueued

    def nudge_recovery(self, force: bool = False) -> int:
        """Periodic/explicit recovery poke: when journaled intents exist
        and a write could land (breaker closed, or a probe window is
        due — or ``force``, the explicit 'server is back' signal), put
        them back on the queue.  While the breaker stays open only one
        intent is enqueued (the probe); its success closes the breaker,
        which replays the rest."""
        if self._journal is None or self._journal.depth() == 0:
            return 0
        if self._breaker is None or self._breaker.state == "closed":
            return self.replay_journal()
        if force:
            self._breaker.trip_half_open()
        elif not self._breaker.probe_due():
            return 0
        for intent in self._journal.pending():
            key = (intent["ns"], intent["name"])
            op = intent["op"]
            if op in (CREATE, UPDATE) and self._store.get(key) is None:
                self._journal.ack(op, key[0], key[1])
                continue
            return 1 if self._queue.try_add_if_absent(Request(key, op)) else 0
        return 0

    def _maybe_retry(self, r: Request, err: Exception) -> bool:
        """async.go:139-154: bounded retries, re-enqueued non-blocking.
        With a journal attached, exhausted retries divert instead of
        dropping — a reservation intent is never lost."""
        if r.retry_count >= self._max_retry_count:
            if self._journal is not None:
                self._divert(r, "journaled_max_retries")
                return True
            self._mark(r, "dropped_max_retries")
            return False
        self._mark(r, "retry")
        enqueued = self._queue.try_add_if_absent(r.with_incremented_retry_count())
        if not enqueued:
            if self._journal is not None:
                self._divert(r, "journaled_queue_full")
                return True
            self._mark(r, "dropped_queue_full")
            return False
        return True

    def _mark(self, r: Request, what: str) -> None:
        if self._metrics is not None:
            self._metrics.mark(what, r.type)
