"""Generic single-writer write-back cache + async client.

The write path of the reference (internal/cache/cache.go + async.go):
mutations hit the local store synchronously and enqueue a write; N
worker threads per cached type drain the sharded queue and replay the
writes against the API server with bounded retries, inline 409-conflict
resolution, and namespace-terminating detection.  Informer events only
fold resourceVersions back in (external creates/updates are ignored —
this process is the sole writer) and deletes remove from the store.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)

from ..kube import errors as kerrors
from ..kube.apiserver import APIServer
from ..kube.informer import Informer
from ..tracing import spans as tracing
from ..types.objects import APIObject
from . import store as _store
from .store import (
    CREATE,
    DELETE,
    ObjectStore,
    Request,
    ShardedUniqueQueue,
    UPDATE,
    create_request,
    delete_request,
    key_of,
    update_request,
)


class AlreadyExistsInCacheError(Exception):
    pass


class NotInCacheError(Exception):
    pass


class WriteBackCache:
    """cache.go:32-125."""

    def __init__(self, queue: ShardedUniqueQueue, object_store: ObjectStore, informer: Informer):
        self._queue = queue
        self._store = object_store
        informer.add_event_handler(
            on_add=self._try_override_rv,
            on_update=lambda old, new: self._try_override_rv(new),
            on_delete=self._on_delete,
        )

    def create(self, obj: APIObject) -> None:
        with tracing.child_span(
            "state.writeback.enqueue", {"op": "create", "kind": obj.KIND}
        ):
            if not self._store.put_if_absent(obj):
                raise AlreadyExistsInCacheError(f"object {key_of(obj)} already exists")
            self._queue.add_if_absent(create_request(obj))

    def get(self, namespace: str, name: str) -> Optional[APIObject]:
        return self._store.get((namespace, name))

    def update(self, obj: APIObject) -> None:
        with tracing.child_span(
            "state.writeback.enqueue", {"op": "update", "kind": obj.KIND}
        ):
            if self._store.get(key_of(obj)) is None:
                raise NotInCacheError(f"object {key_of(obj)} does not exist")
            self._store.put(obj)
            self._queue.add_if_absent(update_request(obj))

    def delete(self, namespace: str, name: str) -> None:
        with tracing.child_span("state.writeback.enqueue", {"op": "delete"}):
            key = (namespace, name)
            self._store.delete(key)
            self._queue.add_if_absent(delete_request(key))

    def list(self) -> List[APIObject]:
        return self._store.list()

    def _try_override_rv(self, obj: APIObject) -> None:
        self._store.override_resource_version_if_newer(obj)

    def _on_delete(self, obj: APIObject) -> None:
        self._store.delete(key_of(obj))


class TypedClient:
    """cache.Client (async.go:38-44): kind-scoped CRUD against the API
    server (or any backend with the same surface)."""

    def __init__(self, api: APIServer, kind: str):
        self._api = api
        self._kind = kind

    def create(self, obj: APIObject) -> APIObject:
        return self._api.create(obj)

    def update(self, obj: APIObject) -> APIObject:
        return self._api.update(obj)

    def delete(self, namespace: str, name: str) -> None:
        self._api.delete(self._kind, namespace, name)

    def get(self, namespace: str, name: str) -> APIObject:
        return self._api.get(self._kind, namespace, name)


class AsyncClient:
    """async.go:44-163: per-shard worker threads draining the queue."""

    def __init__(
        self,
        client: TypedClient,
        queue: ShardedUniqueQueue,
        object_store: ObjectStore,
        max_retry_count: int = 5,
        metrics=None,
    ):
        self._client = client
        self._queue = queue
        self._store = object_store
        self._max_retry_count = max_retry_count
        self._metrics = metrics
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def run(self) -> None:
        for i, q in enumerate(self._queue.get_consumers()):
            t = threading.Thread(target=self._run_worker, args=(q,), daemon=True, name=f"async-{i}")
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    def _run_worker(self, q) -> None:
        import queue as pyqueue

        while not self._stop.is_set():
            try:
                request_getter = q.get(timeout=0.05)
            except pyqueue.Empty:
                continue
            r: Request = request_getter()
            try:
                if r.type == CREATE:
                    self._do_create(r)
                elif r.type == UPDATE:
                    self._do_update(r)
                elif r.type == DELETE:
                    self._do_delete(r)
            except Exception:
                # worker must survive anything, but a failure reaching here
                # is a programming error (client errors are handled in the
                # per-request handlers) — surface it
                logger.exception("async write-back worker failed on %s %s", r.type, r.key)
                try:
                    self._mark(r, "worker_error")
                except Exception:
                    pass

    # -- request handlers (async.go:77-137) ---------------------------------

    def _do_create(self, r: Request) -> None:
        obj = self._store.get(r.key)
        if obj is None:
            return  # deleted while queued
        self._mark(r, "request")
        try:
            result = self._client.create(obj)
        except Exception as err:
            if kerrors.is_namespace_terminating(err):
                self._store.delete(r.key)
                return
            if not self._maybe_retry(r, err):
                self._store.delete(r.key)
            return
        # fold the result's RV in atomically, never resurrecting a key
        # deleted (e.g. by owner GC) while the create was in flight
        self._store.fold_resource_version(result)

    def _do_update(self, r: Request) -> None:
        obj = self._store.get(r.key)
        if obj is None:
            return
        self._mark(r, "request")
        try:
            result = self._client.update(obj)
        except kerrors.ConflictError:
            # refresh RV from the server and retry inline (async.go:111-120);
            # stop if the object vanished locally meanwhile
            try:
                new_obj = self._client.get(r.key[0], r.key[1])
            except Exception as get_err:
                self._maybe_retry(r, get_err)
                return
            if not self._store.fold_resource_version(new_obj):
                return
            self._do_update(update_request(new_obj))
            return
        except Exception as err:
            self._maybe_retry(r, err)
            return
        self._store.fold_resource_version(result)

    def _do_delete(self, r: Request) -> None:
        self._mark(r, "request")
        try:
            self._client.delete(r.key[0], r.key[1])
        except kerrors.NotFoundError:
            return  # already deleted
        except Exception as err:
            self._maybe_retry(r, err)

    def _maybe_retry(self, r: Request, err: Exception) -> bool:
        """async.go:139-154: bounded retries, re-enqueued non-blocking."""
        if r.retry_count >= self._max_retry_count:
            self._mark(r, "dropped_max_retries")
            return False
        self._mark(r, "retry")
        enqueued = self._queue.try_add_if_absent(r.with_incremented_retry_count())
        if not enqueued:
            self._mark(r, "dropped_queue_full")
            return False
        return True

    def _mark(self, r: Request, what: str) -> None:
        if self._metrics is not None:
            self._metrics.mark(what, r.type)
