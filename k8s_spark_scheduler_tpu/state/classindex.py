"""Node equivalence-class index (ROADMAP 2: Firmament/Borg-style
aggregation) — the state-layer companion of the native class-compressed
solver (native/fifo_solver.cpp ``fifo_solve_queue_classes``).

Real fleets have a few dozen machine shapes, so the 100k-node table
collapses to a small set of classes.  This index maintains, O(1) per
ChangeFeed delta (a node mutation touches one class, never a row scan):

- the class multiset keyed by (rounded capacity vector, label
  signature, AZ, schedulability) with per-class multiplicities — the
  capacity observatory's per-class analytics and the ``tpu.classes.*``
  gauges read it;
- ``class_rev`` — bumped whenever the class MULTISET changes (a node
  changes class, appears, or disappears), so consumers can cache
  class-derived work across same-class node churn;
- ``digest`` — an XOR-combination of one 64-bit hash per node over the
  node's FULL content (name, allocatable, usage, overhead, zone,
  ready, unschedulable, label signature).  XOR makes the digest
  order-independent and self-cancelling under churn, so maintaining it
  is O(1) per delta.  Equal digests across two snapshots of the same
  mirror (same structure revision) imply equal rows up to 64-bit
  collisions — the delta-solve engine uses it as an O(1) warm-basis
  tier ahead of the O(N) row compare, and its existing warm≠cold
  parity guard audits the conclusion.

Hashes use the process-seeded builtin ``hash`` (tuple hashing is C
speed); digests are only ever compared within one process, and the
``(instance, digest)`` pairing on snapshots keeps different mirrors
from aliasing.

Thread-safety: the owning TensorSnapshotCache calls every mutator under
its own lock, but the index carries its own lock (and the racecheck
note_access hook) so the capacity observatory can read stats without
entering the mirror's critical section.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by

# class-key capacity rounding: quantities inside one bucket are the
# same machine shape for analytics purposes (base units are cpu milli /
# mem bytes / gpu milli — see ops/tensorize.py)
CPU_BUCKET_MILLI = 500          # half a core
MEM_BUCKET_BYTES = 1 << 30      # 1 GiB
GPU_BUCKET_MILLI = 1000         # whole accelerators


def labels_signature(labels: Dict[str, str]) -> int:
    """Order-independent stable-within-process label signature."""
    return hash(tuple(sorted(labels.items())))


@guarded_by("_lock")
class ClassIndex:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # class key -> multiplicity
        self._counts: Dict[tuple, int] = {}
        # node slot -> (class key, content hash, labels signature)
        self._slots: Dict[int, Tuple[tuple, int, int]] = {}
        self._digest = 0
        self._rev = 0

    # -- keys ----------------------------------------------------------------

    @staticmethod
    def _key(alloc_row, zone: int, ready: bool, unsched: bool,
             labels_sig: int) -> tuple:
        return (
            int(alloc_row[0]) // CPU_BUCKET_MILLI,
            int(alloc_row[1]) // MEM_BUCKET_BYTES,
            int(alloc_row[2]) // GPU_BUCKET_MILLI,
            labels_sig,
            int(zone),
            bool(ready) and not bool(unsched),
        )

    @staticmethod
    def _content_hash(name: str, alloc_row, usage_row, overhead_row,
                      zone: int, ready: bool, unsched: bool,
                      labels_sig: int, res_count: int) -> int:
        return hash((
            name,
            int(alloc_row[0]), int(alloc_row[1]), int(alloc_row[2]),
            int(usage_row[0]), int(usage_row[1]), int(usage_row[2]),
            int(overhead_row[0]), int(overhead_row[1]), int(overhead_row[2]),
            int(zone), bool(ready), bool(unsched), labels_sig,
            int(res_count),
        ))

    # -- maintenance (one call per ChangeFeed delta) -------------------------

    def note_node(self, slot: int, name: str, alloc_row, usage_row,
                  overhead_row, zone: int, ready: bool, unsched: bool,
                  res_count: int = 0,
                  labels: Optional[Dict[str, str]] = None) -> None:
        """(Re)index one node slot.  ``labels=None`` reuses the cached
        label signature (usage/overhead deltas never change labels, and
        recomputing the signature would make them O(labels))."""
        with self._lock:
            racecheck.note_access(self, "_slots")
            prev = self._slots.get(slot)
            if labels is not None:
                sig = labels_signature(labels)
            elif prev is not None:
                sig = prev[2]
            else:
                sig = labels_signature({})
            key = self._key(alloc_row, zone, ready, unsched, sig)
            h = self._content_hash(
                name, alloc_row, usage_row, overhead_row, zone, ready,
                unsched, sig, res_count,
            )
            if prev is not None:
                prev_key, prev_hash, _ = prev
                if prev_key != key:
                    self._retire_key(prev_key)
                    self._admit_key(key)
                self._digest ^= prev_hash
            else:
                self._admit_key(key)
            self._digest ^= h
            self._slots[slot] = (key, h, sig)

    def drop_node(self, slot: int) -> None:
        with self._lock:
            racecheck.note_access(self, "_slots")
            prev = self._slots.pop(slot, None)
            if prev is None:
                return
            self._retire_key(prev[0])
            self._digest ^= prev[1]

    def _admit_key(self, key: tuple) -> None:
        self._counts[key] = self._counts.get(key, 0) + 1
        self._rev += 1

    def _retire_key(self, key: tuple) -> None:
        n = self._counts.get(key, 0) - 1
        if n <= 0:
            self._counts.pop(key, None)
        else:
            self._counts[key] = n
        self._rev += 1

    # -- reads ---------------------------------------------------------------

    @property
    def digest(self) -> int:
        with self._lock:
            return self._digest

    @property
    def class_rev(self) -> int:
        with self._lock:
            return self._rev

    def stats(self) -> Tuple[int, int, float]:
        """(class count, node count, compression ratio nodes/classes)."""
        with self._lock:
            n_classes = len(self._counts)
            n_nodes = len(self._slots)
            ratio = (n_nodes / n_classes) if n_classes else 1.0
            return n_classes, n_nodes, ratio

    def class_sizes(self) -> Dict[tuple, int]:
        """Copy of the class multiset (key -> multiplicity)."""
        with self._lock:
            return dict(self._counts)
