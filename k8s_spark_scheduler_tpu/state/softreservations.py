"""In-memory soft reservations for dynamic-allocation extra executors.

internal/cache/softreservations.go: per-app extra-executor reservations
above the min count, with a Status tombstone map that remembers dead
executors to defeat the death-event/schedule race
(softreservations.go:41-50, 204-210).  Intentionally not persisted —
rebuilt by failover reconciliation (failover.go:174-241).
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..kube.informer import Informer
from ..scheduler.labels import SPARK_APP_ID_LABEL, SPARK_ROLE_LABEL, DRIVER, EXECUTOR, is_spark_scheduler_pod
from ..types.objects import Pod, Reservation
from ..types.resources import NodeGroupResources, Resources


@dataclass
class SoftReservation:
    """softreservations.go:41-50."""

    # executor pod name → Reservation (valid ones only)
    reservations: Dict[str, Reservation] = field(default_factory=dict)
    # executor pod name → valid?  False entries are tombstones of dead
    # executors so a late schedule request can't resurrect a spot
    status: Dict[str, bool] = field(default_factory=dict)


@guarded_by("_lock", "_store", "_observers")
class SoftReservationStore:
    def __init__(self, pod_informer: Optional[Informer] = None):
        self._lock = threading.RLock()
        self._store: Dict[str, SoftReservation] = {}
        # (node, Resources, +1/-1) observers for incremental usage mirrors
        self._observers = []
        if pod_informer is not None:
            pod_informer.add_event_handler(
                on_delete=self._on_pod_deletion,
                filter_func=is_spark_scheduler_pod,
            )

    def get_soft_reservation(self, app_id: str) -> Tuple[SoftReservation, bool]:
        with self._lock:
            sr = self._store.get(app_id)
            if sr is None:
                return SoftReservation(), False
            return copy.deepcopy(sr), True

    def get_all_soft_reservations_copy(self) -> Dict[str, SoftReservation]:
        with self._lock:
            return {app_id: copy.deepcopy(sr) for app_id, sr in self._store.items()}

    def create_soft_reservation_if_not_exists(self, app_id: str) -> None:
        with self._lock:
            racecheck.note_access(self, "_store")
            if app_id not in self._store:
                self._store[app_id] = SoftReservation()

    def add_reservation_for_pod(self, app_id: str, pod_name: str, reservation: Reservation) -> None:
        """No-op if the pod was ever seen (incl. tombstoned)
        (softreservations.go:110-131)."""
        with self._lock:
            racecheck.note_access(self, "_store")
            sr = self._store.get(app_id)
            if sr is None:
                raise KeyError(f"no soft reservation store entry for app {app_id}")
            if pod_name in sr.status:
                return
            sr.reservations[pod_name] = reservation
            sr.status[pod_name] = True
            self._notify(reservation.node, reservation.resources_value(), +1, pod_name)

    def executor_has_soft_reservation(self, executor: Pod) -> bool:
        return self.get_executor_soft_reservation(executor) is not None

    def get_executor_soft_reservation(self, executor: Pod) -> Optional[Reservation]:
        with self._lock:
            app_id = executor.labels.get(SPARK_APP_ID_LABEL)
            if app_id is None:
                return None
            sr = self._store.get(app_id)
            if sr is not None:
                res = sr.reservations.get(executor.name)
                if res is not None:
                    return copy.deepcopy(res)
            return None

    def used_soft_reservation_resources(self) -> NodeGroupResources:
        """softreservations.go:155-170."""
        with self._lock:
            usage: NodeGroupResources = {}
            for sr in self._store.values():
                for reservation in sr.reservations.values():
                    node = reservation.node
                    usage[node] = usage.get(node, Resources.zero()).add(
                        reservation.resources_value()
                    )
            return usage

    def remove_executor_reservation(self, app_id: str, executor_name: str) -> None:
        """Drop the reservation but tombstone the name
        (softreservations.go:204-216)."""
        with self._lock:
            racecheck.note_access(self, "_store")
            sr = self._store.get(app_id)
            if sr is None:
                return
            removed = sr.reservations.pop(executor_name, None)
            sr.status[executor_name] = False
            if removed is not None:
                self._notify(removed.node, removed.resources_value(), -1, executor_name)

    def remove_driver_reservation(self, app_id: str) -> None:
        with self._lock:
            racecheck.note_access(self, "_store")
            sr = self._store.pop(app_id, None)
            if sr is not None:
                for pod_name, reservation in sr.reservations.items():
                    self._notify(reservation.node, reservation.resources_value(), -1, pod_name)

    def _on_pod_deletion(self, pod: Pod) -> None:
        app_id = pod.labels.get(SPARK_APP_ID_LABEL, "")
        role = pod.labels.get(SPARK_ROLE_LABEL)
        if role == DRIVER:
            self.remove_driver_reservation(app_id)
        elif role == EXECUTOR:
            self.remove_executor_reservation(app_id, pod.name)

    def add_change_observer(self, fn) -> None:
        """fn(node, resources, sign, pod_name): called under the store lock
        on every reservation add (+1) / removal (-1)."""
        # under the lock: registration must not race a concurrent
        # _notify iteration over the same list
        with self._lock:
            racecheck.note_access(self, "_observers")
            self._observers.append(fn)

    def _notify(self, node: str, resources: Resources, sign: int, pod_name: str) -> None:
        for fn in self._observers:
            try:
                fn(node, resources, sign, pod_name)
            except Exception:
                import logging

                logging.getLogger(__name__).exception("soft reservation observer failed")

    # -- metrics helpers -----------------------------------------------------

    def get_application_count(self) -> int:
        with self._lock:
            return len(self._store)

    def get_active_extra_executor_count(self) -> int:
        with self._lock:
            return sum(len(sr.reservations) for sr in self._store.values())
