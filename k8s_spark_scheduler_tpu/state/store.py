"""Write-through object store + sharded unique write queue.

Reproduces ``internal/cache/store/`` exactly: an ObjectStore whose Put
preserves the currently-known resourceVersion (store.go:51-59), an
OverrideResourceVersionIfNewer that folds informer truth back in by
numeric comparison (store.go:62-76), and a sharded queue that dedupes
inflight create/update requests per key while always enqueuing deletes
(queue.go:58-92), with fnv32a shard selection so writes for the same
object serialize (queue.go:123-128).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..types.objects import APIObject

Key = Tuple[str, str]  # (namespace, name)


def key_of(obj: APIObject) -> Key:
    return (obj.namespace, obj.name)


CREATE = "create"
UPDATE = "update"
DELETE = "delete"


@dataclass(frozen=True)
class Request:
    """store/request.go:33-69."""

    key: Key
    type: str
    retry_count: int = 0

    def with_incremented_retry_count(self) -> "Request":
        return Request(self.key, self.type, self.retry_count + 1)


def create_request(obj: APIObject) -> Request:
    return Request(key_of(obj), CREATE)


def update_request(obj: APIObject) -> Request:
    return Request(key_of(obj), UPDATE)


def delete_request(key: Key) -> Request:
    return Request(key, DELETE)


@guarded_by("_lock", "_store", "_observers")
class ObjectStore:
    """Thread-safe map[(ns,name)] → object (store.go:27-130).

    Content observers fire (old, new) under the lock on every semantic
    content change (insert, replace, delete) — resourceVersion-only bumps
    don't notify.  Downstream incremental mirrors (the tensor snapshot)
    hang off these, so they see exactly what the store sees, including
    inserts that arrive through informer folds.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._store: Dict[Key, APIObject] = {}
        self._observers = []

    def add_content_observer(self, fn) -> None:
        """Registers fn(old, new) and synchronously replays the current
        contents as (None, obj) so late-constructed mirrors see state
        seeded before they existed (e.g. lister-seeded reservations on
        restart)."""
        with self._lock:
            racecheck.note_access(self, "_observers")
            self._observers.append(fn)
            snapshot = list(self._store.values())
        for obj in snapshot:
            try:
                fn(None, obj)
            except Exception:
                import logging

                logging.getLogger(__name__).exception("store observer replay failed")

    def _notify(self, old: Optional[APIObject], new: Optional[APIObject]) -> None:
        for fn in self._observers:
            try:
                fn(old, new)
            except Exception:
                import logging

                logging.getLogger(__name__).exception("store observer failed")

    def put(self, obj: APIObject) -> None:
        """Store obj, preserving the currently-known resourceVersion: this
        process is the sole writer, so local RV is authoritative
        (store.go:51-59)."""
        with self._lock:
            racecheck.note_access(self, "_store")
            key = key_of(obj)
            current = self._store.get(key)
            if current is not None:
                obj.meta.resource_version = current.meta.resource_version
            self._store[key] = obj
            self._notify(current, obj)

    def override_resource_version_if_newer(self, obj: APIObject) -> bool:
        """Fold an externally-observed object in: only bump our RV if the
        external one is numerically newer (store.go:62-76)."""
        with self._lock:
            racecheck.note_access(self, "_store")
            key = key_of(obj)
            current = self._store.get(key)
            if current is None:
                self._store[key] = obj
                self._notify(None, obj)
                return True
            is_newer = current.meta.resource_version < obj.meta.resource_version
            if is_newer:
                current.meta.resource_version = obj.meta.resource_version
            return is_newer

    def put_if_absent(self, obj: APIObject) -> bool:
        with self._lock:
            racecheck.note_access(self, "_store")
            key = key_of(obj)
            if key in self._store:
                return False
            self._store[key] = obj
            self._notify(None, obj)
            return True

    def fold_resource_version(self, obj: APIObject) -> bool:
        """override_resource_version_if_newer WITHOUT the insert-when-
        absent behavior, as one atomic operation: used by the async client
        after a successful write so a concurrent delete can never be
        resurrected by the fold (check-then-act under the store lock)."""
        with self._lock:
            current = self._store.get(key_of(obj))
            if current is None:
                return False
            if current.meta.resource_version < obj.meta.resource_version:
                current.meta.resource_version = obj.meta.resource_version
                return True
            return False

    def get(self, key: Key) -> Optional[APIObject]:
        with self._lock:
            return self._store.get(key)

    def delete(self, key: Key) -> None:
        with self._lock:
            racecheck.note_access(self, "_store")
            old = self._store.pop(key, None)
            if old is not None:
                self._notify(old, None)

    def list(self) -> List[APIObject]:
        with self._lock:
            return list(self._store.values())


# -- change feed --------------------------------------------------------------
#
# Typed delta kinds published by the state layer's incremental mirrors
# (the tensor snapshot publishes one per mutation it absorbs).  The
# delta-solve engine (ops/deltasolve.py) consumes the SEQUENCE: an
# unchanged sequence number proves NOTHING changed (the O(1) warm
# check); on a changed sequence it goes straight to the exact content
# compare, which subsumes any kind-level filtering (every published
# kind can move availability).  The typed ring behind ``kinds_since``
# is the introspection surface — tests assert on it and operators can
# read what moved when debugging an unexpected cold solve.

DELTA_RESERVATION = "reservation"
DELTA_SOFT_RESERVATION = "soft-reservation"
DELTA_NODE = "node"
DELTA_NODE_STRUCTURE = "node-structure"
DELTA_POD = "pod"


@guarded_by("_lock", "_seq", "_ring")
class ChangeFeed:
    """Monotonic, bounded feed of typed state deltas.

    ``publish`` assigns the next sequence number under the lock; the
    sequence is the feed's only truth — consumers cache the seq they
    last verified against and treat an unchanged seq as proof of an
    unchanged world.  ``kinds_since`` answers "which delta kinds landed
    after seq" from a bounded ring, or ``None`` once seq has fallen off
    the ring; it exists for introspection (tests, debugging a cold
    solve), not invalidation — the engine's content compare already
    subsumes kind-level filtering."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._seq = 0
        # (seq, kind, key) — key is a debugging affordance, never
        # consulted for invalidation decisions
        self._ring: Deque[Tuple[int, str, Optional[str]]] = deque(
            maxlen=capacity
        )
        # optional wakeup Events set on every publish: the capacity
        # sampler and lifecycle ledger park on them so work happens
        # only on state change (Event.set is lock-free and idempotent
        # — safe under the publisher's mirror lock)
        self._wakeups: Tuple[Any, ...] = ()
        # happens-before channel key for the publish→wakeup edge; a
        # process-unique token, captured once, so a recycled object id
        # can never alias this feed's clock to another feed's
        self._hb_key = ("changefeed", racecheck.channel_token())

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def attach_wakeup(self, event) -> None:
        """Add a wakeup Event set on every publish.  Multi-listener:
        appends rather than replaces (wiring-time call)."""
        with self._lock:
            self._wakeups = self._wakeups + (event,)

    def publish(self, kind: str, key: Optional[str] = None) -> int:
        with self._lock:
            racecheck.note_access(self, "_seq")
            self._seq += 1
            self._ring.append((self._seq, kind, key))
            seq = self._seq
            wakeups = self._wakeups
        if wakeups:
            # Event.set is synchronization the lock tracker can't see:
            # record the publish→wakeup happens-before edge explicitly
            # (each waiter calls hb_observe on this channel)
            racecheck.hb_publish(self.hb_channel())
            for wakeup in wakeups:
                wakeup.set()
        return seq

    def hb_channel(self) -> tuple:
        """The happens-before channel key for this feed's publish →
        wakeup edge (racecheck.hb_observe after a wakeup-event wait)."""
        return self._hb_key

    def kinds_since(self, seq: int):
        """frozenset of delta kinds with sequence > seq, or None when
        the ring no longer reaches back that far."""
        with self._lock:
            if seq >= self._seq:
                return frozenset()
            oldest = self._ring[0][0] if self._ring else self._seq + 1
            if seq + 1 < oldest:
                return None
            return frozenset(k for s, k, _ in self._ring if s > seq)


def fnv32a(data: bytes) -> int:
    """FNV-1a 32-bit (hash/fnv), used for shard affinity."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


# maximum queued requests per shard before producers block / TryAdd fails
# (queue.go:22-27)
ASYNC_REQUEST_BUFFER_SIZE = 100


@guarded_by("_lock", "_inflight")
class ShardedUniqueQueue:
    """queue.go:34-128.

    Consumers receive zero-arg callables; invoking one releases the key's
    inflight marker and returns the Request — the store holds the latest
    object, the queue only records "there is a pending write".
    """

    def __init__(self, buckets: int, buffer_size: int = ASYNC_REQUEST_BUFFER_SIZE):
        self._queues: List[_queue.Queue] = [_queue.Queue(maxsize=buffer_size) for _ in range(buckets)]
        self._inflight: set[Key] = set()
        self._lock = threading.Lock()

    def add_if_absent(self, r: Request) -> None:
        """Blocking enqueue; dedupes create/update, never deletes
        (queue.go:63-68)."""
        added = self._add_to_inflight_if_absent(r.key)
        if added or r.type == DELETE:
            self._get_queue(r).put(self._release_func(r))

    def try_add_if_absent(self, r: Request) -> bool:
        """Non-blocking; False only when the shard is full (queue.go:74-92)."""
        added = self._add_to_inflight_if_absent(r.key)
        if added or r.type == DELETE:
            try:
                self._get_queue(r).put_nowait(self._release_func(r))
                return True
            except _queue.Full:
                if added:
                    self._delete_from_inflight(r.key)
                return False
        return True

    def get_consumers(self) -> List[_queue.Queue]:
        return list(self._queues)

    def queue_lengths(self) -> List[int]:
        return [q.qsize() for q in self._queues]

    def _get_queue(self, r: Request) -> _queue.Queue:
        return self._queues[self._bucket(r.key)]

    def _release_func(self, r: Request) -> Callable[[], Request]:
        # queue-handoff happens-before edge, carried INSIDE the item:
        # the consumer inherits the producer's clock exactly when the
        # enqueue succeeded (a Full shard drops the closure, so a failed
        # handoff can neither order nor hide anything)
        snapshot = racecheck.hb_snapshot()

        def release() -> Request:
            racecheck.hb_join(snapshot)
            self._delete_from_inflight(r.key)
            return r

        return release

    def _bucket(self, key: Key) -> int:
        return fnv32a(key[0].encode() + key[1].encode()) % len(self._queues)

    def _add_to_inflight_if_absent(self, key: Key) -> bool:
        with self._lock:
            racecheck.note_access(self, "_inflight")
            if key in self._inflight:
                return False
            self._inflight.add(key)
            return True

    def _delete_from_inflight(self, key: Key) -> None:
        with self._lock:
            racecheck.note_access(self, "_inflight")
            self._inflight.discard(key)
