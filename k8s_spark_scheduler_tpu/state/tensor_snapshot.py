"""Event-driven integer-tensor mirror of cluster state — the steady-state
fast path for `binpack: tpu-batch` at 10k-node scale.

The reference recomputes its scheduling snapshot from scratch on every
Filter request: GetReservedResources walks every reservation
(resourcereservations.go:258-263), GetOverhead walks every pod on every
candidate node (overhead.go:120-153), and NodeSchedulingMetadataForNodes
re-derives availability per node (resources.go:61-100) — all in
arbitrary-precision quantity arithmetic.  That is O(cluster) of host
work per request, which caps honest end-to-end latency long before the
device solve does.

This cache keeps the same state as O(delta)-updated int64 arrays:

- nodes: allocatable/zone/labels/ready from node informer events;
- reservation usage: per-node deltas from ResourceReservationCache and
  SoftReservationStore change observers (this process is the sole
  writer of both, so the mirror is exact);
- overhead: a pod table (requests, node, scheduler flag) from pod
  informer events plus a reserved-pod-name set maintained from the
  same reservation observers; per-request overhead is one vectorized
  segment-sum.

Exactness: every quantity is converted to base units once, at event
time; anything not exactly representable poisons the affected row and
``snapshot()`` reports exact=False so the caller falls back to the
Quantity path.  Decisions from this snapshot are bit-identical to the
slow path (tests/test_tensor_snapshot.py proves it on randomized
mutation sequences).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..analysis import racecheck
from ..ops.tensorize import _resources_to_base
from ..scheduler import labels as L
from ..scheduler.overhead import pod_to_resources
from ..types.objects import Node, Pod
from ..types.resources import ZONE_LABEL, ZONE_LABEL_PLACEHOLDER
from ..analysis.guarded import guarded_by
from .classindex import ClassIndex
from .store import (
    DELTA_NODE,
    DELTA_NODE_STRUCTURE,
    DELTA_POD,
    DELTA_RESERVATION,
    DELTA_SOFT_RESERVATION,
    ChangeFeed,
)

_GROW = 256


@dataclass
class TensorSnapshot:
    """A point-in-time view (copies — safe to use off-lock)."""

    names: List[str]                 # [N] node names
    allocatable: np.ndarray          # [N, 3] int64
    usage: np.ndarray                # [N, 3] int64 (hard + soft reservations)
    overhead: np.ndarray             # [N, 3] int64 (non-reservation pods)
    zone_names: List[str]
    zone_id: np.ndarray              # [N] int32
    ready: np.ndarray                # [N] bool
    unschedulable: np.ndarray        # [N] bool
    labels: List[Dict[str, str]]     # [N]
    exact: bool
    # nodes referenced by ≥1 (hard or soft) reservation — i.e. nodes that
    # would have an entry in GetReservedResources' usage map.  The
    # reschedule path's double-overhead quirk (resource.go:638-643)
    # applies only to such nodes, entry-ness included zero-valued
    # reservations, so a resource-row test cannot stand in for it.
    res_entries: np.ndarray          # [N] bool
    # lexicographic rank of each node's name among live nodes — an int
    # sort key equivalent to sorting the names themselves, maintained on
    # topology changes so per-request orderings never sort object arrays
    name_rank: np.ndarray            # [N] int64

    # (maintainer instance, structure revision): changes whenever the
    # node TABLE changes (add/remove/labels/zone/ready/unschedulable —
    # not usage), letting per-request consumers cache structure-derived
    # work (ops/fast_path._build_prep) across Filter requests
    structure_key: tuple = (-1, -1)

    # (maintainer instance, change-feed sequence): changes on EVERY
    # mutation the mirror absorbs — an equal content_key across two
    # snapshots proves their contents are identical, so consumers
    # (ops/deltasolve.py) can skip even the content compare
    content_key: tuple = (-1, -1)

    # (maintainer instance, XOR node-content digest) from the class
    # index (state/classindex.py): equal digests across snapshots of
    # the same mirror imply equal node rows up to 64-bit collisions —
    # the delta-solve engine's O(1) warm-basis tier between content_key
    # equality and the O(N) row compare.  Survives same-content churn
    # (a reserve+release pair cancels in the XOR) that content_key,
    # which counts every mutation, cannot.
    class_digest: tuple = (-1, -1)

    # class-structure revision: bumps only when the class MULTISET
    # changes, so class-derived caches survive same-class node churn
    class_rev: int = -1

    _name_index: Optional[Dict[str, int]] = None

    @property
    def avail(self) -> np.ndarray:
        return self.allocatable - self.usage - self.overhead

    @property
    def schedulable(self) -> np.ndarray:
        return self.allocatable - self.overhead

    @property
    def name_index(self) -> Dict[str, int]:
        """name → row, built once per snapshot (C-speed dict(zip))."""
        if self._name_index is None:
            self._name_index = dict(zip(self.names, range(len(self.names))))
        return self._name_index


_INSTANCE_SEQ = itertools.count()


@guarded_by("_lock", "_node_slot", "_pod_slot")
class TensorSnapshotCache:
    def __init__(self, node_informer, pod_informer, rr_cache, soft_store):
        self._lock = threading.RLock()
        self._exact = True
        # cache-instance id + structure revision (see TensorSnapshot.
        # structure_key); instance ids are process-unique so revisions
        # from different maintainers can never alias in consumer caches
        self._instance_id = next(_INSTANCE_SEQ)
        self._structure_rev = 0
        # snapshot()'s structure-derived parts, keyed by _structure_rev
        self._struct_cache = None
        # monotonic typed-delta feed: every mutation this mirror absorbs
        # publishes one delta (under the mirror lock, so a snapshot
        # taken under the same lock sees a consistent sequence); the
        # delta-solve engine keys its warm-path checks on the sequence
        self.feed = ChangeFeed()
        # equivalence-class index (ROADMAP 2): every node mutation below
        # renotes its one slot, keeping the class multiset, revision and
        # XOR content digest O(1)-current off the same deltas
        self.classes = ClassIndex()

        # node table
        self._node_slot: Dict[str, int] = {}
        self._node_names: List[Optional[str]] = []
        self._free_nodes: List[int] = []
        self._alloc = np.zeros((0, 3), dtype=np.int64)
        self._usage = np.zeros((0, 3), dtype=np.int64)
        self._res_count = np.zeros(0, dtype=np.int64)
        self._name_rank = np.zeros(0, dtype=np.int64)
        self._names_dirty = True
        self._node_overhead = np.zeros((0, 3), dtype=np.int64)
        self._zone_id = np.zeros(0, dtype=np.int32)
        self._ready = np.zeros(0, dtype=bool)
        self._unsched = np.zeros(0, dtype=bool)
        self._labels: List[Dict[str, str]] = []
        self._zone_names: List[str] = []
        self._zone_index: Dict[str, int] = {}
        # usage destined for nodes we don't (yet) know
        self._orphan_usage: Dict[str, np.ndarray] = {}
        self._orphan_res_count: Dict[str, int] = {}

        # pod table (for overhead)
        self._pod_slot: Dict[Tuple[str, str], int] = {}
        self._pod_requests = np.zeros((0, 3), dtype=np.int64)
        # node NAME per pod slot (resolved to a node slot at recompute
        # time: slots are reused on node churn and pods can be observed
        # before their node, so a stored slot index would go stale)
        self._pod_node_name: List[str] = []
        self._pod_active = np.zeros(0, dtype=bool)
        self._free_pods: List[int] = []
        # pods currently holding a reservation: (ns, name) from RR
        # status.pods; soft reservations track bare pod names (the
        # reference's soft lookup ignores namespace,
        # softreservations.go:133-151)
        self._reserved_pods: Set[Tuple[str, str]] = set()
        self._soft_reserved_names: Dict[str, int] = {}
        self._pod_key_of_slot: Dict[int, Tuple[str, str]] = {}
        self._pods_dirty = False

        node_informer.add_event_handler(
            on_add=self._on_node, on_update=lambda o, n: self._on_node(n),
            on_delete=self._on_node_delete,
        )
        pod_informer.add_event_handler(
            on_add=self._on_pod, on_update=lambda o, n: self._on_pod(n),
            on_delete=self._on_pod_delete,
        )
        rr_cache.add_change_observer(self._on_rr_change)
        soft_store.add_change_observer(self._on_soft_change)

    # -- node events ---------------------------------------------------------

    def _zone_of(self, labels: Dict[str, str]) -> int:
        zone = labels.get(ZONE_LABEL, ZONE_LABEL_PLACEHOLDER)
        idx = self._zone_index.get(zone)
        if idx is None:
            idx = len(self._zone_names)
            self._zone_index[zone] = idx
            self._zone_names.append(zone)
        return idx

    def _grow_nodes(self) -> int:
        n = len(self._node_names)
        extra = _GROW
        self._alloc = np.vstack([self._alloc, np.zeros((extra, 3), np.int64)])
        self._usage = np.vstack([self._usage, np.zeros((extra, 3), np.int64)])
        self._res_count = np.concatenate([self._res_count, np.zeros(extra, np.int64)])
        self._name_rank = np.concatenate([self._name_rank, np.zeros(extra, np.int64)])
        self._node_overhead = np.vstack(
            [self._node_overhead, np.zeros((extra, 3), np.int64)]
        )
        self._zone_id = np.concatenate([self._zone_id, np.zeros(extra, np.int32)])
        self._ready = np.concatenate([self._ready, np.zeros(extra, bool)])
        self._unsched = np.concatenate([self._unsched, np.zeros(extra, bool)])
        self._node_names.extend([None] * extra)
        self._labels.extend([{} for _ in range(extra)])
        self._free_nodes.extend(range(n + extra - 1, n - 1, -1))
        return self._free_nodes.pop()

    def _on_node(self, node: Node) -> None:
        with self._lock:
            racecheck.note_access(self, "_node_slot")
            slot = self._node_slot.get(node.name)
            new_zone = self._zone_of(node.labels)
            if slot is None or (
                self._labels[slot] != node.labels
                or self._zone_id[slot] != new_zone
                or bool(self._ready[slot]) != node.ready
                or bool(self._unsched[slot]) != node.unschedulable
            ):
                # structural change only: allocatable/status heartbeats
                # must not invalidate structure-keyed consumer caches
                self._structure_rev += 1
                self.feed.publish(DELTA_NODE_STRUCTURE, node.name)
            else:
                self.feed.publish(DELTA_NODE, node.name)
            if slot is None:
                slot = self._free_nodes.pop() if self._free_nodes else self._grow_nodes()
                self._node_slot[node.name] = slot
                self._node_names[slot] = node.name
                self._names_dirty = True
                pending = self._orphan_usage.pop(node.name, None)
                self._usage[slot] = pending if pending is not None else 0
                self._res_count[slot] = self._orphan_res_count.pop(node.name, 0)
            row, exact = _resources_to_base(node.allocatable)
            if not exact:
                self._exact = False
            self._alloc[slot] = row
            self._zone_id[slot] = new_zone
            self._ready[slot] = node.ready
            self._unsched[slot] = node.unschedulable
            self._labels[slot] = dict(node.labels)
            self._note_class(slot, labels=node.labels)

    def _on_node_delete(self, node: Node) -> None:
        with self._lock:
            racecheck.note_access(self, "_node_slot")
            self._structure_rev += 1
            self.feed.publish(DELTA_NODE_STRUCTURE, node.name)
            slot = self._node_slot.pop(node.name, None)
            if slot is None:
                return
            # park any remaining usage so a node re-add restores it
            if self._usage[slot].any():
                self._orphan_usage[node.name] = self._usage[slot].copy()
            if self._res_count[slot]:
                self._orphan_res_count[node.name] = int(self._res_count[slot])
            self._node_names[slot] = None
            self._names_dirty = True
            self._alloc[slot] = 0
            self._usage[slot] = 0
            self._res_count[slot] = 0
            self._node_overhead[slot] = 0
            self._ready[slot] = False
            self._labels[slot] = {}
            self._free_nodes.append(slot)
            self._pods_dirty = True
            self.classes.drop_node(slot)

    def _note_class(self, slot: int,
                    labels: Optional[Dict[str, str]] = None) -> None:
        """Mirror one slot's full row into the equivalence-class index
        (O(1); callers hold ``self._lock``).  Overhead is recomputed
        lazily at snapshot() — until then the index sees the previous
        overhead row, and _recompute_overhead re-notes whatever changed,
        so by the time snapshot() stamps class_digest the index is
        consistent with the rows it hands out."""
        name = self._node_names[slot]
        if name is None:
            return
        overhead = (
            self._node_overhead[slot]
            if slot < len(self._node_overhead)
            else np.zeros(3, np.int64)
        )
        self.classes.note_node(
            slot,
            name,
            self._alloc[slot],
            self._usage[slot],
            overhead,
            int(self._zone_id[slot]),
            bool(self._ready[slot]),
            bool(self._unsched[slot]),
            res_count=int(self._res_count[slot]),
            labels=labels,
        )

    # -- reservation usage ---------------------------------------------------

    def _apply_usage(self, node: str, row: np.ndarray, sign: int) -> None:
        # each call is one reservation contribution: the entry count
        # tracks whether the node would appear in GetReservedResources'
        # usage map at all (even with zero-valued rows)
        # like the usage row, the count is NOT clamped: a transient
        # minus-before-plus imbalance must cancel exactly when the
        # matching event arrives, or entry-ness would desync from the
        # reserved-resources map permanently
        slot = self._node_slot.get(node)
        if slot is not None:
            self._usage[slot] += sign * row
            self._res_count[slot] += sign
            self._note_class(slot)
        else:
            current = self._orphan_usage.get(node)
            if current is None:
                current = np.zeros(3, np.int64)
            self._orphan_usage[node] = current + sign * row
            self._orphan_res_count[node] = self._orphan_res_count.get(node, 0) + sign

    @staticmethod
    def _rr_rows(rr) -> Dict[str, np.ndarray]:
        """node → summed base-unit rows for one reservation object."""
        rows: Dict[str, np.ndarray] = {}
        for reservation in rr.spec.reservations.values():
            row, _ = _resources_to_base(reservation.resources_value())
            arr = rows.get(reservation.node)
            if arr is None:
                rows[reservation.node] = np.array(row, np.int64)
            else:
                rows[reservation.node] = arr + np.array(row, np.int64)
        return rows

    def _on_rr_change(self, old, new) -> None:
        with self._lock:
            if old is not None:
                for node, row in self._rr_rows(old).items():
                    self._apply_usage(node, row, -1)
                for pod_name in old.status.pods.values():
                    self._reserved_pods.discard((old.namespace, pod_name))
            if new is not None:
                for reservation in new.spec.reservations.values():
                    _, e = _resources_to_base(reservation.resources_value())
                    if not e:
                        self._exact = False
                for node, row in self._rr_rows(new).items():
                    self._apply_usage(node, row, +1)
                for pod_name in new.status.pods.values():
                    self._reserved_pods.add((new.namespace, pod_name))
            self._pods_dirty = True
            ref = new if new is not None else old
            self.feed.publish(
                DELTA_RESERVATION, ref.name if ref is not None else None
            )

    def _on_soft_change(self, node: str, resources, sign: int, pod_name: str) -> None:
        with self._lock:
            row, exact = _resources_to_base(resources)
            if not exact:
                self._exact = False
            self._apply_usage(node, np.array(row, np.int64), sign)
            count = self._soft_reserved_names.get(pod_name, 0) + sign
            if count <= 0:
                self._soft_reserved_names.pop(pod_name, None)
            else:
                self._soft_reserved_names[pod_name] = count
            self._pods_dirty = True
            self.feed.publish(DELTA_SOFT_RESERVATION, pod_name)

    # -- pod table (overhead) ------------------------------------------------

    def _grow_pods(self) -> int:
        n = len(self._pod_active)
        extra = _GROW
        self._pod_requests = np.vstack([self._pod_requests, np.zeros((extra, 3), np.int64)])
        self._pod_node_name.extend([""] * extra)
        self._pod_active = np.concatenate([self._pod_active, np.zeros(extra, bool)])
        self._free_pods.extend(range(n + extra - 1, n - 1, -1))
        return self._free_pods.pop()

    def _on_pod(self, pod: Pod) -> None:
        with self._lock:
            racecheck.note_access(self, "_pod_slot")
            key = (pod.namespace, pod.name)
            slot = self._pod_slot.get(key)
            if pod.node_name == "":
                if slot is not None:
                    self._pod_active[slot] = False
                    self._pods_dirty = True
                    self.feed.publish(DELTA_POD, pod.name)
                # a nodeless pod the mirror never tracked changes no
                # state: queued-driver heartbeats must not churn the
                # content sequence (they arrive on every Filter cycle)
                return
            if slot is None:
                slot = self._free_pods.pop() if self._free_pods else self._grow_pods()
                self._pod_slot[key] = slot
                self._pod_key_of_slot[slot] = key
            row, exact = _resources_to_base(pod_to_resources(pod))
            if not exact:
                self._exact = False
            self._pod_requests[slot] = row
            self._pod_node_name[slot] = pod.node_name
            self._pod_active[slot] = True
            self.feed.publish(DELTA_POD, pod.name)
            if pod.labels.get(L.SPARK_ROLE_LABEL) == L.EXECUTOR and pod.is_terminated():
                # terminated pods keep informer entries but the reference
                # counts them via the lister; overhead counts any pod whose
                # entry exists — parity is with overhead.go which relies on
                # delete events, so keep the pod until deletion
                pass
            self._pods_dirty = True

    def _on_pod_delete(self, pod: Pod) -> None:
        with self._lock:
            racecheck.note_access(self, "_pod_slot")
            slot = self._pod_slot.pop((pod.namespace, pod.name), None)
            if slot is not None:
                self._pod_active[slot] = False
                self._pod_node_name[slot] = ""
                self._pod_key_of_slot.pop(slot, None)
                self._free_pods.append(slot)
                self._pods_dirty = True
            was_reserved = (pod.namespace, pod.name) in self._reserved_pods
            self._reserved_pods.discard((pod.namespace, pod.name))
            if slot is not None or was_reserved:
                self.feed.publish(DELTA_POD, pod.name)

    # -- snapshot ------------------------------------------------------------

    def _recompute_overhead(self) -> None:
        n_nodes = len(self._node_names)
        overhead = np.zeros((n_nodes, 3), dtype=np.int64)
        active = np.flatnonzero(self._pod_active)
        if len(active):
            # reserved pods don't count (overhead.go:139-141; soft
            # reservations match by bare pod name like the reference)
            mask = np.fromiter(
                (
                    (key := self._pod_key_of_slot.get(int(slot), ("", ""))) not in self._reserved_pods
                    and key[1] not in self._soft_reserved_names
                    for slot in active
                ),
                dtype=bool,
                count=len(active),
            )
            counted = active[mask]
            node_idx = np.fromiter(
                (
                    self._node_slot.get(self._pod_node_name[int(slot)], -1)
                    for slot in counted
                ),
                dtype=np.int64,
                count=len(counted),
            )
            ok = node_idx >= 0
            np.add.at(overhead, node_idx[ok], self._pod_requests[counted][ok])
        old = self._node_overhead
        if len(old) < n_nodes:
            pad = np.zeros((n_nodes - len(old), 3), np.int64)
            old = np.vstack([old, pad]) if len(old) else pad
        changed = np.flatnonzero((old[:n_nodes] != overhead).any(axis=1))
        self._node_overhead = overhead
        self._pods_dirty = False
        # overhead shifted under some nodes: bring their class-index rows
        # up to date (class KEY never depends on overhead, so this only
        # refreshes content hashes — class_rev is untouched)
        for slot in changed:
            self._note_class(int(slot))

    def _recompute_name_ranks(self) -> None:
        live = [i for i, name in enumerate(self._node_names) if name is not None]
        order = sorted(live, key=lambda i: self._node_names[i])
        for rank, slot in enumerate(order):
            self._name_rank[slot] = rank
        self._names_dirty = False

    def snapshot(self) -> TensorSnapshot:
        with self._lock:
            if self._pods_dirty:
                self._recompute_overhead()
            if self._names_dirty:
                self._recompute_name_ranks()
            # structure-derived parts (the Python-loop costs: live-slot
            # scan + 10k-element name/label lists) are cached per
            # structure revision — every mutation of names, labels,
            # zones, ready or unschedulable bumps _structure_rev
            # (_on_node/_on_node_delete), so a cache hit can only serve
            # identical structure.  The cached numpy rows are .copy()s,
            # never views, so later in-place maintainer writes (which
            # bump the rev) cannot reach snapshots already handed out.
            sc = self._struct_cache
            if sc is None or sc[0] != self._structure_rev:
                live = [
                    i for i, name in enumerate(self._node_names) if name is not None
                ]
                idx = np.array(live, dtype=np.int64)
                if len(idx) == 0:
                    idx = np.zeros(0, dtype=np.int64)
                sc = (
                    self._structure_rev,
                    idx,
                    [self._node_names[i] for i in live],
                    # label dicts are replaced (never mutated) on node
                    # events, so sharing the references is safe
                    [self._labels[i] for i in live],
                    list(self._zone_names),
                    self._zone_id[idx].copy(),
                    self._ready[idx].copy(),
                    self._unsched[idx].copy(),
                    self._name_rank[idx].copy(),
                )
                self._struct_cache = sc
            _, idx, names, labels, zone_names, zone_id, ready, unsched, ranks = sc
            return TensorSnapshot(
                names=names,
                allocatable=self._alloc[idx].copy(),
                usage=self._usage[idx].copy(),
                overhead=self._node_overhead[idx].copy()
                if len(self._node_overhead) >= len(self._node_names)
                else np.zeros((len(names), 3), np.int64),
                zone_names=zone_names,
                zone_id=zone_id,
                ready=ready,
                unschedulable=unsched,
                labels=labels,
                exact=self._exact,
                res_entries=self._res_count[idx] > 0,  # comparison allocates fresh
                name_rank=ranks,
                structure_key=(self._instance_id, self._structure_rev),
                # feed.seq is stable here: every publisher holds this
                # mirror's lock, which snapshot() also holds
                content_key=(self._instance_id, self.feed.seq),
                # all class-index mutators run under this lock too, so
                # the digest/rev pair is consistent with the rows above
                class_digest=(self._instance_id, self.classes.digest),
                class_rev=self.classes.class_rev,
            )
