"""Typed write-back caches: ResourceReservations and Demands.

internal/cache/resourcereservations.go (5 writer shards, seeds from the
lister at boot) and demands.go + safedemands.go (the Safe wrapper no-ops
until the Demand CRD exists, then lazily constructs the cache when the
LazyDemandInformer fires).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..analysis.guarded import guarded_by
from ..kube.apiserver import APIServer
from ..kube.crd import DEMAND_CRD_NAME
from ..kube.informer import Informer, InformerFactory
from ..types.objects import Demand, ResourceReservation
from .cache import AsyncClient, TypedClient, WriteBackCache
from .store import ObjectStore, ShardedUniqueQueue

RESERVATION_WRITER_SHARDS = 5  # resourcereservations.go:29-34
DEMAND_WRITER_SHARDS = 5


class ResourceReservationCache:
    """internal/cache/resourcereservations.go:40-138.

    on_change(old, new) observers fire on every local mutation and on
    informer deletes (old/new None for create/delete) — the tensor
    snapshot cache uses them to maintain usage deltas incrementally.
    """

    def __init__(
        self,
        api: APIServer,
        informer: Informer,
        max_retry_count: int = 5,
        rate_bucket=None,
        breaker=None,
        journal=None,
        registry=None,
    ):
        self._queue = ShardedUniqueQueue(RESERVATION_WRITER_SHARDS)
        self._store = ObjectStore()
        # seed from the lister so state survives restarts
        # (resourcereservations.go:53-60)
        for obj in informer.list():
            self._store.put_if_absent(obj)
        self._cache = WriteBackCache(self._queue, self._store, informer)
        client = TypedClient(api, ResourceReservation.KIND)
        if rate_bucket is not None:
            from ..kube.ratelimit import RateLimitedClient

            client = RateLimitedClient(client, rate_bucket)
        from ..types import serde

        self._journal = journal
        self._async = AsyncClient(
            client,
            self._queue,
            self._store,
            max_retry_count,
            breaker=breaker,
            journal=journal,
            kind=ResourceReservation.KIND,
            to_wire=serde.rr_to_dict_v1beta2,
            registry=registry,
        )

    def install_fence(self, gate) -> None:
        """HA wiring: fence every reservation write-back (and journal
        ack) behind the given :class:`~..ha.fencing.FencedWriter`, and
        stamp journal records with the holder's epoch."""
        self._async.fence_gate = gate
        if self._journal is not None:
            self._journal.fence_gate = gate
            self._journal.epoch_source = gate.fence.epoch

    def add_change_observer(self, fn) -> None:
        """fn(old, new) on every semantic content change of the LOCAL
        store — local writes, informer deletes, and informer inserts
        alike (store-level observation, so incremental mirrors can never
        drift from what reads observe)."""
        self._store.add_content_observer(fn)

    def run(self) -> None:
        self._async.run()

    def stop(self) -> None:
        self._async.stop()

    def create(self, rr: ResourceReservation) -> None:
        self._cache.create(rr)

    def update(self, rr: ResourceReservation) -> None:
        self._cache.update(rr)

    def delete(self, namespace: str, name: str) -> None:
        self._cache.delete(namespace, name)

    def get(self, namespace: str, name: str) -> Optional[ResourceReservation]:
        return self._cache.get(namespace, name)

    def list(self) -> List[ResourceReservation]:
        return self._cache.list()

    def inflight_queue_lengths(self) -> List[int]:
        return self._queue.queue_lengths()

    # -- resilience: intent-journal recovery ---------------------------------

    def journal_depth(self) -> int:
        return self._journal.depth() if self._journal is not None else 0

    def nudge_recovery(self, force: bool = False) -> int:
        """Re-enqueue journaled reservation intents when a write could
        land again (see AsyncClient.nudge_recovery)."""
        return self._async.nudge_recovery(force=force)

    def recover_from_journal(self) -> int:
        """Failover replay: apply intents journaled by a PREVIOUS
        scheduler instance against this instance's lister-seeded store.
        Exactly-once at the CRD level: intents whose write already
        landed (the lister saw the object) — or whose object has since
        been GC'd — are acked without a write; only genuinely-unlanded
        intents are enqueued.  Returns the number of intents enqueued."""
        if self._journal is None or self._journal.depth() == 0:
            return 0
        from ..types import serde
        from .store import create_request, update_request

        enqueued = 0
        for intent in self._journal.pending():
            if intent.get("kind") not in (None, ResourceReservation.KIND):
                # defense: a journal file shared with another intent
                # class (e.g. policy evictions) must not be replayed as
                # reservation writes — foreign kinds are left pending
                # for their own recoverer
                continue
            key = (intent["ns"], intent["name"])
            op = intent["op"]
            existing = self._store.get(key)
            if op == "delete":
                if existing is not None:
                    self._cache.delete(key[0], key[1])
                    enqueued += 1
                else:
                    self._journal.ack(op, key[0], key[1])
                continue
            if op == "create" and existing is not None:
                # landed before the old instance died; lister seeded it
                self._journal.ack(op, key[0], key[1])
                continue
            wire = intent.get("obj")
            if not wire:
                self._journal.ack(op, key[0], key[1])
                continue
            obj = serde.rr_from_dict_v1beta2(wire)
            if existing is None:
                # covers updates whose create was collapsed into them
                # while diverted: recreate from the journaled wire copy.
                # If the owning driver died meanwhile, the API server's
                # dangling-owner GC collects the recreated object.
                self._store.put_if_absent(obj)
                self._queue.add_if_absent(create_request(obj))
            else:
                # the old instance was the sole writer: its journaled
                # content is the newest intended state
                self._store.put(obj)
                self._queue.add_if_absent(update_request(obj))
            enqueued += 1
        return enqueued


class DemandCache:
    """internal/cache/demands.go:40-117."""

    def __init__(
        self,
        api: APIServer,
        informer: Informer,
        max_retry_count: int = 5,
        rate_bucket=None,
        registry=None,
    ):
        self._queue = ShardedUniqueQueue(DEMAND_WRITER_SHARDS)
        self._store = ObjectStore()
        for obj in informer.list():
            self._store.put_if_absent(obj)
        self._cache = WriteBackCache(self._queue, self._store, informer)
        client = TypedClient(api, Demand.KIND)
        if rate_bucket is not None:
            from ..kube.ratelimit import RateLimitedClient

            client = RateLimitedClient(client, rate_bucket)
        self._async = AsyncClient(
            client,
            self._queue,
            self._store,
            max_retry_count,
            kind=Demand.KIND,
            registry=registry,
        )

    def install_fence(self, gate) -> None:
        self._async.fence_gate = gate

    def run(self) -> None:
        self._async.run()

    def stop(self) -> None:
        self._async.stop()

    def create(self, demand: Demand) -> None:
        self._cache.create(demand)

    def delete(self, namespace: str, name: str) -> None:
        self._cache.delete(namespace, name)

    def get(self, namespace: str, name: str) -> Optional[Demand]:
        return self._cache.get(namespace, name)

    def list(self) -> List[Demand]:
        return self._cache.list()

    def inflight_queue_lengths(self) -> List[int]:
        return self._queue.queue_lengths()


@guarded_by("_callback_lock", "_callbacks")
class LazyDemandInformer:
    """internal/crd/demand_informer.go:40-138: polls for the Demand CRD to
    become Established, then starts the informer and signals ready."""

    def __init__(
        self,
        api: APIServer,
        informer_factory: InformerFactory,
        poll_interval: float = 60.0,
    ):
        self._api = api
        self._factory = informer_factory
        self._poll_interval = poll_interval
        self._ready = threading.Event()
        self._callbacks: List[Callable[[], None]] = []
        self._callback_lock = threading.Lock()
        self._informer: Optional[Informer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._check_crd():
            self._become_ready()
            return
        self._thread = threading.Thread(target=self._poll, daemon=True, name="lazy-demand-informer")
        self._thread.start()

    def ready(self) -> bool:
        return self._ready.is_set()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def on_ready(self, callback: Callable[[], None]) -> None:
        with self._callback_lock:
            if not self._ready.is_set():
                self._callbacks.append(callback)
                return
        callback()

    def informer(self) -> Optional[Informer]:
        return self._informer

    def _poll(self) -> None:
        while not self._ready.is_set():
            if self._check_crd():
                self._become_ready()
                return
            time.sleep(self._poll_interval)

    def _check_crd(self) -> bool:
        return self._api.crd_established(DEMAND_CRD_NAME)

    def _become_ready(self) -> None:
        informer = self._factory.informer(Demand.KIND)
        if not informer.has_synced():
            informer.start()
        self._informer = informer
        # run callbacks BEFORE signalling ready: a waiter woken by
        # wait_ready() must observe downstream constructions (e.g. the
        # SafeDemandCache delegate) already in place.  The callback lock
        # closes the register-vs-become-ready race: anyone who saw
        # ready=False under the lock is in the list we drain here.
        while True:
            with self._callback_lock:
                callbacks, self._callbacks = self._callbacks, []
                if not callbacks:
                    self._ready.set()
                    return
            for callback in callbacks:
                callback()


@guarded_by("_lock", "_delegate")
class SafeDemandCache:
    """internal/cache/safedemands.go:31-127: degrades to a no-op until the
    Demand CRD exists."""

    def __init__(
        self,
        lazy_informer: LazyDemandInformer,
        api: APIServer,
        max_retry_count: int = 5,
        rate_bucket=None,
        registry=None,
    ):
        self._lazy = lazy_informer
        self._api = api
        self._max_retry_count = max_retry_count
        self._rate_bucket = rate_bucket
        self._registry = registry
        self._fence_gate = None
        self._delegate: Optional[DemandCache] = None
        self._lock = threading.Lock()
        lazy_informer.on_ready(self._construct)

    def install_fence(self, gate) -> None:
        """HA wiring; applied immediately when the delegate exists, or
        at lazy construction otherwise."""
        with self._lock:
            self._fence_gate = gate
            if self._delegate is not None:
                self._delegate.install_fence(gate)

    def _construct(self) -> None:
        with self._lock:
            if self._delegate is None:
                cache = DemandCache(
                    self._api,
                    self._lazy.informer(),
                    self._max_retry_count,
                    rate_bucket=self._rate_bucket,
                    registry=self._registry,
                )
                if self._fence_gate is not None:
                    cache.install_fence(self._fence_gate)
                cache.run()
                self._delegate = cache

    def crd_exists(self) -> bool:
        if self._delegate is not None:
            return True
        return self._lazy.ready()

    def create(self, demand: Demand) -> None:
        if self._delegate is not None:
            self._delegate.create(demand)

    def delete(self, namespace: str, name: str) -> None:
        if self._delegate is not None:
            self._delegate.delete(namespace, name)

    def get(self, namespace: str, name: str) -> Optional[Demand]:
        if self._delegate is not None:
            return self._delegate.get(namespace, name)
        return None

    def list(self) -> List[Demand]:
        if self._delegate is not None:
            return self._delegate.list()
        return []

    def stop(self) -> None:
        if self._delegate is not None:
            self._delegate.stop()

    def inflight_queue_lengths(self) -> List[int]:
        if self._delegate is not None:
            return self._delegate.inflight_queue_lengths()
        return []
