"""Fake cluster autoscaler for end-to-end flows (the reference's demand
consumer is an external scaler watching the Demand CRD, SURVEY §1).

Watches Demands on the embedded API server; for each pending demand it
adds nodes sized to the demand units (in the demanded zone when
enforce_single_zone_scheduling is set) and marks the demand fulfilled —
driving the same phase transitions the waste reporter and demand GC key
on.

Two knobs model real autoscaler behavior instead of instant infinite
capacity:

- ``fulfillment_delay`` (seconds, on the :mod:`..timesource` clock):
  a demand only becomes eligible ``delay`` after it is observed.
  Delayed demands queue in ``pending`` and are provisioned by
  :meth:`process_due` — the discrete-event simulator pumps this at
  virtual due-times; wall-clock tests call it directly.
- ``max_nodes``: a hard cap on nodes this autoscaler will ever create.
  A demand whose first-fit provisioning would exceed the cap is left
  pending (a real bounded ASG does not partially help a gang) and
  counted in ``capped``.

Node names come from a per-instance counter so runs are deterministic
regardless of construction order elsewhere in the process.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from .. import timesource
from ..kube.apiserver import APIServer
from ..kube.errors import NotFoundError
from ..kube.informer import Informer
from ..types.objects import Demand, DemandPhase, Node, ObjectMeta
from ..types.resources import ZONE_LABEL, Resources
from ..analysis.guarded import guarded_by


@dataclass(eq=False)  # identity equality: two queued demands may carry equal payloads
class _PendingDemand:
    due: float
    namespace: str
    name: str
    zone: str
    instance_group: str
    # (resources, count) per unit, captured at observation time
    units: List = field(default_factory=list)


@guarded_by("_lock", "pending", "fulfilled", "created_nodes", "capped")
class FakeAutoscaler:
    def __init__(
        self,
        api: APIServer,
        demand_informer: Informer,
        node_cpu: str = "16",
        node_memory: str = "32Gi",
        node_gpu: str = "0",
        instance_group_label: str = "resource_channel",
        default_zone: str = "zone1",
        fulfillment_delay: float = 0.0,
        max_nodes: Optional[int] = None,
        deferred: bool = False,
        name_prefix: str = "scaled",
    ):
        self._api = api
        self._node_cpu = node_cpu
        self._node_memory = node_memory
        self._node_gpu = node_gpu
        self._instance_group_label = instance_group_label
        self._default_zone = default_zone
        self._delay = fulfillment_delay
        self._max_nodes = max_nodes
        # deferred=True forces even zero-delay demands through the
        # pending queue: fulfillment then happens only at explicit
        # process_due() pumps, in sorted order — the determinism the
        # simulator needs (watch events arrive from racing write-back
        # shards, so inline fulfillment order is scheduling-dependent)
        self._deferred = deferred or fulfillment_delay > 0
        self._name_prefix = name_prefix
        self._counter = itertools.count(1)
        self._lock = threading.Lock()
        self.fulfilled: list[str] = []
        self.pending: list[_PendingDemand] = []
        self.created_nodes = 0
        self.capped: list[str] = []
        demand_informer.add_event_handler(on_add=self._on_demand)

    # -- intake ---------------------------------------------------------------

    def _on_demand(self, demand: Demand) -> None:
        with self._lock:
            if demand.status.phase == DemandPhase.FULFILLED:
                return
            if self._deferred:
                self.pending.append(
                    _PendingDemand(
                        due=timesource.now() + self._delay,
                        namespace=demand.namespace,
                        name=demand.name,
                        zone=demand.spec.zone or self._default_zone,
                        instance_group=demand.spec.instance_group,
                        units=[(u.resources, u.count) for u in demand.spec.units],
                    )
                )
                return
            self._fulfill(
                demand.namespace,
                demand.name,
                demand.spec.zone or self._default_zone,
                demand.spec.instance_group,
                [(u.resources, u.count) for u in demand.spec.units],
            )

    # -- delayed pump ---------------------------------------------------------

    def due_times(self) -> List[float]:
        """Due instants of still-pending demands (for the sim to turn
        into clock events)."""
        with self._lock:
            return sorted({p.due for p in self.pending})

    def process_due(self, now: Optional[float] = None) -> int:
        """Fulfill every pending demand whose delay has elapsed at
        ``now`` (timesource.now() when omitted), in (due, namespace,
        name) order.  Returns the number of demands fulfilled."""
        if now is None:
            now = timesource.now()
        with self._lock:
            due = [p for p in self.pending if p.due <= now]
            if not due:
                return 0
            due.sort(key=lambda p: (p.due, p.namespace, p.name))
            fulfilled = 0
            due_ids = {id(p) for p in due}
            remaining = [p for p in self.pending if id(p) not in due_ids]
            for p in due:
                if self._fulfill(p.namespace, p.name, p.zone, p.instance_group, p.units):
                    fulfilled += 1
                # capped demands stay pending: a later cordon-lift or a
                # raised cap (not modeled) would retry them; dropping
                # them silently would under-report scale-up pressure
                elif self._demand_still_open(p.namespace, p.name):
                    remaining.append(p)
            self.pending = remaining
            return fulfilled

    def _demand_still_open(self, namespace: str, name: str) -> bool:
        try:
            fresh = self._api.get(Demand.KIND, namespace, name)
        except NotFoundError:
            return False
        return fresh.status.phase != DemandPhase.FULFILLED

    # -- provisioning ---------------------------------------------------------

    def _fulfill(self, namespace, name, zone, instance_group, units) -> bool:
        """First-fit the demand units onto fresh nodes and mark the
        demand fulfilled.  Always called with self._lock held."""
        node_capacity = Resources.of(self._node_cpu, self._node_memory, self._node_gpu)
        # first-fit the demand units onto fresh nodes: summed-demand
        # division under-provisions when unit sizes don't divide node
        # capacity (a 10-cpu unit only fits once on a 16-cpu node)
        free: list[Resources] = []
        for resources, count in units:
            for _ in range(count):
                placed = False
                for i, avail in enumerate(free):
                    if not resources.greater_than(avail):
                        free[i] = avail.sub(resources)
                        placed = True
                        break
                if not placed:
                    free.append(node_capacity.sub(resources))
        needed = max(len(free), 1)
        if self._max_nodes is not None and self.created_nodes + needed > self._max_nodes:
            if name not in self.capped:
                self.capped.append(name)  # schedlint: disable=LK001 -- _fulfill is always called with _lock held (see docstring)
            return False
        for _ in range(needed):
            self._api.create(
                Node(
                    meta=ObjectMeta(
                        name=f"{self._name_prefix}-{next(self._counter)}",
                        labels={
                            ZONE_LABEL: zone,
                            self._instance_group_label: instance_group,
                        },
                    ),
                    allocatable=node_capacity,
                )
            )
        self.created_nodes += needed  # schedlint: disable=LK001 -- _fulfill is always called with _lock held (see docstring)
        try:
            fresh = self._api.get(Demand.KIND, namespace, name)
        except NotFoundError:
            # demand deleted while queued (pod scheduled anyway): the
            # nodes stay (real autoscalers don't roll back either)
            return True
        fresh.status.phase = DemandPhase.FULFILLED
        fresh.status.fulfilled_zone = zone
        self._api.update(fresh)
        self.fulfilled.append(name)  # schedlint: disable=LK001 -- _fulfill is always called with _lock held (see docstring)
        return True
