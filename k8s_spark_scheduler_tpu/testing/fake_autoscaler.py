"""Fake cluster autoscaler for end-to-end flows (the reference's demand
consumer is an external scaler watching the Demand CRD, SURVEY §1).

Watches Demands on the embedded API server; for each pending demand it
adds nodes sized to the demand units (in the demanded zone when
enforce_single_zone_scheduling is set) and marks the demand fulfilled —
driving the same phase transitions the waste reporter and demand GC key
on.
"""

from __future__ import annotations

import itertools
import threading
from ..kube.apiserver import APIServer
from ..kube.informer import Informer
from ..types.objects import Demand, DemandPhase, Node, ObjectMeta
from ..types.resources import ZONE_LABEL, Resources

_counter = itertools.count(1)


class FakeAutoscaler:
    def __init__(
        self,
        api: APIServer,
        demand_informer: Informer,
        node_cpu: str = "16",
        node_memory: str = "32Gi",
        node_gpu: str = "0",
        instance_group_label: str = "resource_channel",
        default_zone: str = "zone1",
    ):
        self._api = api
        self._node_cpu = node_cpu
        self._node_memory = node_memory
        self._node_gpu = node_gpu
        self._instance_group_label = instance_group_label
        self._default_zone = default_zone
        self._lock = threading.Lock()
        self.fulfilled: list[str] = []
        demand_informer.add_event_handler(on_add=self._on_demand)

    def _on_demand(self, demand: Demand) -> None:
        with self._lock:
            if demand.status.phase == DemandPhase.FULFILLED:
                return
            zone = demand.spec.zone or self._default_zone
            node_capacity = Resources.of(self._node_cpu, self._node_memory, self._node_gpu)
            # first-fit the demand units onto fresh nodes: summed-demand
            # division under-provisions when unit sizes don't divide node
            # capacity (a 10-cpu unit only fits once on a 16-cpu node)
            needed = 1
            free: list[Resources] = []
            for unit in demand.spec.units:
                for _ in range(unit.count):
                    placed = False
                    for i, avail in enumerate(free):
                        if not unit.resources.greater_than(avail):
                            free[i] = avail.sub(unit.resources)
                            placed = True
                            break
                    if not placed:
                        free.append(node_capacity.sub(unit.resources))
            needed = max(len(free), 1)
            for _ in range(needed):
                self._api.create(
                    Node(
                        meta=ObjectMeta(
                            name=f"scaled-{next(_counter)}",
                            labels={
                                ZONE_LABEL: zone,
                                self._instance_group_label: demand.spec.instance_group,
                            },
                        ),
                        allocatable=node_capacity,
                    )
                )
            fresh = self._api.get(Demand.KIND, demand.namespace, demand.name)
            fresh.status.phase = DemandPhase.FULFILLED
            fresh.status.fulfilled_zone = zone
            self._api.update(fresh)
            self.fulfilled.append(demand.name)
