"""Recorded-wire fake Kubernetes API server.

Speaks the REST + watch subset of the k8s API over real HTTP, backed by
the embedded ``kube/apiserver.py`` store — so the REST backend
(``kube/restbackend.py``) can be exercised against genuine wire shapes
(metav1.Status errors, JSON-lines watch streams, 410 Gone after history
truncation, apiextensions/v1 CRDs) without a cluster.  The reference
takes the equivalent shortcut with client-go fake clientsets
(``extendertest/extender_test_utils.go:70-72``); this fake goes one
layer lower so the HTTP client, serde, and reflector loops are under
test too.

Supported surface:
- core/v1 pods (namespaced) and nodes (cluster-scoped)
- sparkscheduler.palantir.com/v1beta2 resourcereservations
- scaler.palantir.com/v1alpha2 demands
- apiextensions.k8s.io/v1 customresourcedefinitions (status carries the
  Established condition from the embedded registry)
- ``?watch=1`` streams with resourceVersion resume and configurable
  event-history retention: a resume RV older than retained history gets
  410 Gone (exercising the backend's relist-and-diff path)
"""

from __future__ import annotations

import json
import re
import threading
from collections import defaultdict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..kube import apiserver as emb
from ..kube.errors import (
    AlreadyExistsError,
    APIError,
    ConflictError,
    NamespaceTerminatingError,
    NotFoundError,
)
from ..kube.restbackend import _RESOURCES, RestAPIServer
from ..analysis.guarded import guarded_by

_PATHS = {
    ("", "v1", "pods"): "Pod",
    ("", "v1", "nodes"): "Node",
    ("sparkscheduler.palantir.com", "v1beta2", "resourcereservations"): "ResourceReservation",
    ("scaler.palantir.com", "v1alpha2", "demands"): "Demand",
}

_ITEM_RE = re.compile(
    r"^/(?:api/(?P<corev>v1)|apis/(?P<group>[^/]+)/(?P<ver>[^/]+))"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<sub>status))?$"
)


def _status(code: int, reason: str, message: str, details: Optional[dict] = None) -> dict:
    out = {
        "apiVersion": "v1",
        "kind": "Status",
        "status": "Failure",
        "code": code,
        "reason": reason,
        "message": message,
    }
    if details:
        out["details"] = details
    return out


def _error_to_status(err: Exception) -> Tuple[int, dict]:
    if isinstance(err, NamespaceTerminatingError):
        return 403, _status(
            403, "Forbidden", err.message, details={"name": err.namespace}
        )
    if isinstance(err, NotFoundError):
        return 404, _status(404, "NotFound", str(err))
    if isinstance(err, AlreadyExistsError):
        return 409, _status(409, "AlreadyExists", str(err))
    if isinstance(err, ConflictError):
        return 409, _status(409, "Conflict", str(err))
    if isinstance(err, APIError):
        return 500, _status(500, err.reason, err.message)
    return 500, _status(500, "InternalError", str(err))


@guarded_by("_lock", "_history", "_oldest", "_subscribers")
class FakeKubeAPI:
    """HTTP facade over an embedded APIServer store."""

    def __init__(
        self,
        api: Optional[emb.APIServer] = None,
        history_limit: int = 4096,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.api = api or emb.APIServer()
        self.history_limit = history_limit
        # per kind: deque of (rv, event type, wire dict); oldest retained
        # rv marks the 410 horizon
        self._history: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=history_limit)
        )
        self._oldest: Dict[str, int] = defaultdict(int)
        self._subscribers: Dict[str, List] = defaultdict(list)
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        for kind in _RESOURCES:
            self.api.watch(kind, self._make_recorder(kind), replay=True)

        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                fake._handle_http(self, "GET")

            def do_POST(self):
                fake._handle_http(self, "POST")

            def do_PUT(self):
                fake._handle_http(self, "PUT")

            def do_DELETE(self):
                fake._handle_http(self, "DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def host(self) -> str:
        addr, port = self._httpd.server_address[:2]
        return f"http://{addr}:{port}"

    def start(self) -> "FakeKubeAPI":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-kube-api", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()  # unblock streaming watch handler threads
        self._httpd.shutdown()
        self._httpd.server_close()

    def client_backend(self, qps: float = 0.0, burst: int = 0) -> RestAPIServer:
        from ..kube.restclient import ClusterConfig

        return RestAPIServer(ClusterConfig(host=self.host, qps=qps, burst=burst))

    # -- event recording -----------------------------------------------------

    def _make_recorder(self, kind: str):
        res = _RESOURCES[kind]

        def record(event: str, obj):
            wire = res.to_wire(obj)
            rv = obj.meta.resource_version
            with self._lock:
                hist = self._history[kind]
                if len(hist) == hist.maxlen and hist:
                    self._oldest[kind] = hist[0][0]
                hist.append((rv, event, wire))
                subs = list(self._subscribers[kind])
            for q in subs:
                q.append((rv, event, wire))

        return record

    # -- request dispatch ----------------------------------------------------

    def _handle_http(self, req: BaseHTTPRequestHandler, method: str) -> None:
        try:
            split = urlsplit(req.path)
            params = {k: v[0] for k, v in parse_qs(split.query).items()}
            path = split.path
            if path.startswith("/apis/apiextensions.k8s.io/v1/customresourcedefinitions"):
                self._handle_crd(req, method, path)
                return
            m = _ITEM_RE.match(path)
            kind = None
            if m:
                group = m.group("group") or ""
                version = m.group("corev") or m.group("ver")
                kind = _PATHS.get((group, version, m.group("plural")))
            if kind is None:
                self._send(req, 404, _status(404, "NotFound", f"no route {path}"))
                return
            res = _RESOURCES[kind]
            ns, name = m.group("ns"), m.group("name")
            body = self._read_body(req)

            if method == "GET" and name is None and params.get("watch") == "1":
                self._serve_watch(req, kind, params)
                return
            if method == "GET" and name is None:
                self._serve_list(req, kind, ns)
                return
            if method == "GET":
                # cluster-scoped objects live under the store's default
                # namespace key (ObjectMeta.namespace defaults to it)
                obj = self.api.get(kind, ns or "default", name)
                self._send(req, 200, res.to_wire(obj))
                return
            if method == "POST":
                obj = res.from_wire(body)
                if res.namespaced and ns:
                    obj.meta.namespace = ns
                out = self.api.create(obj)
                self._send(req, 201, res.to_wire(out))
                return
            if method == "PUT":
                obj = res.from_wire(body)
                if res.namespaced and ns:
                    obj.meta.namespace = ns
                if m.group("sub") == "status":
                    # real subresource semantics: only status fields move,
                    # the stored spec wins (metadata rv still gates)
                    current = self.api.get(kind, obj.namespace, obj.name)
                    merged = current.deepcopy()
                    merged.meta.resource_version = obj.meta.resource_version
                    if kind == "Pod":
                        merged.phase = obj.phase
                        merged.conditions = obj.conditions
                        merged.container_terminated = obj.container_terminated
                    else:
                        merged.status = obj.status
                    obj = merged
                out = self.api.update(obj)
                self._send(req, 200, res.to_wire(out))
                return
            if method == "DELETE":
                self.api.delete(kind, ns or "default", name)
                self._send(req, 200, _status(200, "", "deleted"))
                return
            self._send(req, 405, _status(405, "MethodNotAllowed", method))
        except BrokenPipeError:
            pass
        except Exception as err:  # wire every failure as a k8s Status
            code, status = _error_to_status(err)
            try:
                self._send(req, code, status)
            except BrokenPipeError:
                pass

    def _handle_crd(self, req, method: str, path: str) -> None:
        name = path.rsplit("/", 1)[1] if path.count("/") > 4 else None
        body = self._read_body(req)
        if method == "GET" and name:
            spec = self.api.get_crd(name)
            if spec is None:
                self._send(req, 404, _status(404, "NotFound", f"crd {name} not found"))
                return
            self._send(req, 200, self._crd_wire(name, spec))
            return
        if method == "POST":
            name = (body.get("metadata") or {}).get("name", "")
            spec = RestAPIServer._crd_from_wire(body)
            # Established is server-side state, not client input: the
            # wire the client POSTs has no status, and a real cluster
            # establishes shortly after create — let the embedded
            # registry's auto-establish model that
            spec.pop("established", None)
            self.api.create_crd(name, spec)
            self._send(req, 201, self._crd_wire(name, self.api.get_crd(name)))
            return
        if method == "PUT" and name:
            spec = RestAPIServer._crd_from_wire(body)
            spec.pop("established", None)
            self.api.update_crd(name, spec)
            self._send(req, 200, self._crd_wire(name, self.api.get_crd(name)))
            return
        if method == "DELETE" and name:
            self.api.delete_crd(name)
            self._send(req, 200, _status(200, "", "deleted"))
            return
        self._send(req, 405, _status(405, "MethodNotAllowed", method))

    @staticmethod
    def _crd_wire(name: str, spec: dict) -> dict:
        wire = RestAPIServer._crd_to_wire(name, spec)
        wire["status"] = {
            "conditions": [
                {
                    "type": "Established",
                    "status": "True" if spec.get("established") else "False",
                }
            ]
        }
        return wire

    # -- list / watch --------------------------------------------------------

    def _serve_list(self, req, kind: str, ns: Optional[str]) -> None:
        res = _RESOURCES[kind]
        objs = self.api.list(kind, ns if res.namespaced else None)
        # the GLOBAL revision, like a real apiserver (empty lists
        # included) — a watch resumed from it detects truncated history
        # via 410 instead of silently skipping events
        rv = self.api.resource_version
        body = {
            "apiVersion": "v1",
            "kind": f"{kind}List",
            "metadata": {"resourceVersion": str(rv)},
            "items": [res.to_wire(o) for o in objs],
        }
        self._send(req, 200, body)

    def _serve_watch(self, req, kind: str, params: dict) -> None:
        try:
            since = int(params.get("resourceVersion") or 0)
        except ValueError:
            since = 0
        timeout = float(params.get("timeoutSeconds") or 300)
        res = _RESOURCES[kind]
        with self._lock:
            if since and since < self._oldest[kind]:
                code, status = 410, _status(
                    410, "Expired", f"too old resource version: {since}"
                )
            else:
                code = 200
                q: deque = deque()
                self._subscribers[kind].append(q)
                if since:
                    backlog = [h for h in self._history[kind] if h[0] > since]
                else:
                    backlog = None  # resolved below, outside the lock
        if code == 410:
            self._send(req, 410, status)
            return
        if backlog is None:
            # rv=0 semantics (real apiserver): synthetic ADDED events for
            # the CURRENT state, then follow live — never a truncated
            # history replay.  The subscriber attached above, so events
            # racing this list are deduped by the rv>sent filter.
            objs = self.api.list(kind)
            baseline = self.api.resource_version
            backlog = [
                (baseline, emb.ADDED, res.to_wire(o)) for o in objs
            ]
            since = 0
        try:
            req.send_response(200)
            req.send_header("Content-Type", "application/json")
            # stream: no Content-Length; HTTP/1.0-style close delimits it
            req.send_header("Connection", "close")
            req.end_headers()
            deadline = threading.Event()

            def write(rv: int, etype: str, wire: dict) -> None:
                line = json.dumps({"type": etype, "object": wire}) + "\n"
                req.wfile.write(line.encode())
                req.wfile.flush()

            sent = since
            for rv, etype, wire in backlog:
                write(rv, etype, wire)
                sent = max(sent, rv)
            import time as _time

            end = _time.monotonic() + timeout
            while _time.monotonic() < end and not self._stopping.is_set():
                while q:
                    rv, etype, wire = q.popleft()
                    if rv > sent:
                        write(rv, etype, wire)
                        sent = max(sent, rv)
                deadline.wait(0.02)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._lock:
                try:
                    self._subscribers[kind].remove(q)
                except ValueError:
                    pass

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _read_body(req) -> dict:
        length = int(req.headers.get("Content-Length") or 0)
        if not length:
            return {}
        return json.loads(req.rfile.read(length).decode() or "{}")

    def _send(self, req, code: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        req.send_response(code)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(payload)))
        req.end_headers()
        req.wfile.write(payload)
