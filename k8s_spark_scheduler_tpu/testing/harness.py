"""Test harness (reference
``internal/extender/extendertest/extender_test_utils.go``).

Builds the entire wiring on the embedded API server and exposes
schedule/terminate/assert helpers plus object factories:
``new_node`` (8 CPU / 8Gi / 1 GPU, zone label), static and dynamic
allocation spark-pod builders with correctly-annotated driver/executor
pods and instance-group affinity.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from .. import timesource
from ..analysis import racecheck
from ..config import FifoConfig, Install
from ..kube.apiserver import APIServer
from ..kube.crd import DEMAND_CRD_NAME, demand_crd_spec
from ..scheduler import labels as L
from ..server.wiring import Server, init_server_with_clients
from ..types.extenderapi import ExtenderArgs, ExtenderFilterResult
from ..types.objects import Container, Node, ObjectMeta, Pod, PodPhase
from ..types.resources import ZONE_LABEL, Resources


class Harness:
    """extender_test_utils.go:54-176."""

    def __init__(
        self,
        binpack_algo: str = "tightly-pack",
        is_fifo: bool = True,
        fifo_config: Optional[FifoConfig] = None,
        instance_group_label: str = "resource_channel",
        dynamic_allocation_single_az: bool = False,
        with_demand_crd: bool = True,
        extra_install: Optional[Install] = None,
        driver_prioritized_node_label=None,
        executor_prioritized_node_label=None,
        unschedulable_polling_interval: float = 60.0,
    ):
        # SCHEDLINT_RACECHECK=1: activate the lockset race detector
        # BEFORE any guarded shared state is constructed, so every lock
        # the server wires up is tracked from birth
        racecheck.enable_if_env()
        self.api = APIServer()
        if with_demand_crd:
            self.api.create_crd(DEMAND_CRD_NAME, demand_crd_spec())
        install = extra_install or Install(
            fifo=is_fifo,
            fifo_config=fifo_config or FifoConfig(),
            binpack_algo=binpack_algo,
            instance_group_label=instance_group_label,
            should_schedule_dynamically_allocated_executors_in_same_az=dynamic_allocation_single_az,
            driver_prioritized_node_label=driver_prioritized_node_label,
            executor_prioritized_node_label=executor_prioritized_node_label,
        )
        self.server: Server = init_server_with_clients(
            self.api,
            install,
            start_background=True,
            demand_poll_interval=0.02,
            unschedulable_polling_interval=unschedulable_polling_interval,
        )
        self.extender = self.server.extender
        self.unschedulable_marker = self.server.unschedulable_marker
        if with_demand_crd:
            self.server.lazy_demand_informer.wait_ready(5)

    def close(self) -> None:
        self.server.stop()

    # -- cluster management --------------------------------------------------

    def add_node(self, node: Node) -> Node:
        return self.api.create(node)

    def new_node(
        self,
        name: str,
        cpu="8",
        memory="8Gi",
        gpu="1",
        zone: str = "zone1",
        instance_group: str = "batch-medium-priority",
        instance_group_label: str = "resource_channel",
        unschedulable: bool = False,
        ready: bool = True,
        labels: Optional[dict] = None,
    ) -> Node:
        """extender_test_utils.go:239-271."""
        node = Node(
            meta=ObjectMeta(
                name=name,
                labels={
                    ZONE_LABEL: zone,
                    instance_group_label: instance_group,
                    **(labels or {}),
                },
            ),
            allocatable=Resources.of(cpu, memory, gpu),
            unschedulable=unschedulable,
            ready=ready,
        )
        return self.add_node(node)

    # -- pod factories -------------------------------------------------------

    @staticmethod
    def static_allocation_spark_pods(
        app_id: str,
        executor_count: int,
        driver_cpu="1",
        driver_mem="1Gi",
        driver_gpu: Optional[str] = None,
        executor_cpu="1",
        executor_mem="1Gi",
        executor_gpu: Optional[str] = None,
        instance_group: str = "batch-medium-priority",
        instance_group_label: str = "resource_channel",
        namespace: str = "default",
        creation_timestamp: Optional[float] = None,
    ) -> List[Pod]:
        """extender_test_utils.go:275-339: [driver, executor-0..n-1]."""
        annotations = {
            L.DRIVER_CPU: driver_cpu,
            L.DRIVER_MEMORY: driver_mem,
            L.EXECUTOR_CPU: executor_cpu,
            L.EXECUTOR_MEMORY: executor_mem,
            L.EXECUTOR_COUNT: str(executor_count),
        }
        if driver_gpu is not None:
            annotations[L.DRIVER_NVIDIA_GPUS] = driver_gpu
        if executor_gpu is not None:
            annotations[L.EXECUTOR_NVIDIA_GPUS] = executor_gpu
        return Harness._spark_pods(
            app_id,
            executor_count,
            annotations,
            instance_group,
            instance_group_label,
            namespace,
            creation_timestamp,
        )

    @staticmethod
    def dynamic_allocation_spark_pods(
        app_id: str,
        min_executor_count: int,
        max_executor_count: int,
        driver_cpu="1",
        driver_mem="1Gi",
        executor_cpu="1",
        executor_mem="1Gi",
        executor_gpu: Optional[str] = None,
        instance_group: str = "batch-medium-priority",
        instance_group_label: str = "resource_channel",
        namespace: str = "default",
        creation_timestamp: Optional[float] = None,
    ) -> List[Pod]:
        """extender_test_utils.go:342-423: driver + max_executor_count
        executor pods (the extras only get soft reservations)."""
        annotations = {
            L.DRIVER_CPU: driver_cpu,
            L.DRIVER_MEMORY: driver_mem,
            L.EXECUTOR_CPU: executor_cpu,
            L.EXECUTOR_MEMORY: executor_mem,
            L.DYNAMIC_ALLOCATION_ENABLED: "true",
            L.DA_MIN_EXECUTOR_COUNT: str(min_executor_count),
            L.DA_MAX_EXECUTOR_COUNT: str(max_executor_count),
        }
        if executor_gpu is not None:
            annotations[L.EXECUTOR_NVIDIA_GPUS] = executor_gpu
        return Harness._spark_pods(
            app_id,
            max_executor_count,
            annotations,
            instance_group,
            instance_group_label,
            namespace,
            creation_timestamp,
        )

    @staticmethod
    def _spark_pods(
        app_id: str,
        executor_count: int,
        annotations: dict,
        instance_group: str,
        instance_group_label: str,
        namespace: str,
        creation_timestamp: Optional[float],
    ) -> List[Pod]:
        ts = creation_timestamp if creation_timestamp is not None else timesource.now()
        driver = Pod(
            meta=ObjectMeta(
                name=f"{app_id}-driver",
                namespace=namespace,
                labels={L.SPARK_ROLE_LABEL: L.DRIVER, L.SPARK_APP_ID_LABEL: app_id},
                annotations=dict(annotations),
                creation_timestamp=ts,
            ),
            scheduler_name=L.SPARK_SCHEDULER_NAME,
            node_affinity={instance_group_label: [instance_group]},
            containers=[Container(requests=Resources.of(annotations[L.DRIVER_CPU], annotations[L.DRIVER_MEMORY]))],
        )
        pods = [driver]
        for i in range(executor_count):
            pods.append(
                Pod(
                    meta=ObjectMeta(
                        name=f"{app_id}-exec-{i + 1}",
                        namespace=namespace,
                        labels={L.SPARK_ROLE_LABEL: L.EXECUTOR, L.SPARK_APP_ID_LABEL: app_id},
                        annotations=dict(annotations),
                        creation_timestamp=ts,
                    ),
                    scheduler_name=L.SPARK_SCHEDULER_NAME,
                    node_affinity={instance_group_label: [instance_group]},
                    containers=[
                        Container(
                            requests=Resources.of(
                                annotations[L.EXECUTOR_CPU], annotations[L.EXECUTOR_MEMORY]
                            )
                        )
                    ],
                )
            )
        return pods

    # -- scheduling simulation ----------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        return self.api.create(pod)

    def schedule(self, pod: Pod, node_names: Sequence[str]) -> ExtenderFilterResult:
        """Simulate the kube-scheduler callback AND the bind
        (extender_test_utils.go:179-193): on success sets nodeName, phase
        Running, and updates the store."""
        existing = self.server.pod_informer.get(pod.namespace, pod.name)
        if existing is None:
            pod = self.api.create(pod)
        else:
            pod = existing.deepcopy()
        # route through the concurrent admission engine when wired —
        # exactly what the HTTP layer does (server/http.py), so harness
        # scheduling exercises the same speculate→commit path
        engine = getattr(self.server, "concurrent", None)
        predicate = engine.predicate if engine is not None else self.extender.predicate
        result = predicate(ExtenderArgs(pod=pod, node_names=list(node_names)))
        if result.node_names:
            bound = self.api.get(Pod.KIND, pod.namespace, pod.name)
            bound.node_name = result.node_names[0]
            bound.phase = PodPhase.RUNNING
            self.api.update(bound)
        return result

    def terminate_pod(self, pod: Pod) -> None:
        """extender_test_utils.go:196-209: phase Succeeded + terminated
        container statuses."""
        fresh = self.api.get(Pod.KIND, pod.namespace, pod.name)
        fresh.phase = PodPhase.SUCCEEDED
        fresh.container_terminated = [True] * max(1, len(fresh.containers))
        self.api.update(fresh)

    def delete_pod(self, pod: Pod) -> None:
        self.api.delete(Pod.KIND, pod.namespace, pod.name)

    # -- assertions ----------------------------------------------------------

    @staticmethod
    def assert_success(result: ExtenderFilterResult) -> str:
        assert result.node_names, f"expected success, got failure: {result.failed_nodes}"
        return result.node_names[0]

    @staticmethod
    def assert_failure(result: ExtenderFilterResult) -> None:
        assert not result.node_names, f"expected failure, got node {result.node_names}"

    def get_resource_reservation(self, app_id: str, namespace: str = "default"):
        return self.server.resource_reservation_cache.get(namespace, app_id)

    def wait_quiesced(self, timeout: float = 5.0) -> bool:
        """Wait until async write-back queues drain and the local
        reservation cache agrees with the API server — makes
        timing-sensitive scenario tests deterministic (the transient
        divergence is reference-equivalent but nondeterministic).

        Keys with a pending intent-journal entry are excluded from the
        comparison: while the write-back breaker is open (API-server
        outage) the local cache legitimately leads the API server by
        exactly the journaled intents — that divergence IS the quiesced
        state, and the auditor's lost-intent check covers it."""
        def rr_content(rrs, exclude):
            return {
                (rr.namespace, rr.name): (
                    sorted((k, v.node) for k, v in rr.spec.reservations.items()),
                    sorted(rr.status.pods.items()),
                )
                for rr in rrs
                if (rr.namespace, rr.name) not in exclude
            }

        def settled():
            if any(self.server.resource_reservation_cache.inflight_queue_lengths()):
                return False
            kit = getattr(self.server, "resilience", None)
            pending = kit.journal.pending_keys() if kit is not None else set()
            # compare full content (a popped-but-unapplied write has equal
            # key sets but differing specs)
            local = rr_content(
                self.server.resource_reservation_cache.list(), pending
            )
            remote = rr_content(self.api.list("ResourceReservation"), pending)
            return local == remote
        return self.wait_for_api(settled, timeout=timeout)

    def wait_for_api(self, cond, timeout: float = 5.0, tick: float = 0.01) -> bool:
        """waitForCondition (cmd/integration common.go:119-136).

        Deadline on the REAL monotonic clock, never the (possibly
        virtual, frozen) timesource — a sim run must keep bounded
        waits bounded."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(tick)
        return False
