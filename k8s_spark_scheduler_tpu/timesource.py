"""Pluggable wall-clock source for the control plane.

Every *semantic* "what time is it" read in the scheduler — object
creation timestamps, the failover idle-reconcile trigger, FIFO
enforce-after ages, demand-waste attribution, the unschedulable-pod
timeout — goes through :func:`now` instead of ``time.time``.  In
production it IS ``time.time``; the discrete-event simulator
(:mod:`k8s_spark_scheduler_tpu.sim`) swaps in a virtual clock so hours
of cluster life replay in milliseconds and timers fire at simulated
instants, deterministically.

Span *durations* go through the separate :func:`perf` hook (default
``time.perf_counter``): a trace must not mix virtual start instants
with wall-clock durations, so the simulator installs its clock for
both and a completed sim trace is virtual end to end.  Everything
else that measures latency (lock wait/hold telemetry, bench loops)
and harness/infrastructure deadlines (``time.monotonic`` waits) stays
on the real clock on purpose: a sim run still wants real decision
latencies, and a frozen virtual clock must never turn a bounded wait
into an infinite one.
"""

from __future__ import annotations

import time
from typing import Callable

_source: Callable[[], float] = time.time
_perf: Callable[[], float] = time.perf_counter


def now() -> float:
    """Current semantic wall-clock time (seconds since epoch, or
    virtual seconds when a simulator clock is installed)."""
    return _source()


def set_source(fn: Callable[[], float]) -> None:
    """Install a replacement time source (e.g. a VirtualClock's
    ``now``).  Affects every thread in the process — callers own the
    responsibility to :func:`reset` when done (the sim runner does this
    in a ``finally``)."""
    global _source
    _source = fn


def perf() -> float:
    """Monotonic instant for span durations (seconds; no defined
    epoch).  Real ``perf_counter`` in production, the virtual clock in
    a sim run — keeping every number inside one trace on one
    timeline."""
    return _perf()


def set_perf_source(fn: Callable[[], float]) -> None:
    """Install a replacement duration source for spans.  Same process-
    wide scope and reset obligation as :func:`set_source`."""
    global _perf
    _perf = fn


def reset() -> None:
    """Restore the real wall clock (both sources)."""
    global _source, _perf
    _source = time.time
    _perf = time.perf_counter


def is_virtual() -> bool:
    return _source is not time.time
