"""Pluggable wall-clock source for the control plane.

Every *semantic* "what time is it" read in the scheduler — object
creation timestamps, the failover idle-reconcile trigger, FIFO
enforce-after ages, demand-waste attribution, the unschedulable-pod
timeout — goes through :func:`now` instead of ``time.time``.  In
production it IS ``time.time``; the discrete-event simulator
(:mod:`k8s_spark_scheduler_tpu.sim`) swaps in a virtual clock so hours
of cluster life replay in milliseconds and timers fire at simulated
instants, deterministically.

Latency *measurement* (``perf_counter`` spans, histograms) and
harness/infrastructure deadlines (``time.monotonic`` waits) are
intentionally NOT routed through here: a sim run still wants real
decision latencies, and a frozen virtual clock must never turn a
bounded wait into an infinite one.
"""

from __future__ import annotations

import time
from typing import Callable

_source: Callable[[], float] = time.time


def now() -> float:
    """Current semantic wall-clock time (seconds since epoch, or
    virtual seconds when a simulator clock is installed)."""
    return _source()


def set_source(fn: Callable[[], float]) -> None:
    """Install a replacement time source (e.g. a VirtualClock's
    ``now``).  Affects every thread in the process — callers own the
    responsibility to :func:`reset` when done (the sim runner does this
    in a ``finally``)."""
    global _source
    _source = fn


def reset() -> None:
    """Restore the real wall clock."""
    global _source
    _source = time.time


def is_virtual() -> bool:
    return _source is not time.time
