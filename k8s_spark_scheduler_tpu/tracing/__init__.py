"""Span-based tracing for the scheduling hot path.

The reference scheduler runs inside the witchcraft runtime, which gives
every request a zipkin-style trace (trc1 log lines, span ids on every
log statement).  This package is that runtime's analog for the
reproduction: lightweight in-process spans with parent/child links and
tags, a bounded ring of completed traces served over ``GET /traces``,
and kernel-level profiling hooks that split JAX solver time into
trace/compile vs execute (``tracing.profiling``).

Design constraints (the hot path is ~1ms end to end):

- a span is a handful of attribute writes + one ``perf_counter`` pair;
- context propagation uses one ``contextvars.ContextVar`` shared by all
  tracers, so events/logs can stamp ``trace_id`` without knowing which
  tracer opened the trace;
- a disabled tracer returns a shared no-op context manager (zero
  allocation), so tracing can never regress an untraced deployment —
  enforced by tests/test_perf_guard.py.
"""

from .spans import (  # noqa: F401
    NOOP_SPAN,
    Span,
    Tracer,
    add_tag,
    child_span,
    current_span,
    current_trace_id,
    default_tracer,
)
