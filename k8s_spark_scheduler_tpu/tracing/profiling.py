"""Kernel-level profiling hooks for the JAX solvers.

The predicate hot path dispatches compiled programs (XLA scans, pallas
kernels, the native C++ lane).  A flat request timer can't tell an
operator whether a slow Filter paid jit *compilation* (new shape bucket
→ seconds) or *execution* (steady state → sub-millisecond), so the
profiler splits every profiled dispatch into:

- **compile time** — wall time of the traced Python call when the jit
  cache grew (trace + lower + compile; ``KERNEL_COMPILE_TIME``),
- **execute time** — ``block_until_ready``-bounded device time
  (``KERNEL_EXECUTE_TIME``),
- **cache hits/misses** — ``KERNEL_CACHE_HITS`` / ``KERNEL_CACHE_MISSES``,

all tagged with the kernel name and the lane ("xla", "pallas",
"native", …), and mirrored onto the active trace span so a span tree
shows exactly which kernel compiled mid-request.

Cache-miss detection prefers the jitted function's own cache
(``fn._cache_size()``); lanes that can't expose one (pallas wrappers)
fall back to a seen-(kernel, shape-key) set.  The native C++ lane has
no compile phase: profiled with ``jit=False``, it records execute time
only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional, Set, Tuple

from ..metrics import names as mnames
from .spans import NOOP_SPAN, Tracer, current_span, default_tracer
from ..analysis.guarded import guarded_by


def jit_cache_size(fn) -> Optional[int]:
    """Entry count of a jitted function's compilation cache, or None
    when the callable doesn't expose one (plain wrappers, native)."""
    try:
        return fn._cache_size()
    except Exception:
        return None


class _KernelRecord:
    """Per-dispatch timing marks.  ``sync(*arrays)`` must be called
    right after the traced call returns, with the outputs — it stamps
    the dispatch end, then blocks until the arrays are device-ready."""

    __slots__ = ("t0", "t_dispatch", "t_end")

    def __init__(self) -> None:
        self.t0 = time.perf_counter()
        self.t_dispatch: Optional[float] = None
        self.t_end: Optional[float] = None

    def sync(self, *arrays: Any) -> None:
        self.t_dispatch = time.perf_counter()
        for a in arrays:
            block = getattr(a, "block_until_ready", None)
            if block is not None:
                block()
        self.t_end = time.perf_counter()


class _Profile:
    __slots__ = ("_profiler", "_kernel", "_lane", "_fn", "_shape_key", "_jit",
                 "_rec", "_span", "_cache_before")

    def __init__(self, profiler, kernel, lane, fn, shape_key, jit):
        self._profiler = profiler
        self._kernel = kernel
        self._lane = lane
        self._fn = fn
        self._shape_key = shape_key
        self._jit = jit
        self._rec: Optional[_KernelRecord] = None
        self._span = NOOP_SPAN
        self._cache_before: Optional[int] = None

    def __enter__(self) -> _KernelRecord:
        # kernel spans are always sub-phases: attach only when a request
        # span is active, so background solves (warmup, the
        # unschedulable scan) don't litter the ring with root traces
        if current_span() is not None:
            self._span = self._profiler.tracer.span(
                f"kernel:{self._kernel}", {mnames.TAG_LANE: self._lane}
            )
        self._span.__enter__()
        if self._jit and self._fn is not None:
            self._cache_before = jit_cache_size(self._fn)
        self._rec = _KernelRecord()
        return self._rec

    def __exit__(self, exc_type, exc, tb) -> bool:
        rec = self._rec
        now = time.perf_counter()
        t_end = rec.t_end if rec.t_end is not None else now
        t_dispatch = rec.t_dispatch if rec.t_dispatch is not None else t_end
        try:
            if exc is None:
                self._record(rec.t0, t_dispatch, t_end)
        finally:
            self._span.__exit__(exc_type, exc, tb)
        return False

    def _record(self, t0: float, t_dispatch: float, t_end: float) -> None:
        prof = self._profiler
        metrics = prof.metrics
        tags = {mnames.TAG_KERNEL: self._kernel, mnames.TAG_LANE: self._lane}
        if not self._jit:
            execute = t_end - t0
            metrics.histogram(mnames.KERNEL_EXECUTE_TIME, execute, tags)
            self._span.tag("executeMs", round(execute * 1000.0, 4))
            return

        miss = prof._classify_miss(
            self._kernel, self._fn, self._shape_key, self._cache_before
        )
        if miss:
            compile_s = t_dispatch - t0
            execute = t_end - t_dispatch
            metrics.counter(mnames.KERNEL_CACHE_MISSES, tags)
            metrics.histogram(mnames.KERNEL_COMPILE_TIME, compile_s, tags)
            self._span.tag("compileMs", round(compile_s * 1000.0, 4))
        else:
            # steady state: dispatch is µs-level, fold it into execute
            execute = t_end - t0
            metrics.counter(mnames.KERNEL_CACHE_HITS, tags)
        metrics.histogram(mnames.KERNEL_EXECUTE_TIME, execute, tags)
        self._span.tag("executeMs", round(execute * 1000.0, 4))
        self._span.tag("cacheHit", not miss)


@guarded_by("_seen_lock", "_seen")
class KernelProfiler:
    """Profiling sink: records into a metrics registry and the active
    trace.  One module-level instance (``default_profiler``) is rebound
    to the server's registry/tracer by the wiring."""

    def __init__(self, metrics=None, tracer: Optional[Tracer] = None):
        from ..metrics.registry import default_registry

        self.metrics = metrics if metrics is not None else default_registry
        self.tracer = tracer if tracer is not None else default_tracer
        self._seen: Set[Tuple[str, Any]] = set()
        self._seen_lock = threading.Lock()

    def configure(self, metrics=None, tracer: Optional[Tracer] = None) -> None:
        if metrics is not None:
            self.metrics = metrics
        if tracer is not None:
            self.tracer = tracer

    def profile(
        self,
        kernel: str,
        lane: str = "",
        fn=None,
        shape_key: Any = None,
        jit: bool = True,
    ) -> _Profile:
        """Context manager around one kernel dispatch.  The managed
        value is a record whose ``sync(*outputs)`` the caller invokes
        immediately after the dispatch returns."""
        return _Profile(self, kernel, lane, fn, shape_key, jit)

    def _classify_miss(self, kernel, fn, shape_key, cache_before) -> bool:
        if fn is not None and cache_before is not None:
            after = jit_cache_size(fn)
            return after is not None and after > cache_before
        key = (kernel, shape_key)
        with self._seen_lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            return True


default_profiler = KernelProfiler()
