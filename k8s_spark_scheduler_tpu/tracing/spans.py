"""Spans, trace assembly, and the bounded completed-trace ring.

One trace per scheduling request: the HTTP layer opens the root span
(``http.request``), the extender and the solvers open children, and
when the root closes the finished tree is serialized into the tracer's
ring where ``GET /traces`` and ``GET /debug/schedule/<pod>`` read it.

The active span is a module-level ``ContextVar`` — per-thread in the
threaded HTTP server (each request handler thread has its own context),
and shared across tracer instances so ``events.events`` and log lines
can stamp the current ``trace_id`` without any plumbing.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, List, Optional, Tuple

from .. import timesource
from ..analysis.guarded import guarded_by

# the single active-span slot shared by every Tracer (see module doc)
_CURRENT: ContextVar[Optional["Span"]] = ContextVar(
    "k8s_spark_scheduler_tpu_current_span", default=None
)

_SPAN_SEQ = itertools.count(1)


def current_span() -> Optional["Span"]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    span = _CURRENT.get()
    return span.trace_id if span is not None else None


def add_tag(key: str, value: Any) -> None:
    """Tag the active span, if any — safe to call from untraced code."""
    span = _CURRENT.get()
    if span is not None:
        span.tags[key] = value


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def child_span(name: str, tags: Optional[Dict[str, Any]] = None):
    """Span attached to the active trace, or the shared no-op when none
    is active — for library layers (state caches, solvers) that must
    observe request traces but never start root traces of their own."""
    parent = _CURRENT.get()
    if parent is None:
        return NOOP_SPAN
    span = Span(name, parent.trace_id, parent)
    parent.children.append(span)
    if tags:
        span.tags.update(tags)
    return span


class Span:
    """One timed phase.  Children attach at creation; duration lands at
    context-manager exit.  Not a dataclass: __slots__ + plain attribute
    writes keep per-span cost to a few hundred ns."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent",
        "start_time",
        "duration",
        "tags",
        "children",
        "_t0",
        "_token",
        "_tracer",
    )

    def __init__(self, name: str, trace_id: str, parent: Optional["Span"]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = format(next(_SPAN_SEQ), "x")
        self.parent = parent
        self.start_time = 0.0
        self.duration: Optional[float] = None
        self.tags: Dict[str, Any] = {}
        self.children: List[Span] = []
        self._t0 = 0.0
        self._token = None
        self._tracer: Optional["Tracer"] = None

    def tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "spanId": self.span_id,
            "parentId": self.parent.span_id if self.parent is not None else None,
            "startTime": self.start_time,
            "durationMs": round((self.duration or 0.0) * 1000.0, 4),
            "tags": dict(self.tags),
        }
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    # -- context manager ------------------------------------------------------

    def __enter__(self) -> "Span":
        # semantic instant, not latency: sim traces carry virtual time
        self.start_time = timesource.now()
        self._token = _CURRENT.set(self)
        # duration through the same pluggable source family: a sim
        # trace must not mix virtual timestamps with wall durations
        self._t0 = timesource.perf()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration = timesource.perf() - self._t0
        if exc is not None and "error" not in self.tags:
            self.tags["error"] = f"{type(exc).__name__}: {exc}"
        if self._token is not None:
            _CURRENT.reset(self._token)
        if self.parent is None and self._tracer is not None:
            self._tracer._finish_trace(self)
        return False


class _NoopSpan:
    """Shared do-nothing span: returned by disabled tracers so call
    sites never branch.  tag()/attribute writes are swallowed."""

    __slots__ = ()
    trace_id = None
    span_id = None
    tags: Dict[str, Any] = {}

    def tag(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


@guarded_by("_lock", "_ring", "_finished")
class Tracer:
    """Span factory + bounded ring of completed traces.

    ``span(name)`` opens a child of the active span, or a new root (and
    therefore a new trace) when none is active.  When a root span exits,
    the whole tree is serialized and appended to the ring; optionally
    every span's duration is recorded as a tagged histogram so /metrics
    carries per-phase latency distributions without reading traces.
    """

    def __init__(
        self,
        capacity: int = 256,
        enabled: bool = True,
        metrics=None,
        record_span_metrics: bool = True,
    ):
        self.enabled = enabled
        self._ring: deque = deque(maxlen=capacity)
        # total completed traces ever — cursor for completed_since();
        # the ring holds the most recent len(_ring) of them
        self._finished = 0
        self._lock = threading.Lock()
        self._metrics = metrics
        self._record_span_metrics = record_span_metrics
        # trace-completion observers (e.g. the critical-path analyzer):
        # called with the live root Span after the tree lands in the
        # ring, outside the ring lock.  Wiring-time append only.
        self._observers: list = []

    # -- span creation --------------------------------------------------------

    def span(
        self,
        name: str,
        tags: Optional[Dict[str, Any]] = None,
        trace_id: Optional[str] = None,
    ):
        """Context manager for one phase.  ``trace_id`` is honored only
        when this span starts a new trace (no active parent)."""
        if not self.enabled:
            return NOOP_SPAN
        parent = _CURRENT.get()
        if parent is not None:
            span = Span(name, parent.trace_id, parent)
            parent.children.append(span)
        else:
            span = Span(name, trace_id or new_trace_id(), None)
            span._tracer = self
        if tags:
            span.tags.update(tags)
        return span

    # -- completed traces -----------------------------------------------------

    def _finish_trace(self, root: Span) -> None:
        trace = {
            "traceId": root.trace_id,
            "startTime": root.start_time,
            "durationMs": round((root.duration or 0.0) * 1000.0, 4),
            "root": root.to_dict(),
        }
        with self._lock:
            self._ring.append(trace)
            self._finished += 1
        if self._metrics is not None and self._record_span_metrics:
            from ..metrics import names as mnames

            stack = [root]
            while stack:
                span = stack.pop()
                self._metrics.histogram(
                    mnames.TRACE_SPAN_TIME,
                    span.duration or 0.0,
                    {mnames.TAG_SPAN: span.name},
                )
                stack.extend(span.children)
        for observer in self._observers:
            try:
                observer(root)
            except Exception:  # an observer must never break a request
                pass

    def add_observer(self, fn) -> None:
        """Register a trace-completion callback ``fn(root_span)``.
        Call at wiring time only — the list is read unlocked."""
        self._observers.append(fn)

    @property
    def completed_total(self) -> int:
        """Total traces ever completed (monotonic drain cursor)."""
        with self._lock:
            return self._finished

    def completed_since(self, cursor: int) -> Tuple[List[dict], int]:
        """Traces completed after ``cursor`` (oldest first, truncated
        to the ring's reach) and the new cursor value.  Pull-based
        alternative to add_observer for consumers that must never run
        inside a request — the lifecycle ledger drains here off-thread
        because for direct predicate calls the root span closes (and
        observers fire) while the predicate lock is still held."""
        with self._lock:
            total = self._finished
            fresh = total - cursor
            if fresh <= 0:
                return [], total
            n = min(fresh, len(self._ring))
            if n == 0:
                return [], total
            out = list(self._ring)[-n:]
        return out, total

    def traces(self, limit: Optional[int] = None) -> List[dict]:
        """Completed traces, newest first."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        if limit is not None:
            out = out[: max(limit, 0)]
        return out

    def find_by_tag(self, key: str, value: Any) -> Optional[dict]:
        """Newest completed trace with ``tags[key] == value`` on any
        span in the tree."""
        for trace in self.traces():
            if _tree_has_tag(trace["root"], key, value):
                return trace
        return None

    def find_by_trace_id(self, trace_id: str) -> Optional[dict]:
        for trace in self.traces():
            if trace["traceId"] == trace_id:
                return trace
        return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def _tree_has_tag(span_dict: dict, key: str, value: Any) -> bool:
    if span_dict.get("tags", {}).get(key) == value:
        return True
    return any(
        _tree_has_tag(c, key, value) for c in span_dict.get("children", ())
    )


def render_trace_text(trace: dict, events: Optional[List[Tuple[str, dict]]] = None) -> str:
    """Human-readable span tree (the /debug/schedule payload): one line
    per span with duration, indented by depth, tags inline; correlated
    events appended."""
    lines = [
        f"trace {trace['traceId']}  start={time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(trace['startTime']))}Z"
        f"  total={trace['durationMs']:.3f}ms"
    ]

    def walk(span: dict, depth: int) -> None:
        tags = span.get("tags", {})
        tag_str = " ".join(f"{k}={v}" for k, v in sorted(tags.items(), key=lambda kv: kv[0]))
        lines.append(
            f"{'  ' * depth}- {span['name']}  {span['durationMs']:.3f}ms"
            + (f"  [{tag_str}]" if tag_str else "")
        )
        for child in span.get("children", ()):
            walk(child, depth + 1)

    walk(trace["root"], 1)
    if events:
        lines.append("events:")
        for name, values in events:
            lines.append(f"  - {name} {values}")
    return "\n".join(lines) + "\n"


# module-level default (swappable for tests; the server wires its own)
default_tracer = Tracer()
