from .resources import (
    NodeGroupResources,
    NodeGroupSchedulingMetadata,
    NodeSchedulingMetadata,
    Resources,
)
