"""kube-scheduler extender protocol types
(k8s.io/kube-scheduler/extender/v1, used at cmd/endpoints.go:25-41)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .objects import Pod


@dataclass
class ExtenderArgs:
    pod: Pod
    node_names: List[str] = field(default_factory=list)


@dataclass
class ExtenderFilterResult:
    node_names: Optional[List[str]] = None
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "NodeNames": self.node_names,
            "FailedNodes": self.failed_nodes or None,
            "Error": self.error or None,
        }
