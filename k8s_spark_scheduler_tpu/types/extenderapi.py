"""kube-scheduler extender protocol types
(k8s.io/kube-scheduler/extender/v1, used at cmd/endpoints.go:25-41)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .objects import Pod


@dataclass
class ExtenderArgs:
    pod: Pod
    # an interned tuple on the HTTP path (serde.intern_node_names);
    # plain lists from direct callers work identically
    node_names: Sequence[str] = field(default_factory=list)


@dataclass
class ExtenderFilterResult:
    node_names: Optional[List[str]] = None
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    error: str = ""
    # (candidate names, shared message) when failed_nodes is the uniform
    # all-candidates map — lets serde reuse an encoded response buffer
    # keyed by the interned tuple's identity (serde.encode_extender_
    # filter_result).  Purely an encoding hint; to_dict ignores it.
    # The shared message carries the decision-provenance shortfall when
    # enabled ("short N executors (… milli-cpu) in cpu; blocked by …",
    # provenance/explain.py) — distinct shortfalls are distinct cache
    # entries, bounded by the encoder's LRU.
    uniform_failure: Optional[Tuple[Sequence[str], str]] = None

    def to_dict(self) -> dict:
        return {
            "NodeNames": self.node_names,
            "FailedNodes": self.failed_nodes or None,
            "Error": self.error or None,
        }
