"""API object model: pods, nodes, reservations, demands.

Covers the reference's CRD types
(``lib/pkg/apis/sparkscheduler/v1beta2/types_resource_reservation.go:51-57``,
``lib/pkg/apis/scaler/v1alpha2/types_demand.go:72-157``) and the small
subset of core/v1 Pod + Node the scheduler reads.  The objects are plain
dataclasses with dict (de)serialization so they can live in the embedded
state store, be diffed by resourceVersion, and round-trip through JSON.
"""

from __future__ import annotations

import copy as _copy
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import timesource
from ..utils.quantity import Quantity
from .resources import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_NVIDIA_GPU,
    Resources,
)

_monotonic_counter = itertools.count(1)


def now() -> float:
    return timesource.now()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    uid: str = ""
    owner_references: List["OwnerReference"] = field(default_factory=list)

    def ensure_identity(self) -> None:
        if not self.uid:
            self.uid = f"uid-{next(_monotonic_counter)}"
        if not self.creation_timestamp:
            self.creation_timestamp = now()

    def copy(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name,
            namespace=self.namespace,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
            resource_version=self.resource_version,
            uid=self.uid,
            owner_references=[
                OwnerReference(r.kind, r.name, r.uid, r.controller)
                for r in self.owner_references
            ],
        )


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = True


class APIObject:
    """Base for objects stored in the state store."""

    meta: ObjectMeta
    KIND: str = "Object"

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def namespace(self) -> str:
        return self.meta.namespace

    @property
    def labels(self) -> Dict[str, str]:
        return self.meta.labels

    @property
    def annotations(self) -> Dict[str, str]:
        return self.meta.annotations

    @property
    def creation_timestamp(self) -> float:
        return self.meta.creation_timestamp

    def deepcopy(self):
        """Deep copy of the object tree.  Subclasses override with
        hand-rolled constructions: ``copy.deepcopy``'s generic reflection
        cost ~3.5ms per ResourceReservation on the async write-back
        threads, which on a single-core host steals GIL time from
        in-flight Filter requests.  ``Quantity``/``Resources`` values are
        immutable (utils/quantity.py) and shared, not cloned."""
        return _copy.deepcopy(self)


# ---------------------------------------------------------------------------
# core/v1 subset
# ---------------------------------------------------------------------------


@dataclass
class Container:
    name: str = "main"
    requests: Resources = field(default_factory=Resources.zero)


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class Pod(APIObject):
    KIND = "Pod"
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    scheduler_name: str = ""
    node_name: str = ""  # spec.nodeName: set on bind
    node_selector: Dict[str, str] = field(default_factory=dict)
    # required node affinity match expressions: label → allowed values
    # (In semantics; the reference extracts instance group from
    # nodeAffinity/nodeSelector, internal/podspec.go:29-53)
    node_affinity: Dict[str, List[str]] = field(default_factory=dict)
    # full nodeSelectorTerms with k8s GetRequiredNodeAffinity semantics:
    # a list of TERMS (OR — a node must satisfy at least one), each a
    # list of (key, operator, values) expressions (AND within the term);
    # operators: In/NotIn/Exists/DoesNotExist/Gt/Lt.  When present this
    # supersedes the simple node_affinity dict (which serde fills only
    # for the single-term all-In case)
    affinity_terms: List[list] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    phase: str = PodPhase.PENDING
    # per-container terminated flags used by IsPodTerminated
    # (internal/common/utils/pods.go:69-75: terminated iff there is at
    # least one container status and all are terminated)
    container_terminated: List[bool] = field(default_factory=list)
    conditions: Dict[str, "PodCondition"] = field(default_factory=dict)

    def is_terminated(self) -> bool:
        return len(self.container_terminated) > 0 and all(self.container_terminated)

    def deepcopy(self) -> "Pod":
        return Pod(
            meta=self.meta.copy(),
            scheduler_name=self.scheduler_name,
            node_name=self.node_name,
            node_selector=dict(self.node_selector),
            node_affinity={k: list(v) for k, v in self.node_affinity.items()},
            # terms are lists of (key, op, values) expressions; the
            # values lists are never mutated after parse, so sharing the
            # expression tuples is safe — only the list nesting is cloned
            affinity_terms=[list(term) for term in self.affinity_terms],
            containers=[Container(c.name, c.requests) for c in self.containers],
            init_containers=[
                Container(c.name, c.requests) for c in self.init_containers
            ],
            phase=self.phase,
            container_terminated=list(self.container_terminated),
            conditions={
                k: PodCondition(
                    c.type, c.status, c.reason, c.message, c.transition_time
                )
                for k, c in self.conditions.items()
            },
        )

    def matches_node(self, node: "Node") -> bool:
        """Required node affinity + nodeSelector match."""
        return self.matches_labels(node.labels)

    def matches_labels(self, labels: Dict[str, str]) -> bool:
        """The k8s required-scheduling match against a label set
        (component-helpers nodeaffinity semantics, as the reference
        evaluates via GetRequiredNodeAffinity): nodeSelector entries AND;
        nodeSelectorTerms OR, expressions within a term AND."""
        for k, v in self.node_selector.items():
            if labels.get(k) != v:
                return False
        terms = self.affinity_terms
        if not terms and self.node_affinity:
            terms = [[(k, "In", values) for k, values in self.node_affinity.items()]]
        if not terms:
            return True
        return any(self._term_matches(term, labels) for term in terms)

    @staticmethod
    def _term_matches(term, labels: Dict[str, str]) -> bool:
        for key, operator, values in term:
            value = labels.get(key)
            if operator == "In":
                if value not in values:
                    return False
            elif operator == "NotIn":
                if value is not None and value in values:
                    return False
            elif operator == "Exists":
                if value is None:
                    return False
            elif operator == "DoesNotExist":
                if value is not None:
                    return False
            elif operator in ("Gt", "Lt"):
                try:
                    node_val = int(value)
                    want = int(values[0])
                except (TypeError, ValueError, IndexError):
                    return False
                if operator == "Gt" and not node_val > want:
                    return False
                if operator == "Lt" and not node_val < want:
                    return False
            else:
                return False  # unknown operator: fail closed
        return True


@dataclass
class PodCondition:
    type: str
    status: str  # "True" / "False"
    reason: str = ""
    message: str = ""
    transition_time: float = 0.0


@dataclass
class Node(APIObject):
    KIND = "Node"
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    allocatable: Resources = field(default_factory=Resources.zero)
    unschedulable: bool = False
    ready: bool = True

    @property
    def zone(self) -> str:
        from .resources import ZONE_LABEL, ZONE_LABEL_PLACEHOLDER

        return self.labels.get(ZONE_LABEL, ZONE_LABEL_PLACEHOLDER)

    def deepcopy(self) -> "Node":
        return Node(
            meta=self.meta.copy(),
            allocatable=self.allocatable,  # immutable value
            unschedulable=self.unschedulable,
            ready=self.ready,
        )


# ---------------------------------------------------------------------------
# ResourceReservation (v1beta2 storage schema,
# types_resource_reservation.go:23-103)
# ---------------------------------------------------------------------------


@dataclass
class Reservation:
    node: str
    resources: Dict[str, Quantity] = field(default_factory=dict)

    @staticmethod
    def for_resources(node: str, r: Resources) -> "Reservation":
        return Reservation(
            node=node,
            resources={
                RESOURCE_CPU: r.cpu,
                RESOURCE_MEMORY: r.memory,
                RESOURCE_NVIDIA_GPU: r.nvidia_gpu,
            },
        )

    def resources_value(self) -> Resources:
        return Resources(
            self.resources.get(RESOURCE_CPU, Quantity(0)),
            self.resources.get(RESOURCE_MEMORY, Quantity(0)),
            self.resources.get(RESOURCE_NVIDIA_GPU, Quantity(0)),
        )


@dataclass
class ResourceReservationSpec:
    reservations: Dict[str, Reservation] = field(default_factory=dict)


@dataclass
class ResourceReservationStatus:
    # reservation name → bound pod name (types_resource_reservation.go:99-103)
    pods: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceReservation(APIObject):
    KIND = "ResourceReservation"
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceReservationSpec = field(default_factory=ResourceReservationSpec)
    status: ResourceReservationStatus = field(default_factory=ResourceReservationStatus)

    def deepcopy(self) -> "ResourceReservation":
        return ResourceReservation(
            meta=self.meta.copy(),
            spec=ResourceReservationSpec(
                reservations={
                    name: Reservation(r.node, dict(r.resources))
                    for name, r in self.spec.reservations.items()
                }
            ),
            status=ResourceReservationStatus(pods=dict(self.status.pods)),
        )


# ---------------------------------------------------------------------------
# Demand (v1alpha2 storage schema, types_demand.go:29-157)
# ---------------------------------------------------------------------------


class DemandPhase:
    EMPTY = ""
    PENDING = "pending"
    FULFILLED = "fulfilled"
    CANNOT_FULFILL = "cannot-fulfill"


@dataclass
class DemandUnit:
    resources: Resources
    count: int
    # pod names this unit is for, keyed by namespace
    pod_names_by_namespace: Dict[str, List[str]] = field(default_factory=dict)


@dataclass
class DemandSpec:
    units: List[DemandUnit] = field(default_factory=list)
    instance_group: str = ""
    is_long_lived: bool = False
    enforce_single_zone_scheduling: bool = False
    zone: Optional[str] = None


@dataclass
class DemandStatus:
    phase: str = DemandPhase.EMPTY
    last_transition_time: float = 0.0
    fulfilled_zone: Optional[str] = None


@dataclass
class Demand(APIObject):
    KIND = "Demand"
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DemandSpec = field(default_factory=DemandSpec)
    status: DemandStatus = field(default_factory=DemandStatus)

    def deepcopy(self) -> "Demand":
        return Demand(
            meta=self.meta.copy(),
            spec=DemandSpec(
                units=[
                    DemandUnit(
                        resources=u.resources,  # immutable value
                        count=u.count,
                        pod_names_by_namespace={
                            ns: list(names)
                            for ns, names in u.pod_names_by_namespace.items()
                        },
                    )
                    for u in self.spec.units
                ],
                instance_group=self.spec.instance_group,
                is_long_lived=self.spec.is_long_lived,
                enforce_single_zone_scheduling=self.spec.enforce_single_zone_scheduling,
                zone=self.spec.zone,
            ),
            status=DemandStatus(
                phase=self.status.phase,
                last_transition_time=self.status.last_transition_time,
                fulfilled_zone=self.status.fulfilled_zone,
            ),
        )
