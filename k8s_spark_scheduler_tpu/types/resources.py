"""Resource algebra: the L4 layer of the reference.

Covers ``/root/reference/vendor/.../pkg/resources/resources.go`` —
``Resources`` (3-dim quantity vector), ``NodeGroupResources``,
``NodeSchedulingMetadata`` and the builders that derive availability from
node allocatable minus usage minus overhead.

Unlike the Go original (mutating methods on shared pointers), ``Resources``
here is an immutable value type: the scheduler core threads updated copies
explicitly, which keeps the snapshot → tensor marshalling for the TPU
solver trivially consistent (no aliasing).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, Iterable, Mapping, Optional

from ..utils.quantity import Quantity, QuantityLike, parse_quantity

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"

# zone label fallback when a node carries no zone label
# (reference resources.go:27, :78-81)
ZONE_LABEL_PLACEHOLDER = "default"
# failure-domain zone label key (reference uses corev1.LabelZoneFailureDomain
# for metadata and v1.LabelTopologyZone when filtering; both map here)
ZONE_LABEL = "topology.kubernetes.io/zone"


@dataclass(frozen=True)
class Resources:
    """CPU / Memory / NvidiaGPU quantity vector (resources.go:151-155)."""

    cpu: Quantity = field(default_factory=Quantity)
    memory: Quantity = field(default_factory=Quantity)
    nvidia_gpu: Quantity = field(default_factory=Quantity)

    @staticmethod
    def of(cpu: QuantityLike = 0, memory: QuantityLike = 0, nvidia_gpu: QuantityLike = 0) -> "Resources":
        return Resources(parse_quantity(cpu), parse_quantity(memory), parse_quantity(nvidia_gpu))

    @staticmethod
    def zero() -> "Resources":
        return Resources()

    def add(self, other: "Resources") -> "Resources":
        return Resources(
            self.cpu.add(other.cpu),
            self.memory.add(other.memory),
            self.nvidia_gpu.add(other.nvidia_gpu),
        )

    def sub(self, other: "Resources") -> "Resources":
        return Resources(
            self.cpu.sub(other.cpu),
            self.memory.sub(other.memory),
            self.nvidia_gpu.sub(other.nvidia_gpu),
        )

    def set_max(self, other: "Resources") -> "Resources":
        """Per-dimension max (resources.go:224-235)."""
        return Resources(
            other.cpu if other.cpu.cmp(self.cpu) > 0 else self.cpu,
            other.memory if other.memory.cmp(self.memory) > 0 else self.memory,
            other.nvidia_gpu if other.nvidia_gpu.cmp(self.nvidia_gpu) > 0 else self.nvidia_gpu,
        )

    def greater_than(self, other: "Resources") -> bool:
        """True if ANY dimension is greater (resources.go:239-241).

        ``demand.greater_than(available)`` is the reference's
        does-not-fit test.
        """
        return (
            self.cpu.cmp(other.cpu) > 0
            or self.memory.cmp(other.memory) > 0
            or self.nvidia_gpu.cmp(other.nvidia_gpu) > 0
        )

    def eq(self, other: "Resources") -> bool:
        return (
            self.cpu.cmp(other.cpu) == 0
            and self.memory.cmp(other.memory) == 0
            and self.nvidia_gpu.cmp(other.nvidia_gpu) == 0
        )

    def copy(self) -> "Resources":
        return self  # immutable

    def to_dict(self) -> Dict[str, str]:
        return {
            RESOURCE_CPU: self.cpu.serialize(),
            RESOURCE_MEMORY: self.memory.serialize(),
            RESOURCE_NVIDIA_GPU: self.nvidia_gpu.serialize(),
        }

    @staticmethod
    def from_dict(d: Mapping[str, QuantityLike]) -> "Resources":
        return Resources.of(
            d.get(RESOURCE_CPU, 0), d.get(RESOURCE_MEMORY, 0), d.get(RESOURCE_NVIDIA_GPU, 0)
        )

    def __repr__(self) -> str:
        return (
            f"Resources(cpu={self.cpu.serialize()}, memory={self.memory.serialize()}, "
            f"gpu={self.nvidia_gpu.serialize()})"
        )


# NodeGroupResources — map[node]Resources (resources.go:103).  Plain dict,
# with the reference's in-place Add/Sub helpers as functions.
NodeGroupResources = Dict[str, Resources]


def group_add(into: NodeGroupResources, other: NodeGroupResources) -> None:
    for node, r in other.items():
        into[node] = into.get(node, Resources.zero()).add(r)


def group_sub(into: NodeGroupResources, other: NodeGroupResources) -> None:
    for node, r in other.items():
        into[node] = into.get(node, Resources.zero()).sub(r)


@dataclass
class NodeSchedulingMetadata:
    """Per-node scheduling view (resources.go:158-166)."""

    available: Resources
    schedulable: Resources
    creation_timestamp: float = 0.0
    zone_label: str = ZONE_LABEL_PLACEHOLDER
    all_labels: Mapping[str, str] = field(default_factory=dict)
    unschedulable: bool = False
    ready: bool = True


NodeGroupSchedulingMetadata = Dict[str, NodeSchedulingMetadata]


def subtract_usage_if_exists(
    metadata: NodeGroupSchedulingMetadata, used: NodeGroupResources
) -> None:
    """Subtract usage per node, only for known nodes (resources.go:129-135).

    Mutates ``metadata`` entries' ``available`` in place (rebinds the
    immutable Resources value).
    """
    for node_name, used_resources in used.items():
        md = metadata.get(node_name)
        if md is not None:
            md.available = md.available.sub(used_resources)


def usage_for_nodes(resource_reservations: Iterable) -> NodeGroupResources:
    """Tally reserved resources per node from reservations
    (resources.go:31-43).  Accepts any iterable of objects exposing
    ``spec.reservations`` mapping name → object with .node / .resources.
    """
    usage: NodeGroupResources = {}
    for rr in resource_reservations:
        for reservation in rr.spec.reservations.values():
            node = reservation.node
            usage[node] = usage.get(node, Resources.zero()).add(reservation.resources_value())
    return usage


def available_for_nodes(nodes: Iterable, current_usage: NodeGroupResources) -> NodeGroupResources:
    """allocatable − usage per node (resources.go:46-56)."""
    out: NodeGroupResources = {}
    for node in nodes:
        used = current_usage.get(node.name, Resources.zero())
        out[node.name] = node.allocatable.sub(used)
    return out


def node_scheduling_metadata_for_nodes(
    nodes: Iterable,
    current_usage: NodeGroupResources,
    overhead_usage: NodeGroupResources,
) -> NodeGroupSchedulingMetadata:
    """available = allocatable − usage − overhead; schedulable =
    allocatable − overhead (resources.go:61-100)."""
    out: NodeGroupSchedulingMetadata = {}
    for node in nodes:
        overhead = overhead_usage.get(node.name, Resources.zero())
        used = current_usage.get(node.name, Resources.zero()).add(overhead)
        zone = node.labels.get(ZONE_LABEL, ZONE_LABEL_PLACEHOLDER)
        out[node.name] = NodeSchedulingMetadata(
            available=node.allocatable.sub(used),
            schedulable=node.allocatable.sub(overhead),
            creation_timestamp=node.creation_timestamp,
            zone_label=zone,
            all_labels=dict(node.labels),
            unschedulable=node.unschedulable,
            ready=node.ready,
        )
    return out


def create_scheduling_metadata(
    cpu: QuantityLike,
    memory: QuantityLike,
    nvidia_gpu: QuantityLike = 0,
    zone_label: str = ZONE_LABEL_PLACEHOLDER,
    schedulable: Optional[Resources] = None,
) -> NodeSchedulingMetadata:
    """Test helper mirroring CreateSchedulingMetadata (resources.go:260-266):
    schedulable defaults to effectively-infinite totals."""
    inf = Resources.of(2**62, 2**62, 2**62)
    return NodeSchedulingMetadata(
        available=Resources.of(cpu, memory, nvidia_gpu),
        schedulable=schedulable if schedulable is not None else inf,
        zone_label=zone_label,
    )


def copy_metadata(metadata: NodeGroupSchedulingMetadata) -> NodeGroupSchedulingMetadata:
    """Deep-enough copy for what the packers mutate (available)."""
    return {name: dataclasses.replace(md) for name, md in metadata.items()}
