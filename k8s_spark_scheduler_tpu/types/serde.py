"""Wire (de)serialization + CRD version conversion.

Covers the reference's k8s JSON shapes for the extender protocol and the
ResourceReservation v1beta1 ↔ v1beta2 conversion
(lib/pkg/apis/sparkscheduler/v1beta1/conversion_resource_reservation.go:
the v1beta1 schema is flat {Node, CPU, Memory}; lossless round-trips
keep a JSON copy of the full v1beta2 spec in the
``sparkscheduler.palantir.com/reservation-spec`` annotation), plus
Demand v1alpha1 ↔ v1alpha2 (flat resources vs resource list).
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any, Dict, List

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..utils.quantity import Quantity
from .extenderapi import ExtenderArgs, ExtenderFilterResult
from .objects import (
    Container,
    Demand,
    DemandSpec,
    DemandStatus,
    DemandUnit,
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodCondition,
    Reservation,
    ResourceReservation,
    ResourceReservationSpec,
    ResourceReservationStatus,
)
from .resources import RESOURCE_CPU, RESOURCE_MEMORY, Resources

GROUP_NAME = "sparkscheduler.palantir.com"
RESERVATION_SPEC_ANNOTATION_KEY = GROUP_NAME + "/reservation-spec"


# ---------------------------------------------------------------------------
# ObjectMeta
# ---------------------------------------------------------------------------


def ts_to_rfc3339(ts: float) -> str:
    """k8s metav1.Time wire form (UTC, second precision)."""
    import datetime

    return (
        datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


def _ts_from_wire(value) -> float:
    """Accept the embedded wire's float timestamps AND k8s RFC3339."""
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    import datetime

    try:
        return float(value)
    except (TypeError, ValueError):
        pass
    try:
        return datetime.datetime.strptime(
            str(value), "%Y-%m-%dT%H:%M:%SZ"
        ).replace(tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        return 0.0


def meta_to_dict(meta: ObjectMeta) -> dict:
    """Embedded-wire form (float timestamps); the REST backend converts
    to real k8s RFC3339 in one place (restbackend._k8s_wire)."""
    out: Dict[str, Any] = {
        "name": meta.name,
        "namespace": meta.namespace,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
        "creationTimestamp": meta.creation_timestamp,
        "resourceVersion": str(meta.resource_version),
        "uid": meta.uid,
    }
    if meta.owner_references:
        out["ownerReferences"] = [
            {
                "apiVersion": "v1",
                "kind": ref.kind,
                "name": ref.name,
                "uid": ref.uid,
                "controller": ref.controller,
            }
            for ref in meta.owner_references
        ]
    if meta.deletion_timestamp is not None:
        out["deletionTimestamp"] = meta.deletion_timestamp
    return out


def meta_from_dict(d: dict) -> ObjectMeta:
    rv_raw = d.get("resourceVersion", 0)
    try:
        rv = int(rv_raw)
    except (TypeError, ValueError):
        rv = 0
    deletion = d.get("deletionTimestamp")
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        creation_timestamp=_ts_from_wire(d.get("creationTimestamp")),
        deletion_timestamp=_ts_from_wire(deletion) if deletion is not None else None,
        resource_version=rv,
        uid=d.get("uid", ""),
        owner_references=[
            OwnerReference(
                kind=ref.get("kind", ""),
                name=ref.get("name", ""),
                uid=ref.get("uid", ""),
                controller=bool(ref.get("controller", True)),
            )
            for ref in d.get("ownerReferences") or []
        ],
    )


# ---------------------------------------------------------------------------
# Pod (k8s core/v1 subset used by the extender protocol)
# ---------------------------------------------------------------------------


def pod_from_dict(d: dict) -> Pod:
    meta = meta_from_dict(d.get("metadata") or {})
    spec = d.get("spec") or {}
    status = d.get("status") or {}

    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    affinity_terms: List[list] = []
    for term in required.get("nodeSelectorTerms") or []:
        parsed_term = [
            (expr.get("key", ""), expr.get("operator"), list(expr.get("values") or []))
            for expr in term.get("matchExpressions") or []
        ]
        if parsed_term:
            affinity_terms.append(parsed_term)
    # the simple In-map convenience view (instance-group extraction) is
    # only sound for a single all-In term
    node_affinity: Dict[str, List[str]] = {}
    if len(affinity_terms) == 1 and all(op == "In" for _, op, _ in affinity_terms[0]):
        node_affinity = {k: v for k, _, v in affinity_terms[0]}
        affinity_terms = []

    def _containers(key: str) -> List[Container]:
        out = []
        for c in spec.get(key) or []:
            requests = (c.get("resources") or {}).get("requests") or {}
            out.append(
                Container(name=c.get("name", "main"), requests=Resources.from_dict(requests))
            )
        return out

    conditions = {}
    for c in status.get("conditions") or []:
        ctype = c.get("type", "")
        conditions[ctype] = PodCondition(
            type=ctype,
            status=c.get("status", ""),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            transition_time=_ts_from_wire(c.get("lastTransitionTime")),
        )
    container_terminated = [
        "terminated" in ((cs.get("state") or {}))
        for cs in status.get("containerStatuses") or []
    ]

    return Pod(
        meta=meta,
        scheduler_name=spec.get("schedulerName", ""),
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        node_affinity=node_affinity,
        affinity_terms=affinity_terms,
        containers=_containers("containers"),
        # init containers count toward pod requests — max(sum, each init)
        # (reference overhead.go:195-209); dropping them under-counts
        # overhead for pods with large init steps
        init_containers=_containers("initContainers"),
        phase=status.get("phase", "Pending"),
        container_terminated=container_terminated,
        conditions=conditions,
    )


def pod_to_dict(pod: Pod) -> dict:
    if pod.affinity_terms:
        terms = [
            {
                "matchExpressions": [
                    {"key": k, "operator": op, "values": list(values)}
                    for k, op, values in term
                ]
            }
            for term in pod.affinity_terms
        ]
    elif pod.node_affinity:
        terms = [
            {
                "matchExpressions": [
                    {"key": k, "operator": "In", "values": v}
                    for k, v in pod.node_affinity.items()
                ]
            }
        ]
    else:
        terms = []

    def _containers_to_dicts(containers) -> list:
        return [
            {"name": c.name, "resources": {"requests": c.requests.to_dict()}}
            for c in containers
        ]

    spec = {
        "schedulerName": pod.scheduler_name,
        "nodeName": pod.node_name,
        "nodeSelector": dict(pod.node_selector),
        "affinity": {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": terms
                }
            }
        }
        if terms
        else {},
        "containers": _containers_to_dicts(pod.containers),
    }
    if pod.init_containers:
        spec["initContainers"] = _containers_to_dicts(pod.init_containers)
    status: Dict[str, Any] = {"phase": pod.phase}
    if pod.conditions:
        status["conditions"] = [
            {
                "type": c.type,
                "status": c.status,
                "reason": c.reason,
                "message": c.message,
                "lastTransitionTime": c.transition_time,
            }
            for c in pod.conditions.values()
        ]
    if pod.container_terminated:
        status["containerStatuses"] = [
            {"state": {"terminated": {}} if t else {"running": {}}}
            for t in pod.container_terminated
        ]
    return {
        "metadata": meta_to_dict(pod.meta),
        "spec": spec,
        "status": status,
    }


# ---------------------------------------------------------------------------
# Node (k8s core/v1 subset the scheduler reads:
# status.allocatable, spec.unschedulable, the Ready condition)
# ---------------------------------------------------------------------------


def node_to_dict(node: Node) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": meta_to_dict(node.meta),
        "spec": {"unschedulable": node.unschedulable} if node.unschedulable else {},
        "status": {
            "allocatable": node.allocatable.to_dict(),
            "conditions": [
                {"type": "Ready", "status": "True" if node.ready else "False"}
            ],
        },
    }


def node_from_dict(d: dict) -> Node:
    status = d.get("status") or {}
    ready = False
    for c in status.get("conditions") or []:
        if c.get("type") == "Ready":
            ready = c.get("status") == "True"
    return Node(
        meta=meta_from_dict(d.get("metadata") or {}),
        allocatable=Resources.from_dict(status.get("allocatable") or {}),
        unschedulable=bool((d.get("spec") or {}).get("unschedulable", False)),
        ready=ready,
    )


# ---------------------------------------------------------------------------
# Extender protocol
# ---------------------------------------------------------------------------


def extender_args_from_dict(d: dict) -> ExtenderArgs:
    return ExtenderArgs(
        pod=pod_from_dict(d.get("Pod") or d.get("pod") or {}),
        node_names=intern_node_names(
            list(d.get("NodeNames") or d.get("nodeNames") or [])
        ),
    )


def extender_filter_result_to_dict(result: ExtenderFilterResult) -> dict:
    return result.to_dict()


# -- node-name interning + response-buffer reuse ------------------------------
#
# kube-scheduler sends the SAME candidate node-name list (10k strings,
# ~200KB of JSON) on every Filter request, and the extender's failure
# responses serialize a FailedNodes map over that same list with one
# shared message.  Interning the parsed list gives every downstream
# consumer a stable tuple object: identity-keyed caches (the uniform
# failure-response encoder below) become exact, the per-request garbage
# of 10k strings disappears, and the fast-path prep key's candidate
# tuple is shared instead of rebuilt.  Correctness never rests on the
# fingerprint: a candidate is returned only after a full element-wise
# compare (C-speed list/tuple equality), so a fingerprint collision
# costs a compare, not a wrong candidate list.


@guarded_by("_lock", "_entries", "hits", "misses")
class NodeNamesInterner:
    """Bounded exact-verified intern pool for candidate node-name lists.

    Bounded on BOTH axes: at most MAX_ENTRIES distinct fingerprints, and
    at most MAX_PER_BUCKET variants per fingerprint — interior node
    churn that keeps (len, first, last, middle) stable must rotate a
    bucket, not grow it."""

    MAX_ENTRIES = 8
    MAX_PER_BUCKET = 4

    def __init__(self):
        self._lock = threading.Lock()
        # fingerprint → list of interned tuples sharing it
        self._entries: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.metrics = None  # optional registry, set by server wiring

    @staticmethod
    def _fingerprint(names) -> tuple:
        n = len(names)
        if n == 0:
            return (0,)
        return (n, names[0], names[-1], names[n // 2])

    def intern(self, names: list) -> tuple:
        incoming = tuple(names)
        fp = self._fingerprint(incoming)
        hit = None
        with self._lock:
            racecheck.note_access(self, "_entries")
            bucket = self._entries.get(fp)
            if bucket is not None:
                self._entries.move_to_end(fp)
                for cand in bucket:
                    # exact verification — the fingerprint only routes
                    if cand == incoming:
                        hit = cand
                        break
            if hit is not None:
                self.hits += 1
            else:
                if bucket is None:
                    bucket = []
                    self._entries[fp] = bucket
                bucket.append(incoming)
                while len(bucket) > self.MAX_PER_BUCKET:
                    bucket.pop(0)
                self.misses += 1
                while len(self._entries) > self.MAX_ENTRIES:
                    self._entries.popitem(last=False)
        # metrics outside the intern lock (registry has its own)
        self._count("hit" if hit is not None else "miss")
        return hit if hit is not None else incoming

    def _count(self, kind: str) -> None:
        m = self.metrics
        if m is not None:
            from ..metrics import names as mnames

            m.counter(
                mnames.SERDE_INTERN_HITS
                if kind == "hit"
                else mnames.SERDE_INTERN_MISSES
            )

    def size(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._entries.values())


names_interner = NodeNamesInterner()


def intern_node_names(names: list) -> tuple:
    return names_interner.intern(names)


@guarded_by("_lock", "_cache")
class UniformFailureEncoder:
    """Reusable encoded-response buffers for uniform all-nodes failures.

    A Filter failure answers ``{node: message for node in candidates}``
    — at 10k candidates that is ~2-5 ms of json.dumps per response, for
    bytes that are identical across every request sharing the (interned
    candidate tuple, message) pair.  Entries pin the names tuple they
    were built for and verify identity on hit, so an id() recycled
    after eviction can never alias."""

    MAX_ENTRIES = 16

    def __init__(self):
        self._lock = threading.Lock()
        # (id(names), message) → (names, encoded bytes)
        self._cache: OrderedDict = OrderedDict()

    def encode(self, names: tuple, message: str, error: str = "") -> bytes:
        key = (id(names), message, error)
        with self._lock:
            racecheck.note_access(self, "_cache")
            hit = self._cache.get(key)
            if hit is not None and hit[0] is names:
                self._cache.move_to_end(key)
                return hit[1]
        encoded = json.dumps(
            {
                "NodeNames": None,
                "FailedNodes": {n: message for n in names} or None,
                "Error": error or None,
            }
        ).encode()
        with self._lock:
            racecheck.note_access(self, "_cache")
            self._cache[key] = (names, encoded)
            while len(self._cache) > self.MAX_ENTRIES:
                self._cache.popitem(last=False)
        return encoded

    def size(self) -> int:
        with self._lock:
            return len(self._cache)


uniform_failure_encoder = UniformFailureEncoder()


def encode_extender_filter_result(result: ExtenderFilterResult) -> bytes:
    """Encoded response body, served from the reusable buffer pool when
    the result is a uniform all-nodes failure over an interned candidate
    tuple (ExtenderFilterResult.uniform_failure, set by the extender's
    failure paths); a fresh dumps otherwise."""
    uniform = getattr(result, "uniform_failure", None)
    if (
        uniform is not None
        and isinstance(uniform[0], tuple)
        and len(result.failed_nodes) == len(uniform[0])
        and not result.node_names
    ):
        names, message = uniform
        return uniform_failure_encoder.encode(names, message, result.error)
    return json.dumps(result.to_dict()).encode()


# ---------------------------------------------------------------------------
# ResourceReservation v1beta2 (storage) + v1beta1 (served)
# ---------------------------------------------------------------------------


def rr_spec_to_dict_v1beta2(spec: ResourceReservationSpec) -> dict:
    return {
        "reservations": {
            name: {
                "node": res.node,
                "resources": {k: q.serialize() for k, q in res.resources.items()},
            }
            for name, res in spec.reservations.items()
        }
    }


def rr_spec_from_dict_v1beta2(d: dict) -> ResourceReservationSpec:
    reservations = {}
    for name, r in (d.get("reservations") or {}).items():
        reservations[name] = Reservation(
            node=r.get("node", ""),
            resources={k: Quantity(v) for k, v in (r.get("resources") or {}).items()},
        )
    return ResourceReservationSpec(reservations=reservations)


def rr_to_dict_v1beta2(rr: ResourceReservation) -> dict:
    return {
        "apiVersion": f"{GROUP_NAME}/v1beta2",
        "kind": "ResourceReservation",
        "metadata": meta_to_dict(rr.meta),
        "spec": rr_spec_to_dict_v1beta2(rr.spec),
        "status": {"pods": dict(rr.status.pods)},
    }


def rr_from_dict_v1beta2(d: dict) -> ResourceReservation:
    return ResourceReservation(
        meta=meta_from_dict(d.get("metadata") or {}),
        spec=rr_spec_from_dict_v1beta2(d.get("spec") or {}),
        status=ResourceReservationStatus(pods=dict((d.get("status") or {}).get("pods") or {})),
    )


def rr_to_dict_v1beta1(rr: ResourceReservation) -> dict:
    """ConvertFrom (v1beta2 → v1beta1), conversion_resource_reservation.go:
    86-121: flat {node,cpu,memory} reservations + full v1beta2 spec JSON
    kept in the reservation-spec annotation for lossless round trips."""
    meta = meta_to_dict(rr.meta)
    annotations = dict(meta.get("annotations") or {})
    annotations[RESERVATION_SPEC_ANNOTATION_KEY] = json.dumps(
        rr_spec_to_dict_v1beta2(rr.spec), sort_keys=True
    )
    meta["annotations"] = annotations
    return {
        "apiVersion": f"{GROUP_NAME}/v1beta1",
        "kind": "ResourceReservation",
        "metadata": meta,
        "spec": {
            "reservations": {
                name: {
                    "node": res.node,
                    "cpu": res.resources.get(RESOURCE_CPU, Quantity(0)).serialize(),
                    "memory": res.resources.get(RESOURCE_MEMORY, Quantity(0)).serialize(),
                }
                for name, res in rr.spec.reservations.items()
            }
        },
        "status": {"pods": dict(rr.status.pods)},
    }


def rr_from_dict_v1beta1(d: dict) -> ResourceReservation:
    """ConvertTo (v1beta1 → v1beta2), conversion_resource_reservation.go:
    28-83: base values from the flat struct; any extra resource
    dimensions (e.g. GPU) recovered from the reservation-spec annotation;
    the annotation itself is dropped from the converted object."""
    meta = meta_from_dict(d.get("metadata") or {})
    annotation_json = meta.annotations.pop(RESERVATION_SPEC_ANNOTATION_KEY, None)

    reservations: Dict[str, Reservation] = {}
    for name, r in ((d.get("spec") or {}).get("reservations") or {}).items():
        reservations[name] = Reservation(
            node=r.get("node", ""),
            resources={
                RESOURCE_CPU: Quantity(r.get("cpu", "0")),
                RESOURCE_MEMORY: Quantity(r.get("memory", "0")),
            },
        )

    if annotation_json:
        try:
            annotation_spec = rr_spec_from_dict_v1beta2(json.loads(annotation_json))
        except (ValueError, TypeError):
            annotation_spec = None
        if annotation_spec is not None:
            for name, annotation_res in annotation_spec.reservations.items():
                existing = reservations.get(name)
                if existing is None:
                    continue
                for resource_name, quantity in annotation_res.resources.items():
                    if resource_name not in existing.resources:
                        existing.resources[resource_name] = quantity

    return ResourceReservation(
        meta=meta,
        spec=ResourceReservationSpec(reservations=reservations),
        status=ResourceReservationStatus(pods=dict((d.get("status") or {}).get("pods") or {})),
    )


def convert_rr(obj: dict, desired_api_version: str) -> dict:
    """Webhook conversion entry: any served version → desired version."""
    api_version = obj.get("apiVersion", "")
    if api_version == desired_api_version:
        return obj
    if api_version.endswith("v1beta1"):
        hub = rr_from_dict_v1beta1(obj)
    elif api_version.endswith("v1beta2"):
        hub = rr_from_dict_v1beta2(obj)
    else:
        raise ValueError(f"unknown apiVersion {api_version}")
    if desired_api_version.endswith("v1beta2"):
        return rr_to_dict_v1beta2(hub)
    if desired_api_version.endswith("v1beta1"):
        return rr_to_dict_v1beta1(hub)
    raise ValueError(f"unknown desired apiVersion {desired_api_version}")


# ---------------------------------------------------------------------------
# Demand v1alpha2 (storage) + v1alpha1
# ---------------------------------------------------------------------------

SCALER_GROUP = "scaler.palantir.com"


def demand_to_dict_v1alpha2(demand: Demand) -> dict:
    return {
        "apiVersion": f"{SCALER_GROUP}/v1alpha2",
        "kind": "Demand",
        "metadata": meta_to_dict(demand.meta),
        "spec": {
            "units": [
                {
                    "resources": u.resources.to_dict(),
                    "count": u.count,
                    "podNamesByNamespace": {k: list(v) for k, v in u.pod_names_by_namespace.items()},
                }
                for u in demand.spec.units
            ],
            "instanceGroup": demand.spec.instance_group,
            "isLongLived": demand.spec.is_long_lived,
            "enforceSingleZoneScheduling": demand.spec.enforce_single_zone_scheduling,
            "zone": demand.spec.zone,
        },
        "status": {
            "phase": demand.status.phase,
            "lastTransitionTime": demand.status.last_transition_time,
            "fulfilledZone": demand.status.fulfilled_zone,
        },
    }


def demand_from_dict_v1alpha2(d: dict) -> Demand:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    units = [
        DemandUnit(
            resources=Resources.from_dict(u.get("resources") or {}),
            count=int(u.get("count", 0)),
            pod_names_by_namespace={
                k: list(v) for k, v in (u.get("podNamesByNamespace") or {}).items()
            },
        )
        for u in spec.get("units") or []
    ]
    return Demand(
        meta=meta_from_dict(d.get("metadata") or {}),
        spec=DemandSpec(
            units=units,
            instance_group=spec.get("instanceGroup", ""),
            is_long_lived=bool(spec.get("isLongLived", False)),
            enforce_single_zone_scheduling=bool(spec.get("enforceSingleZoneScheduling", False)),
            zone=spec.get("zone"),
        ),
        status=DemandStatus(
            phase=status.get("phase", ""),
            last_transition_time=float(status.get("lastTransitionTime") or 0.0),
            fulfilled_zone=status.get("fulfilledZone"),
        ),
    )


def demand_to_dict_v1alpha1(demand: Demand) -> dict:
    """v1alpha1 units use flat cpu/memory fields (types_demand.go v1alpha1)."""
    d = demand_to_dict_v1alpha2(demand)
    d["apiVersion"] = f"{SCALER_GROUP}/v1alpha1"
    for u, unit in zip(d["spec"]["units"], demand.spec.units):
        resources = u.pop("resources")
        u["cpu"] = resources[RESOURCE_CPU]
        u["memory"] = resources[RESOURCE_MEMORY]
    return d


def demand_from_dict_v1alpha1(d: dict) -> Demand:
    converted = json.loads(json.dumps(d))
    for u in (converted.get("spec") or {}).get("units") or []:
        u["resources"] = {
            RESOURCE_CPU: u.pop("cpu", "0"),
            RESOURCE_MEMORY: u.pop("memory", "0"),
        }
    return demand_from_dict_v1alpha2(converted)
