from .quantity import Quantity, parse_quantity
