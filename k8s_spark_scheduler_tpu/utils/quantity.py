"""Exact-arithmetic Kubernetes resource quantities.

The reference scheduler does all feasibility math on k8s
``resource.Quantity`` values (arbitrary-precision decimals with SI /
binary-SI suffixes) — see
``/root/reference/vendor/.../pkg/resources/resources.go:151-155`` and the
capacity floor-division at
``/root/reference/vendor/.../pkg/capacity/capacity.go:36-54`` which uses
``inf.Dec`` exact arithmetic.  Feasibility decisions must therefore never
go through floats.  We represent a quantity as an exact
``fractions.Fraction`` which is a strict superset of inf.Dec's decimals,
so every reference result is reproduced bit-for-bit.

The TPU batch solver works on integer tensors (milli-CPU / bytes /
milli-GPU); :meth:`Quantity.milli_value_exact` reports whether a value is
exactly representable so the solver can guarantee oracle parity.
"""

from __future__ import annotations

import math
import re
from fractions import Fraction
from typing import Union

_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}

# decimal exponent ("1e3") takes precedence over the "E" (exa) suffix,
# matching k8s parsing: the exponent form requires digits after e/E.
_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)"
    r"(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<exp>[eE][+-]?\d+)|(?P<suffix>n|u|m|k|M|G|T|P|E|Ki|Mi|Gi|Ti|Pi|Ei))?$"
)

QuantityLike = Union["Quantity", str, int, float, Fraction]


class Quantity:
    """An exact, immutable resource quantity.

    Mirrors the observable behavior of k8s ``resource.Quantity``: exact
    decimal arithmetic, any-precision compare, and ceil-to-int64
    ``value()`` / ``milli_value()`` accessors.
    """

    __slots__ = ("_v", "_s")

    def __init__(self, value: QuantityLike = 0, _s: str | None = None):
        if isinstance(value, Quantity):
            self._v = value._v
            self._s = value._s
        elif isinstance(value, str):
            self._v = _parse(value)
            self._s = value
        elif isinstance(value, (int, Fraction)):
            self._v = Fraction(value)
            self._s = _s
        elif isinstance(value, float):
            if not value.is_integer():
                raise ValueError(
                    f"refusing to build a Quantity from non-integral float {value!r}; "
                    "use a string or Fraction for exactness"
                )
            self._v = Fraction(int(value))
            self._s = _s
        else:
            raise TypeError(f"cannot build Quantity from {type(value)!r}")

    # -- accessors ---------------------------------------------------------

    @property
    def exact(self) -> Fraction:
        return self._v

    def value(self) -> int:
        """Ceil to integer, like k8s Quantity.Value()."""
        return math.ceil(self._v)

    def milli_value(self) -> int:
        """Ceil of value*1000, like k8s Quantity.MilliValue()."""
        return math.ceil(self._v * 1000)

    def milli_value_exact(self) -> tuple[int, bool]:
        """(milli value, whether the quantity is exactly milli-integral)."""
        v = self._v * 1000
        return math.ceil(v), v.denominator == 1

    def is_zero(self) -> bool:
        return self._v == 0

    # -- arithmetic (immutable; callers rebind) ----------------------------

    def add(self, other: "Quantity") -> "Quantity":
        return Quantity(self._v + other._v)

    def sub(self, other: "Quantity") -> "Quantity":
        return Quantity(self._v - other._v)

    def neg(self) -> "Quantity":
        return Quantity(-self._v)

    def cmp(self, other: "Quantity") -> int:
        if self._v < other._v:
            return -1
        if self._v > other._v:
            return 1
        return 0

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Quantity) and self._v == other._v

    def __lt__(self, other: "Quantity") -> bool:
        return self._v < other._v

    def __le__(self, other: "Quantity") -> bool:
        return self._v <= other._v

    def __hash__(self) -> int:
        return hash(self._v)

    def __repr__(self) -> str:
        return f"Quantity({self.serialize()!r})"

    # -- serialization ------------------------------------------------------

    def serialize(self) -> str:
        """A parseable string form. Round-trips the original text if the
        quantity was built from one; otherwise emits a canonical decimal.
        """
        if self._s is not None:
            return self._s
        return _format(self._v)

    def copy(self) -> "Quantity":
        return self  # immutable


def _parse(s: str) -> Fraction:
    text = s.strip()
    m = _QUANTITY_RE.match(text)
    if m is None:
        raise ValueError(f"unparseable quantity {s!r}")
    num = Fraction(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    exp = m.group("exp")
    if exp:
        num *= Fraction(10) ** int(exp[1:])
    suffix = m.group("suffix") or ""
    return num * _SUFFIXES[suffix]


def _format(v: Fraction) -> str:
    if v.denominator == 1:
        return str(v.numerator)
    milli = v * 1000
    if milli.denominator == 1:
        return f"{milli.numerator}m"
    nano = v * 10**9
    if nano.denominator == 1:
        return f"{nano.numerator}n"
    # fall back to an exact decimal expansion if possible, else a fraction
    # of nano-units rounded up (never rounds availability up vs demand:
    # callers only hit this path for display).
    return f"{math.ceil(nano)}n"


def parse_quantity(s: QuantityLike) -> Quantity:
    return s if isinstance(s, Quantity) else Quantity(s)


def zero() -> Quantity:
    return Quantity(0)
