"""Subprocess probe for a wedge-prone accelerator backend.

The dev TPU here sits behind a relay whose backend init can block
forever (uninterruptibly — even SIGKILL may not collect the child).
Probing in a detached subprocess with a poll loop keeps the calling
process unblocked no matter what the child does.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Optional

_PROBE_SRC = "import jax; print(jax.default_backend())"


def run_detached(argv, timeout_s: float, stdout, stderr) -> Optional[int]:
    """Run ``argv`` detached with a poll-loop timeout; returns the exit
    code, or None when it was still running at the deadline (killed, and
    reaped only if the kill lands).

    Popen + a poll loop — never a blocking wait — because a wedged child
    can sit in uninterruptible device I/O where ``communicate()`` after
    kill() blocks forever too.  ``start_new_session`` keeps terminal
    signals away from the child.
    """
    child = subprocess.Popen(
        argv, stdout=stdout, stderr=stderr, start_new_session=True
    )
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and child.poll() is None:
        time.sleep(0.5)
    code = child.poll()
    if code is None:
        child.kill()
        try:  # reap if the kill lands; wait(timeout) polls, never blocks
            child.wait(timeout=1)
        except subprocess.TimeoutExpired:
            pass
        return None
    return code


def probe_default_backend(
    timeout_s: float = 120.0, nice: bool = False
) -> Optional[str]:
    """Return the default jax backend name ("tpu", "cpu", ...), or None
    when backend init hangs past ``timeout_s`` or exits nonzero.

    nice=True runs the probe child under ``nice -n 19`` — for callers
    like the TPU sentinel whose repeated probes must never perturb
    latency measurements sharing the single-core dev host.  It stays
    OFF by default: a starved probe under CPU contention can time out
    spuriously, and e.g. the entry() CPU-pinning probe must not
    mis-diagnose a healthy relay as wedged because a bench was running.
    """
    argv = [sys.executable, "-c", _PROBE_SRC]
    if nice:
        import shutil

        nice_bin = shutil.which("nice")
        if nice_bin:
            argv = [nice_bin, "-n", "19"] + argv
    with tempfile.TemporaryFile() as outf, tempfile.TemporaryFile() as errf:
        code = run_detached(argv, timeout_s, outf, errf)
        if code is None:
            print(
                f"backend probe hung past {timeout_s:.0f}s (relay wedged?)",
                file=sys.stderr,
            )
            return None
        if code != 0:
            errf.seek(0)
            print(
                "backend probe failed:\n"
                + errf.read().decode(errors="replace")[-500:],
                file=sys.stderr,
            )
            return None
        outf.seek(0)
        return outf.read().decode(errors="replace").strip() or None


def live_platforms() -> str:
    """The effective jax_platforms value: the live config (authoritative —
    this container's sitecustomize pins it via jax.config.update, which
    env vars cannot override after import) falling back to the env var
    for processes where jax reads JAX_PLATFORMS at import normally."""
    try:
        import jax

        live = getattr(jax.config, "jax_platforms", None)
    except Exception:
        live = None
    if live:
        return str(live)
    return os.environ.get("JAX_PLATFORMS", "") or ""
