// Native FIFO queue gang solver — the host-CPU lane of the batch
// solver (ops/batch_solver.py::solve_queue), for deployments without a
// TPU and for the bench's CPU fallback.
//
// Replicates the device solver's decisions BIT-EXACTLY (same capacity
// rule as reference capacity.go:36-75 with the negative-availability
// short-circuit; same first-priority driver choice binpack.go:60-87;
// same usage-subtraction quirk sparkpods.go:139-146): the parity suite
// (tests/test_native_fifo.py) runs the randomized differential against
// solve_queue for both tightly-pack and distribute-evenly.
//
// Design notes for the one-core host this runs on:
//  - per app, per-node capacity needs a floor-division per nonzero
//    executor dimension; int32/int32 division done in double is exact
//    (|numerator| < 2^31 and numerator = q*den ⟹ representable; a
//    non-integer quotient is ≥ 1/den > ulp away from any integer since
//    num·den < 2^52) and, unlike integer division, vectorizes.
//  - driver choice walks a rank-sorted candidate list (built once per
//    queue: driver_rank is constant) and computes the with-driver
//    capacity lazily — almost always a handful of probes instead of a
//    second full N-vector pass.
//  - all int32 arithmetic wraps exactly like XLA's (unsigned ops).
//
// C ABI via ctypes (k8s_spark_scheduler_tpu/native/fifo.py).

#include <algorithm>
#include <array>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

constexpr int kDims = 3;
constexpr int32_t kBig = 2147483647;  // batch_solver.BIG

inline int32_t wrap_sub(int32_t a, int32_t b) {
  return static_cast<int32_t>(static_cast<uint32_t>(a) -
                              static_cast<uint32_t>(b));
}

// Per-node executor capacity clamped to [0, k] (capacity.go:36-75 via
// batch_solver.node_capacity): zero-requirement dim is unbounded unless
// availability is negative; any value ≤ 0 clips to 0, so truncating
// division equals the device kernel's floor division after the clip.
// A negative requirement divides by 1 like the host's max(executor, 1)
// (unreachable with valid tensorized Resources, but the parity contract
// covers the whole int32 input domain).
inline int32_t clamped_cap(const int32_t* a, const int32_t* e, int32_t k) {
  int32_t cap = k;
  for (int j = 0; j < kDims; ++j) {
    int32_t c;
    if (e[j] == 0) {
      c = a[j] >= 0 ? kBig : 0;
    } else if (a[j] <= 0) {
      c = 0;
    } else {
      c = static_cast<int32_t>(static_cast<double>(a[j]) /
                               static_cast<double>(std::max(e[j], 1)));
    }
    cap = std::min(cap, c);
  }
  return std::max(cap, 0);
}

// Capacity pass, restructured dim-at-a-time (r5): one sweep per nonzero
// executor dimension over that dimension's availability plane, then a
// finalize sweep.  Measured 2.3x faster than the fused 3-dim loop at
// 10k nodes (/tmp-style A/B harness, NOTES_ROUND4 discipline): the
// single-dim loops vectorize cleanly where the fused body's register
// pressure defeated gcc, and the cap array stays L1/L2-resident between
// sweeps.  Division is reciprocal-multiply with an exact two-step
// integer correction: q0 = trunc(a * (1/e)) is within ±1 of floor(a/e)
// (abs error ≤ 2^31 * 2^-51 « 1/2), and the corrections pin q to the
// largest q with q*e ≤ a — exact floor.  floor == truncation for
// positive quotients; for negative quotients they differ, but every
// consumer clamps at 0 / keys on the sign, so only the sign of a
// non-positive capacity must match the fused pass (it does).
//
// Zero-requirement dims bound capacity only when the availability is
// already overdrawn: cap forced ≤ 0 (kZeroDimNeg) so the finalize clamp
// zeroes it — same observable result as the fused pass's explicit 0/-1.

// first nonzero dim: initializes cap = min(init, floor(a/e))
static inline void dim_first(const int32_t* a, int64_t nb, int32_t e,
                             int32_t init, int32_t* cap) {
  const int32_t d = std::max(e, 1);  // negative req divides by 1
  const double inv = 1.0 / static_cast<double>(d);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += ((static_cast<int64_t>(q) + 1) * d <= a[i]);
    q -= (static_cast<int64_t>(q) * d > a[i]);
    cap[i] = std::min(init, q);
  }
}

// subsequent nonzero dims: cap = min(cap, floor(a/e))
static inline void dim_next(const int32_t* a, int64_t nb, int32_t e,
                            int32_t* cap) {
  const int32_t d = std::max(e, 1);
  const double inv = 1.0 / static_cast<double>(d);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += ((static_cast<int64_t>(q) + 1) * d <= a[i]);
    q -= (static_cast<int64_t>(q) * d > a[i]);
    cap[i] = std::min(cap[i], q);
  }
}

// zero-requirement dim: negative availability forces cap non-positive
static inline void dim_zero_mask(const int32_t* a, int64_t nb,
                                 int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) cap[i] = a[i] >= 0 ? cap[i] : int32_t{-1};
}

// shared sweep plan: division dims then zero-dim masks, cap initialized
// to `init` (k for the clamped pass, kMfSent for min-frag)
static inline void cap_sweeps(const int32_t* a0, const int32_t* a1,
                              const int32_t* a2, int64_t nb,
                              const int32_t* e, int32_t init, int32_t* cap) {
  const int32_t* planes[kDims] = {a0, a1, a2};
  int nz[kDims], nnz = 0, zd[kDims], nzd = 0;
  for (int j = 0; j < kDims; ++j) {
    if (e[j] != 0) nz[nnz++] = j; else zd[nzd++] = j;
  }
  if (nnz == 0) {
    std::fill(cap, cap + nb, init);
  } else {
    dim_first(planes[nz[0]], nb, e[nz[0]], init, cap);
    for (int t = 1; t < nnz; ++t) dim_next(planes[nz[t]], nb, e[nz[t]], cap);
  }
  for (int t = 0; t < nzd; ++t) dim_zero_mask(planes[zd[t]], nb, cap);
}

// clamped capacity pass (solve_queue): cap in [0, k], Σ cap returned
int64_t cap_pass_all(const int32_t* a0, const int32_t* a1, const int32_t* a2,
                     const uint8_t* exec_ok, int64_t nb, const int32_t* e,
                     int32_t k, int32_t* cap) {
  cap_sweeps(a0, a1, a2, nb, e, k, cap);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? cap[i] : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

// ---------------------------------------------------------------------------
// Minimal-fragmentation drain (minimal_fragmentation.go:59-137 semantics,
// matching ops/batch_adapter.minimal_fragmentation_from_capacities and —
// under the solver's MF sentinel guard — the device kernel
// batch_solver.min_frag_counts).
// ---------------------------------------------------------------------------

// Unbounded-capacity sentinel (the device kernel's batch_solver.MF_SENT):
// callers hold the mf_sentinel_safe guard (scaled availabilities ≤
// MF_SENT − 1), so a real capacity can never collide with it and the
// explicit has-sentinel subset rule below equals the host decode's
// 2^62-sentinel (k + max)/2 formula.
constexpr int32_t kMfSent = 2147483646;

inline int64_t floor_div32(int32_t a, int32_t b) {  // b > 0
  return a >= 0 ? a / b : -((-(int64_t)a + b - 1) / b);
}

// UNCLAMPED per-node capacity for the min-frag drain (capacity.go:36-75:
// floor division per dim; zero-requirement dim unbounded unless the
// availability is already negative; negative requirement divides by 1).
inline int32_t mf_cap_one(int32_t a0, int32_t a1, int32_t a2,
                          const int32_t* e) {
  const int32_t a[kDims] = {a0, a1, a2};
  int64_t cap = kMfSent;
  for (int j = 0; j < kDims; ++j) {
    int64_t c;
    if (e[j] == 0) {
      c = a[j] >= 0 ? kMfSent : 0;
    } else {
      c = floor_div32(a[j], std::max(e[j], 1));
    }
    cap = std::min(cap, c);
  }
  return static_cast<int32_t>(std::max<int64_t>(cap, 0));
}

// Whole-axis min-frag capacity pass, built on the shared dim-at-a-time
// sweeps (cap_sweeps with a kMfSent init).  Writes UNCLAMPED exact-floor
// capacities (values ≤ 0 mean ineligible) and returns Σ clamp(c, 0, k),
// the tightly feasibility total, so the min-frag queue step needs no
// separate feasibility pass over the node axis.
// Branchless extremes of a capacity vector, folded into the pass (and
// recomputable standalone after the driver-node fix-up): the max, the
// smallest capacity ≥ k, and the smallest positive capacity.  These
// three values decide the whole min-frag attempt structure (see
// mf_assign); the standalone scan vectorizes fully (~0.3 us at 10k
// nodes), so it runs after the driver-node fix-up rather than fused
// into the pass (where the extra accumulators break vectorization).
struct MfExtremes {
  int32_t maxc = 0;
  int32_t min_ge = kBig;   // min capacity ≥ k (kBig = none)
  int32_t min_pos = kBig;  // min capacity > 0 (kBig = none)
};

int64_t mf_cap_pass_all(const int32_t* a0, const int32_t* a1,
                        const int32_t* a2, const uint8_t* elig, int64_t nb,
                        const int32_t* e, int32_t k, int32_t* cap) {
  cap_sweeps(a0, a1, a2, nb, e, kMfSent, cap);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = elig[i] ? cap[i] : 0;
    cap[i] = c;
    total += std::clamp<int32_t>(c, 0, k);
  }
  return total;
}

// pure single-accumulator reductions vectorize; the fused 3-accumulator
// select loop does not (measured 20 us vs 3.6 us at 10k — r5 A/B), so
// the conditional mins are a select MAP into scratch followed by a pure
// min REDUCE.
__attribute__((noinline)) int32_t reduce_max(const int32_t* p, int64_t n) {
  int32_t m = 0;
  for (int64_t i = 0; i < n; ++i) m = std::max(m, p[i]);
  return m;
}

__attribute__((noinline)) int32_t reduce_min(const int32_t* p, int64_t n) {
  int32_t m = kBig;
  for (int64_t i = 0; i < n; ++i) m = std::min(m, p[i]);
  return m;
}

MfExtremes mf_extremes(const std::vector<int32_t>& caps, int32_t k,
                       std::vector<int32_t>& scratch) {
  MfExtremes ext;
  const int32_t* p = caps.data();
  const int64_t n = static_cast<int64_t>(caps.size());
  scratch.resize(n);
  int32_t* s = scratch.data();
  ext.maxc = reduce_max(p, n);
  for (int64_t i = 0; i < n; ++i) s[i] = p[i] >= k ? p[i] : kBig;
  ext.min_ge = reduce_min(s, n);
  for (int64_t i = 0; i < n; ++i) s[i] = p[i] > 0 ? p[i] : kBig;
  ext.min_pos = reduce_min(s, n);
  return ext;
}

// (node, executors-placed) segments in DRAIN order — the reference's
// placement list order, which the single-AZ zone score consumes as the
// occurrence sequence.  Nodes are unique across segments.
using MfSegs = std::vector<std::pair<int32_t, int64_t>>;

// minimal_fragmentation.go:96-137 WITHOUT the sort: the ascending-order
// drain only ever consults (a) the first sorted entry with cap ≥ k —
// i.e. the smallest such capacity, earliest node among equals — and
// (b) the max-capacity class in node order, so two O(N) scans per drain
// round replace the O(N log N) sort (a 10k-node sort per app dominated
// the whole queue pass).  Round count is bounded by the number of fully
// drained classes, itself ≤ k.  `caps` is by-node (≤ 0 = ineligible)
// and is consumed (drained entries zeroed).
bool mf_drain(std::vector<int32_t>& caps, int64_t k, MfSegs& segs) {
  const int64_t nb = static_cast<int64_t>(caps.size());
  while (true) {
    int64_t best = -1;
    int32_t best_cap = 0, maxc = 0;
    for (int64_t i = 0; i < nb; ++i) {
      const int32_t c = caps[i];
      if (c <= 0) continue;
      if (c >= k && (best < 0 || c < best_cap)) {
        best = i;
        best_cap = c;
      }
      if (c > maxc) maxc = c;
    }
    if (best >= 0) {  // first node that can fit everything that's left
      segs.emplace_back(static_cast<int32_t>(best), k);
      return true;
    }
    if (maxc <= 0) return false;
    // drain the max-capacity class in node order
    for (int64_t i = 0; i < nb && k >= maxc; ++i) {
      if (caps[i] == maxc) {
        segs.emplace_back(static_cast<int32_t>(i), maxc);
        k -= maxc;
        caps[i] = 0;
      }
    }
    if (k == 0) return true;
  }
}

// Scratch for the bucketed drain, reused across apps (allocation-free
// steady state).
struct MfScratch {
  std::vector<int32_t> bucket_count;   // per capacity value in [1, k)
  std::vector<int32_t> bucket_offset;  // cursor into nodes (consumed prefix)
  std::vector<int32_t> bucket_end;
  std::vector<int32_t> nodes;          // bucket-grouped node ids, node order
  std::vector<int32_t> copy;           // fallback for the scan drain
};

// bucket-capped drain: every capacity entering a drain is < k (a cap
// ≥ k resolves on the instant-fit probe before any draining), so a
// counting sort by value gives O(nb + k) rounds-free access to both
// "smallest capacity ≥ remainder" and "max class in node order".
// `in_subset(c)` selects the eligible entries.
template <typename Pred>
bool mf_drain_bucketed(const std::vector<int32_t>& caps, int64_t k,
                       Pred in_subset, MfScratch& ws, MfSegs& segs) {
  const int64_t nb = static_cast<int64_t>(caps.size());
  const int64_t kb = k;  // bucket domain: values 1..k-1
  ws.bucket_count.assign(kb, 0);
  for (int64_t i = 0; i < nb; ++i) {
    const int32_t c = caps[i];
    if (c > 0 && in_subset(c)) ++ws.bucket_count[c];  // c < k guaranteed
  }
  ws.bucket_offset.resize(kb);
  ws.bucket_end.resize(kb);
  int32_t total_nodes = 0;
  for (int64_t v = 1; v < kb; ++v) {
    ws.bucket_offset[v] = total_nodes;
    total_nodes += ws.bucket_count[v];
    ws.bucket_end[v] = total_nodes;
  }
  if (total_nodes == 0) return false;
  ws.nodes.resize(total_nodes);
  {
    std::vector<int32_t>& cursor = ws.bucket_count;  // reuse as fill cursor
    for (int64_t v = 1; v < kb; ++v) cursor[v] = ws.bucket_offset[v];
    for (int64_t i = 0; i < nb; ++i) {
      const int32_t c = caps[i];
      if (c > 0 && in_subset(c)) ws.nodes[cursor[c]++] = static_cast<int32_t>(i);
    }
  }
  int64_t rem = k;
  int64_t maxv = kb - 1;
  while (true) {
    while (maxv >= 1 && ws.bucket_offset[maxv] == ws.bucket_end[maxv]) --maxv;
    if (maxv < 1) return false;
    // instant fit: smallest unconsumed capacity ≥ rem, earliest node
    if (rem <= maxv) {
      int64_t v = rem;
      while (ws.bucket_offset[v] == ws.bucket_end[v]) ++v;  // ≤ maxv by above
      segs.emplace_back(ws.nodes[ws.bucket_offset[v]], rem);
      return true;
    }
    // drain the max class in node order while rem ≥ maxv
    while (rem >= maxv && ws.bucket_offset[maxv] != ws.bucket_end[maxv]) {
      segs.emplace_back(ws.nodes[ws.bucket_offset[maxv]++], maxv);
      rem -= maxv;
    }
    if (rem == 0) return true;
  }
}

// minimal_fragmentation.go:71-94: the avoid-mostly-empty-nodes subset
// attempt (capacities < (k + max)/2), then the full set.  The attempt
// structure is decided entirely from the pass's branchless extremes:
//  - subset first probe = smallest capacity ≥ k *within* the subset.
//    The overall smallest capacity ≥ k (min_ge) IS that winner whenever
//    min_ge < target (subset candidates are a subset of the ≥ k
//    candidates, all ≥ min_ge, and the min_ge node itself qualifies);
//    if min_ge ≥ target the subset has no ≥ k member at all.
//  - subset non-empty ⟺ the smallest positive capacity < target.
//  - entering a drain implies every eligible capacity < k, so the
//    counting-bucket drain applies (O(nb + k), copy-free).
// Only the fast-path placement needs a further scan: find the earliest
// node holding the winning capacity value.
bool mf_assign(const std::vector<int32_t>& caps_by_node, int64_t k,
               const MfExtremes& ext, MfScratch& ws, MfSegs& segs) {
  segs.clear();
  if (k <= 0 || ext.maxc <= 0) return false;

  // a sentinel present makes the subset "every bounded node" and the
  // attempt unconditional (min_frag_counts' has_sent rule — identical
  // to the host's (k + 2^62)/2 threshold)
  const bool has_sent = ext.maxc == kMfSent;
  const bool attempt_subset = has_sent || k < ext.maxc;
  const int64_t target =
      has_sent
          ? static_cast<int64_t>(kMfSent)
          : (attempt_subset ? (k + static_cast<int64_t>(ext.maxc)) / 2 : 0);

  auto place_first_with = [&](int32_t value) {
    // blocked any-match (the fixed-length inner loop vectorizes; an
    // early-exit elementwise scan would not)
    const int64_t nb = static_cast<int64_t>(caps_by_node.size());
    const int32_t* caps = caps_by_node.data();
    constexpr int64_t B = 256;
    int64_t i = 0;
    for (; i + B <= nb; i += B) {
      bool any = false;
      for (int64_t j = i; j < i + B; ++j) any |= caps[j] == value;
      if (any) break;
    }
    for (; i < nb; ++i) {
      if (caps[i] == value) {
        segs.emplace_back(static_cast<int32_t>(i), k);
        return;
      }
    }
  };

  const bool have_ge = ext.min_ge != kBig && ext.min_ge >= k;
  if (attempt_subset) {
    if (have_ge && ext.min_ge < target) {
      place_first_with(ext.min_ge);
      return true;
    }
    const bool sub_any = ext.min_pos != kBig && ext.min_pos < target;
    if (sub_any) {
      // no subset capacity is ≥ k here (min_ge ≥ target or none)
      bool ok;
      if (k < (int64_t{1} << 16)) {
        ok = mf_drain_bucketed(caps_by_node, k,
                               [&](int32_t c) { return c < target; }, ws,
                               segs);
      } else {
        ws.copy = caps_by_node;
        for (int32_t& c : ws.copy) {
          if (c >= target) c = 0;
        }
        ok = mf_drain(ws.copy, k, segs);
      }
      if (ok) return true;
      segs.clear();
    }
  }
  if (have_ge) {
    place_first_with(ext.min_ge);
    return true;
  }
  if (k < (int64_t{1} << 16)) {
    return mf_drain_bucketed(caps_by_node, k, [](int32_t) { return true; },
                             ws, segs);
  }
  ws.copy = caps_by_node;
  return mf_drain(ws.copy, k, segs);
}

// ---------------------------------------------------------------------------
// Sharded capacity pass — the cold-solve fallback of the delta-solve
// session (ops/deltasolve.py).  The per-app capacity pass is the only
// O(nodes) cost with no carry dependency, so it shards cleanly: each
// worker runs the dim-at-a-time sweeps over a contiguous node range and
// reports a partial total; the caller sums partials in shard order, so
// results are BIT-identical to the serial pass (per-node caps are
// independent, int64 partial sums are exact).  Dispatch is condvar
// wake + condvar completion, never spinning: on an oversubscribed or
// single-core host idle workers cost nothing.  The pool only engages
// when the session was loaded with n_threads > 1 AND the node axis is
// long enough that the ~10us dispatch round-trip amortizes (the
// min_pool_nodes load parameter; at 10k nodes a pass is ~20us, at 100k
// ~200us — the pool is for the latter).
// ---------------------------------------------------------------------------

constexpr int kMaxPoolThreads = 8;

struct CapTask {
  const int32_t* a0;
  const int32_t* a1;
  const int32_t* a2;
  const uint8_t* elig;
  const int32_t* e;
  int32_t k;
  int mode;  // 0 = clamped [0,k] (solve_queue); 1 = unclamped min-frag
  int32_t* cap;
  int64_t* totals;  // [shards] partial totals, summed in shard order
  int64_t nb;
  int shards;
};

void cap_task_shard(const CapTask& t, int shard) {
  const int64_t lo = t.nb * shard / t.shards;
  const int64_t hi = t.nb * (shard + 1) / t.shards;
  if (hi <= lo) {
    t.totals[shard] = 0;
    return;
  }
  const int32_t init = t.mode == 0 ? t.k : kMfSent;
  cap_sweeps(t.a0 + lo, t.a1 + lo, t.a2 + lo, hi - lo, t.e, init, t.cap + lo);
  int64_t total = 0;
  if (t.mode == 0) {
    for (int64_t i = lo; i < hi; ++i) {
      int32_t c = t.elig[i] ? t.cap[i] : 0;
      c = std::max(c, 0);
      t.cap[i] = c;
      total += c;
    }
  } else {
    for (int64_t i = lo; i < hi; ++i) {
      int32_t c = t.elig[i] ? t.cap[i] : 0;
      t.cap[i] = c;
      total += std::clamp<int32_t>(c, 0, t.k);
    }
  }
  t.totals[shard] = total;
}

class SweepPool {
 public:
  explicit SweepPool(int workers) : n_(std::max(workers, 1)) {
    for (int w = 1; w < n_; ++w) {
      threads_.emplace_back([this, w] { worker(w); });
    }
  }

  ~SweepPool() {
    {
      std::lock_guard<std::mutex> g(m_);
      stop_ = true;
    }
    cv_work_.notify_all();
    for (auto& t : threads_) t.join();
  }

  int workers() const { return n_; }

  // Runs cap_task_shard for every shard; the caller thread takes shard 0
  // and blocks until all workers report done.
  void run(const CapTask& t) {
    if (n_ <= 1) {
      cap_task_shard(t, 0);
      return;
    }
    {
      std::lock_guard<std::mutex> g(m_);
      task_ = &t;
      ++gen_;
      pending_ = n_ - 1;
    }
    cv_work_.notify_all();
    cap_task_shard(t, 0);
    std::unique_lock<std::mutex> g(m_);
    cv_done_.wait(g, [this] { return pending_ == 0; });
    task_ = nullptr;
  }

 private:
  void worker(int w) {
    uint64_t seen = 0;
    std::unique_lock<std::mutex> g(m_);
    for (;;) {
      cv_work_.wait(g, [&] { return stop_ || gen_ != seen; });
      if (stop_) return;
      seen = gen_;
      const CapTask* t = task_;
      g.unlock();
      cap_task_shard(*t, w);
      g.lock();
      if (--pending_ == 0) cv_done_.notify_one();
    }
  }

  const int n_;
  std::vector<std::thread> threads_;
  std::mutex m_;
  std::condition_variable cv_work_, cv_done_;
  const CapTask* task_ = nullptr;
  uint64_t gen_ = 0;
  int pending_ = 0;
  bool stop_ = false;
};

// Serial when pool is null / single-worker, sharded otherwise; the two
// produce identical caps and totals (see CapTask notes).
int64_t cap_pass_sharded(SweepPool* pool, int mode, const int32_t* a0,
                         const int32_t* a1, const int32_t* a2,
                         const uint8_t* elig, int64_t nb, const int32_t* e,
                         int32_t k, int32_t* cap) {
  if (pool == nullptr || pool->workers() <= 1) {
    return mode == 0 ? cap_pass_all(a0, a1, a2, elig, nb, e, k, cap)
                     : mf_cap_pass_all(a0, a1, a2, elig, nb, e, k, cap);
  }
  int64_t totals[kMaxPoolThreads] = {0};
  CapTask t{a0, a1, a2, elig, e,  k,
            mode, cap, totals, nb, pool->workers()};
  pool->run(t);
  int64_t total = 0;
  for (int s = 0; s < t.shards; ++s) total += totals[s];
  return total;
}

// ---------------------------------------------------------------------------
// Shared per-app queue step — ONE implementation of the FIFO step for
// both the stateless entry points (fifo_solve_queue /
// fifo_solve_queue_minfrag) and the persistent session below, so the
// session's warm-resume decisions are bit-identical to a cold solve by
// construction, not by parallel maintenance of two loops.
// ---------------------------------------------------------------------------

struct QueueScratch {
  std::vector<int32_t> cap;      // clamped capacities (plain policies)
  std::vector<int32_t> mf_caps;  // unclamped min-frag capacities
  MfScratch mf_ws;
  MfSegs segs;
};

std::vector<int32_t> build_cand(const int32_t* driver_rank, int64_t nb) {
  std::vector<int32_t> cand;
  cand.reserve(nb);
  for (int64_t i = 0; i < nb; ++i) {
    if (driver_rank[i] < kBig) cand.push_back(static_cast<int32_t>(i));
  }
  std::sort(cand.begin(), cand.end(), [&](int32_t x, int32_t y) {
    return driver_rank[x] < driver_rank[y];
  });
  return cand;
}

// Optional per-step usage capture for the provenance explainer
// (fifo_explain_queue): how many nodes hosted executors (each loses one
// executor row — the sparkpods.go:139-146 quirk) and whether the driver
// row was applied separately.  nullptr (every hot-path caller) costs one
// pointer test per app — zero observable cost when provenance is off.
struct StepUsage {
  int32_t hosting_nodes = 0;
  int32_t driver_row_applied = 0;
};

// One tightly/evenly FIFO step: capacity pass + first-rank driver probe
// + the usage-subtraction quirk.  Mutates the planes on success.
// Returns the driver index or -1 (infeasible, planes untouched).
int32_t step_app_plain(int32_t* a0, int32_t* a1, int32_t* a2,
                       const uint8_t* exec_ok, int64_t nb,
                       const std::vector<int32_t>& cand, const int32_t* d,
                       const int32_t* e, int32_t k, int evenly,
                       QueueScratch& ws, SweepPool* pool,
                       StepUsage* usage = nullptr) {
  int32_t* cap = ws.cap.data();
  int64_t total =
      cap_pass_sharded(pool, 0, a0, a1, a2, exec_ok, nb, e, k, cap);
  int32_t didx = -1;
  int32_t capd = 0;
  if (total >= k) {
    for (int32_t i : cand) {
      int32_t a[kDims] = {a0[i], a1[i], a2[i]};
      if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
      if (total - cap[i] + cwd >= k) {
        didx = i;
        capd = cwd;
        break;
      }
    }
  }
  if (didx < 0) return -1;
  auto sub_exec = [&](int64_t i) {
    a0[i] = wrap_sub(a0[i], e[0]);
    a1[i] = wrap_sub(a1[i], e[1]);
    a2[i] = wrap_sub(a2[i], e[2]);
  };
  bool driver_hosts_exec = false;
  int32_t hosts = 0;
  if (evenly) {
    // hosting nodes = first k capacity-bearing nodes in node order
    int32_t placed = 0;
    for (int64_t i = 0; i < nb && placed < k; ++i) {
      int32_t c = (i == didx) ? capd : cap[i];
      if (c <= 0) continue;
      ++placed;
      ++hosts;
      if (i == didx) driver_hosts_exec = true;
      sub_exec(i);
    }
  } else {
    // tightly-pack: greedy fill in node order until k executors sit
    int64_t cum = 0;
    for (int64_t i = 0; i < nb && cum < k; ++i) {
      int32_t c = (i == didx) ? capd : cap[i];
      if (c <= 0) continue;
      cum += c;
      ++hosts;
      if (i == didx) driver_hosts_exec = true;
      sub_exec(i);
    }
  }
  if (!driver_hosts_exec) {
    a0[didx] = wrap_sub(a0[didx], d[0]);
    a1[didx] = wrap_sub(a1[didx], d[1]);
    a2[didx] = wrap_sub(a2[didx], d[2]);
  }
  if (usage != nullptr) {
    usage->hosting_nodes = hosts;
    usage->driver_row_applied = driver_hosts_exec ? 0 : 1;
  }
  return didx;
}

// One minimal-fragmentation FIFO step (fifo_solve_queue_minfrag body).
int32_t step_app_minfrag(int32_t* a0, int32_t* a1, int32_t* a2,
                         const uint8_t* exec_ok, int64_t nb,
                         const std::vector<int32_t>& cand, const int32_t* d,
                         const int32_t* e, int32_t k, QueueScratch& ws,
                         SweepPool* pool, StepUsage* usage = nullptr) {
  int32_t* caps = ws.mf_caps.data();
  // ONE pass yields both the UNCLAMPED min-frag capacities and the
  // tightly feasibility total sum(clamp(c, 0, k))
  int64_t total =
      cap_pass_sharded(pool, 1, a0, a1, a2, exec_ok, nb, e, k, caps);
  int32_t didx = -1;
  if (total >= k) {
    for (int32_t i : cand) {
      int32_t a[kDims] = {a0[i], a1[i], a2[i]};
      if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
      if (total - std::clamp<int32_t>(caps[i], 0, k) + cwd >= k) {
        didx = i;
        break;
      }
    }
  }
  if (didx < 0) return -1;

  // min-frag placement with the driver subtracted on its node — only
  // the driver node's capacity differs from the fused pass
  if (exec_ok[didx]) {
    int32_t av[kDims];
    av[0] = wrap_sub(a0[didx], d[0]);
    av[1] = wrap_sub(a1[didx], d[1]);
    av[2] = wrap_sub(a2[didx], d[2]);
    caps[didx] = mf_cap_one(av[0], av[1], av[2], e);
  }
  bool placed_any =
      k > 0 && mf_assign(ws.mf_caps, k,
                         mf_extremes(ws.mf_caps, k, ws.mf_ws.copy), ws.mf_ws,
                         ws.segs);

  // usage subtraction quirk: one executor's worth per hosting node,
  // the driver row on its node unless it also hosts executors
  bool driver_hosts_exec = false;
  if (placed_any) {
    for (const auto& seg : ws.segs) {
      const int32_t i = seg.first;
      if (i == didx) driver_hosts_exec = true;
      a0[i] = wrap_sub(a0[i], e[0]);
      a1[i] = wrap_sub(a1[i], e[1]);
      a2[i] = wrap_sub(a2[i], e[2]);
    }
  }
  if (!driver_hosts_exec) {
    a0[didx] = wrap_sub(a0[didx], d[0]);
    a1[didx] = wrap_sub(a1[didx], d[1]);
    a2[didx] = wrap_sub(a2[didx], d[2]);
  }
  if (usage != nullptr) {
    // MfSegs nodes are unique across segments, so the segment count IS
    // the hosting-node count
    usage->hosting_nodes =
        placed_any ? static_cast<int32_t>(ws.segs.size()) : 0;
    usage->driver_row_applied = driver_hosts_exec ? 0 : 1;
  }
  return didx;
}

void split_planes(const int32_t* rows, int64_t nb, std::vector<int32_t>& a0,
                  std::vector<int32_t>& a1, std::vector<int32_t>& a2) {
  a0.resize(nb);
  a1.resize(nb);
  a2.resize(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = rows[i * kDims + 0];
    a1[i] = rows[i * kDims + 1];
    a2[i] = rows[i * kDims + 2];
  }
}

void join_planes(const std::vector<int32_t>& a0, const std::vector<int32_t>& a1,
                 const std::vector<int32_t>& a2, int64_t nb, int32_t* rows) {
  for (int64_t i = 0; i < nb; ++i) {
    rows[i * kDims + 0] = a0[i];
    rows[i * kDims + 1] = a1[i];
    rows[i * kDims + 2] = a2[i];
  }
}

// ---------------------------------------------------------------------------
// Equivalence-class compressed stepping (ROADMAP 2: the Firmament /
// Borg-style node-aggregation relaxation).  Real fleets have a few
// dozen machine shapes, so most of the per-app O(nodes) capacity pass
// recomputes identical divisions.  The class solver partitions nodes by
// EXACT (avail triple, exec_ok) equality, evaluates each capacity
// formula once per class, and weights by multiplicity.  Nodes whose
// planes diverge from their class representative (because a placement
// wrote them) move to a small sorted overlay evaluated per node; when
// the overlay outgrows nb/32 the partition is rebuilt in one O(nb)
// hash pass.
//
// Parity is by construction, not by approximation:
//  - the planes stay authoritative — every plane read (driver probe,
//    subtraction, checkpointing) is the row solver's exact read;
//  - live class members share the representative triple EXACTLY, so
//    the per-class capacity equals the per-row capacity;
//  - fills and drains walk merged per-class member cursors + the
//    overlay in ascending node order — the same node visit order as
//    the row loops — and bind concrete node ids at that moment
//    (deterministic bind-time expansion);
//  - min-frag class values come from mf_cap_one (clamped at 0), which
//    is observationally equivalent to the row pass's unclamped
//    negatives: every consumer filters on c > 0 / c >= k / equality
//    with a positive value.
// The property suite (tests/test_class_compression.py) re-verifies the
// byte-identity across seeds, policies, and session lanes.
// ---------------------------------------------------------------------------

inline uint64_t class_hash(int32_t a0, int32_t a1, int32_t a2, uint8_t e) {
  uint64_t h = static_cast<uint32_t>(a0);
  h = h * 0x9E3779B97F4A7C15ull + static_cast<uint32_t>(a1);
  h = h * 0x9E3779B97F4A7C15ull + static_cast<uint32_t>(a2);
  h = h * 0x9E3779B97F4A7C15ull + e;
  h ^= h >> 29;
  h *= 0xBF58476D1CE4E5B9ull;
  h ^= h >> 32;
  return h;
}

struct ClassSolver {
  struct Cls {
    int32_t a[kDims];
    uint8_t eok = 0;
    int32_t live = 0;                // members whose planes still match a[]
    std::vector<int32_t> members;    // ascending node ids (dead ones are
                                     // skipped via node_cls mismatch)
  };
  std::vector<Cls> classes;
  std::vector<int32_t> node_cls;  // node -> class id, -1 = overlay (diverged)
  std::vector<int32_t> ov_nodes;  // ascending diverged node ids
  // open-addressing hash over class keys (power-of-two table)
  std::vector<int32_t> table;
  uint64_t mask = 0;
  int64_t nb = 0;
  int64_t ov_limit = 0;
  // per-app scratch (allocation-free steady state)
  std::vector<int32_t> cls_caps;   // per-class capacity value
  std::vector<int32_t> ov_caps;    // per-overlay-entry capacity value
  std::vector<size_t> cls_cur;     // per-class member cursor (fills)
  std::vector<int32_t> newly;      // nodes written by the current app
  std::vector<int32_t> merge_tmp;  // fresh overlay ids to splice in
  std::vector<std::pair<int32_t, int32_t>> heap;  // (node, source) min-heap
  // compression evidence for the bench lane / session stats
  int64_t classes_last = 0;  // class count at the most recent rebuild
  int64_t rebuilds = 0;
  int64_t ov_peak = 0;
};

void class_rebuild(ClassSolver& cs, const int32_t* a0, const int32_t* a1,
                   const int32_t* a2, const uint8_t* eok, int64_t nb) {
  cs.nb = nb;
  cs.classes.clear();
  cs.node_cls.assign(nb, -1);
  cs.ov_nodes.clear();
  uint64_t want = 16;
  while (want < static_cast<uint64_t>(nb) * 2) want <<= 1;
  cs.table.assign(want, -1);
  cs.mask = want - 1;
  for (int64_t i = 0; i < nb; ++i) {
    uint64_t slot = class_hash(a0[i], a1[i], a2[i], eok[i]) & cs.mask;
    int32_t id = -1;
    while (true) {
      const int32_t t = cs.table[slot];
      if (t < 0) break;
      const ClassSolver::Cls& c = cs.classes[t];
      if (c.a[0] == a0[i] && c.a[1] == a1[i] && c.a[2] == a2[i] &&
          c.eok == eok[i]) {
        id = t;
        break;
      }
      slot = (slot + 1) & cs.mask;
    }
    if (id < 0) {
      id = static_cast<int32_t>(cs.classes.size());
      ClassSolver::Cls c;
      c.a[0] = a0[i];
      c.a[1] = a1[i];
      c.a[2] = a2[i];
      c.eok = eok[i];
      cs.classes.push_back(std::move(c));
      cs.table[slot] = id;
    }
    cs.classes[id].members.push_back(static_cast<int32_t>(i));
    ++cs.classes[id].live;
    cs.node_cls[i] = id;
  }
  // rebuild threshold: a rebuild is one O(nb) hash pass (~1 ms at
  // 100k), while every app pays O(overlay) — nb/64 keeps the mean
  // overlay cost below the per-app class pass without rebuild churn
  cs.ov_limit = std::max<int64_t>(int64_t{512}, nb / 64);
  cs.classes_last = static_cast<int64_t>(cs.classes.size());
  ++cs.rebuilds;
}

// Node's capacity under the current per-class / per-overlay values
// (driver-probe read: identical to the row pass's cap[i] because live
// members share the representative triple exactly).
inline int32_t class_cap_at(const ClassSolver& cs, int32_t i) {
  const int32_t c = cs.node_cls[i];
  if (c >= 0) return cs.cls_caps[c];
  const auto it =
      std::lower_bound(cs.ov_nodes.begin(), cs.ov_nodes.end(), i);
  return cs.ov_caps[static_cast<size_t>(it - cs.ov_nodes.begin())];
}

// Fold the nodes the current app wrote into the overlay (they diverged
// from their class representative); rebuild the whole partition once
// the overlay outgrows its bound.  `newly` holds unique node ids.
void class_absorb(ClassSolver& cs, const int32_t* a0, const int32_t* a1,
                  const int32_t* a2, const uint8_t* eok) {
  if (cs.newly.empty()) return;
  std::sort(cs.newly.begin(), cs.newly.end());
  cs.merge_tmp.clear();
  for (const int32_t i : cs.newly) {
    const int32_t c = cs.node_cls[i];
    if (c < 0) continue;  // already diverged in an earlier step
    cs.node_cls[i] = -1;
    --cs.classes[c].live;
    cs.merge_tmp.push_back(i);
  }
  cs.newly.clear();
  if (cs.merge_tmp.empty()) return;
  const size_t before = cs.ov_nodes.size();
  cs.ov_nodes.insert(cs.ov_nodes.end(), cs.merge_tmp.begin(),
                     cs.merge_tmp.end());
  std::inplace_merge(cs.ov_nodes.begin(),
                     cs.ov_nodes.begin() + static_cast<int64_t>(before),
                     cs.ov_nodes.end());
  cs.ov_peak =
      std::max(cs.ov_peak, static_cast<int64_t>(cs.ov_nodes.size()));
  if (static_cast<int64_t>(cs.ov_nodes.size()) > cs.ov_limit) {
    class_rebuild(cs, a0, a1, a2, eok, cs.nb);
  }
}

// One tightly/evenly FIFO step over the class partition — same contract
// as step_app_plain (mutates planes on success, returns didx or -1) and
// byte-identical verdicts/planes by construction.
int32_t step_app_plain_classes(ClassSolver& cs, int32_t* a0, int32_t* a1,
                               int32_t* a2, const uint8_t* exec_ok,
                               int64_t nb, const std::vector<int32_t>& cand,
                               const int32_t* d, const int32_t* e, int32_t k,
                               int evenly) {
  const int64_t nc = static_cast<int64_t>(cs.classes.size());
  cs.cls_caps.resize(nc);
  int64_t total = 0;
  for (int64_t c = 0; c < nc; ++c) {
    const ClassSolver::Cls& cl = cs.classes[c];
    const int32_t cap = cl.eok ? clamped_cap(cl.a, e, k) : 0;
    cs.cls_caps[c] = cap;
    total += static_cast<int64_t>(cap) * cl.live;
  }
  const int64_t nov = static_cast<int64_t>(cs.ov_nodes.size());
  cs.ov_caps.resize(nov);
  for (int64_t j = 0; j < nov; ++j) {
    const int32_t i = cs.ov_nodes[j];
    const int32_t a[kDims] = {a0[i], a1[i], a2[i]};
    const int32_t cap = exec_ok[i] ? clamped_cap(a, e, k) : 0;
    cs.ov_caps[j] = cap;
    total += cap;
  }

  // driver probe — the row walk verbatim (planes are authoritative)
  int32_t didx = -1;
  int32_t capd = 0;
  if (total >= k) {
    for (const int32_t i : cand) {
      const int32_t a[kDims] = {a0[i], a1[i], a2[i]};
      if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      const int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
      if (total - class_cap_at(cs, i) + cwd >= k) {
        didx = i;
        capd = cwd;
        break;
      }
    }
  }
  if (didx < 0) return -1;

  // fill: merged ascending walk over the positive-capacity nodes.
  // Sources: one cursor per class (live members, didx excluded), one
  // overlay cursor, and the didx singleton carrying capd — together
  // they enumerate exactly the nodes the row loop would visit, in the
  // same order.  Source ids: [0, nc) classes, nc overlay, nc+1 didx.
  const int32_t kSrcOv = static_cast<int32_t>(nc);
  const int32_t kSrcD = static_cast<int32_t>(nc) + 1;
  cs.cls_cur.assign(static_cast<size_t>(nc), 0);
  cs.heap.clear();
  auto cls_next = [&](int32_t c) -> int32_t {
    const ClassSolver::Cls& cl = cs.classes[c];
    size_t& cur = cs.cls_cur[c];
    while (cur < cl.members.size()) {
      const int32_t m = cl.members[cur++];
      if (cs.node_cls[m] == c && m != didx) return m;
    }
    return -1;
  };
  int64_t ov_cur = 0;
  int64_t ov_head_j = -1;
  auto ov_next = [&]() -> int32_t {
    while (ov_cur < nov) {
      const int64_t j = ov_cur++;
      if (cs.ov_caps[j] > 0 && cs.ov_nodes[j] != didx) {
        ov_head_j = j;
        return cs.ov_nodes[j];
      }
    }
    ov_head_j = -1;
    return -1;
  };
  const auto hcmp = [](const std::pair<int32_t, int32_t>& x,
                       const std::pair<int32_t, int32_t>& y) {
    return x.first > y.first;  // min-heap on node id
  };
  for (int32_t c = 0; c < nc; ++c) {
    if (cs.cls_caps[c] <= 0) continue;
    const int32_t n = cls_next(c);
    if (n >= 0) cs.heap.emplace_back(n, c);
  }
  {
    const int32_t n = ov_next();
    if (n >= 0) cs.heap.emplace_back(n, kSrcOv);
  }
  if (capd > 0) cs.heap.emplace_back(didx, kSrcD);
  std::make_heap(cs.heap.begin(), cs.heap.end(), hcmp);

  auto sub_exec = [&](int32_t i) {
    a0[i] = wrap_sub(a0[i], e[0]);
    a1[i] = wrap_sub(a1[i], e[1]);
    a2[i] = wrap_sub(a2[i], e[2]);
  };
  cs.newly.clear();
  bool driver_hosts_exec = false;
  int64_t cum = 0;      // tightly: cumulative capacity
  int32_t placed = 0;   // evenly: hosting nodes
  while (!cs.heap.empty()) {
    if (evenly ? placed >= k : cum >= k) break;
    std::pop_heap(cs.heap.begin(), cs.heap.end(), hcmp);
    const auto [i, src] = cs.heap.back();
    cs.heap.pop_back();
    int32_t cap_i;
    int32_t nxt = -1;
    if (src == kSrcD) {
      cap_i = capd;
    } else if (src == kSrcOv) {
      cap_i = cs.ov_caps[ov_head_j];
      nxt = ov_next();
    } else {
      cap_i = cs.cls_caps[src];
      nxt = cls_next(src);
    }
    if (nxt >= 0) {
      cs.heap.emplace_back(nxt, src);
      std::push_heap(cs.heap.begin(), cs.heap.end(), hcmp);
    }
    cum += cap_i;
    ++placed;
    if (i == didx) driver_hosts_exec = true;
    sub_exec(i);
    cs.newly.push_back(i);
  }
  if (!driver_hosts_exec) {
    a0[didx] = wrap_sub(a0[didx], d[0]);
    a1[didx] = wrap_sub(a1[didx], d[1]);
    a2[didx] = wrap_sub(a2[didx], d[2]);
    cs.newly.push_back(didx);
  }
  class_absorb(cs, a0, a1, a2, exec_ok);
  return didx;
}

// --- class-structured min-frag drain -----------------------------------
// The row drain orders nodes by capacity VALUE (instant fit = smallest
// value ≥ remainder, then drain the max value in node order).  The class
// variant keeps a value-ordered map whose entries enumerate the nodes
// holding that value — per-class member cursors, an overlay list, and
// the didx singleton — and pops the globally earliest node among the
// sources, reproducing the bucketed drain's consumed-prefix node order.

struct ClsDrainVal {
  // (class id, member cursor, cached head node or kBig) triples
  std::vector<std::array<int32_t, 3>> cls;
  std::vector<int32_t> ov;  // ascending overlay node ids with this value
  size_t ov_cur = 0;
  bool has_didx = false;
};

int32_t cls_drain_head(const ClassSolver& cs, ClsDrainVal& dv, int32_t didx) {
  int32_t best = kBig;
  for (auto& src : dv.cls) {
    if (src[2] == kBig && src[1] >= 0) {
      // refresh the cached head: next live member != didx
      const ClassSolver::Cls& cl = cs.classes[src[0]];
      int32_t head = kBig;
      size_t cur = static_cast<size_t>(src[1]);
      while (cur < cl.members.size()) {
        const int32_t m = cl.members[cur];
        if (cs.node_cls[m] == src[0] && m != didx) {
          head = m;
          break;
        }
        ++cur;
      }
      src[1] = static_cast<int32_t>(cur);
      src[2] = head;
      if (head == kBig) src[1] = -1;  // exhausted
    }
    if (src[2] < best) best = src[2];
  }
  if (dv.ov_cur < dv.ov.size()) best = std::min(best, dv.ov[dv.ov_cur]);
  if (dv.has_didx && didx < best) best = didx;
  return best == kBig ? -1 : best;
}

void cls_drain_advance(const ClassSolver& cs, ClsDrainVal& dv, int32_t node,
                       int32_t didx) {
  if (dv.has_didx && node == didx) {
    dv.has_didx = false;
    return;
  }
  if (dv.ov_cur < dv.ov.size() && dv.ov[dv.ov_cur] == node) {
    ++dv.ov_cur;
    return;
  }
  for (auto& src : dv.cls) {
    if (src[2] == node) {
      ++src[1];
      src[2] = kBig;  // head consumed; refresh lazily
      return;
    }
  }
}

bool cls_drain_exhausted(const ClassSolver& cs, ClsDrainVal& dv,
                         int32_t didx) {
  return cls_drain_head(cs, dv, didx) < 0;
}

// One minimal-fragmentation FIFO step over the class partition — same
// contract as step_app_minfrag, byte-identical by construction.
int32_t step_app_minfrag_classes(ClassSolver& cs, int32_t* a0, int32_t* a1,
                                 int32_t* a2, const uint8_t* exec_ok,
                                 int64_t nb,
                                 const std::vector<int32_t>& cand,
                                 const int32_t* d, const int32_t* e,
                                 int32_t k, MfSegs& segs) {
  const int64_t nc = static_cast<int64_t>(cs.classes.size());
  cs.cls_caps.resize(nc);
  int64_t total = 0;
  for (int64_t c = 0; c < nc; ++c) {
    const ClassSolver::Cls& cl = cs.classes[c];
    const int32_t v = cl.eok ? mf_cap_one(cl.a[0], cl.a[1], cl.a[2], e) : 0;
    cs.cls_caps[c] = v;
    total += static_cast<int64_t>(std::clamp<int32_t>(v, 0, k)) * cl.live;
  }
  const int64_t nov = static_cast<int64_t>(cs.ov_nodes.size());
  cs.ov_caps.resize(nov);
  for (int64_t j = 0; j < nov; ++j) {
    const int32_t i = cs.ov_nodes[j];
    const int32_t v =
        exec_ok[i] ? mf_cap_one(a0[i], a1[i], a2[i], e) : 0;
    cs.ov_caps[j] = v;
    total += std::clamp<int32_t>(v, 0, k);
  }

  int32_t didx = -1;
  if (total >= k) {
    for (const int32_t i : cand) {
      const int32_t a[kDims] = {a0[i], a1[i], a2[i]};
      if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      const int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
      if (total - std::clamp<int32_t>(class_cap_at(cs, i), 0, k) + cwd >= k) {
        didx = i;
        break;
      }
    }
  }
  if (didx < 0) return -1;

  // driver-node fix-up: didx contributes its own value (mf_cap_one on
  // avail − driver when eligible, 0 otherwise) and is excluded from its
  // class's multiplicity everywhere below
  int32_t dval = 0;
  if (exec_ok[didx]) {
    dval = mf_cap_one(wrap_sub(a0[didx], d[0]), wrap_sub(a1[didx], d[1]),
                      wrap_sub(a2[didx], d[2]), e);
  }
  const int32_t didx_cls = cs.node_cls[didx];
  auto eff_live = [&](int64_t c) {
    return cs.classes[c].live - (didx_cls == static_cast<int32_t>(c) ? 1 : 0);
  };

  bool placed_any = false;
  segs.clear();
  if (k > 0) {
    // extremes over the implied by-node capacity vector
    int32_t maxc = 0, min_ge = kBig, min_pos = kBig;
    auto fold = [&](int32_t v) {
      maxc = std::max(maxc, v);
      if (v >= k && v < min_ge) min_ge = v;
      if (v > 0 && v < min_pos) min_pos = v;
    };
    for (int64_t c = 0; c < nc; ++c) {
      if (eff_live(c) > 0) fold(cs.cls_caps[c]);
    }
    for (int64_t j = 0; j < nov; ++j) {
      if (cs.ov_nodes[j] != didx) fold(cs.ov_caps[j]);
    }
    fold(dval);

    if (maxc > 0) {
      const bool has_sent = maxc == kMfSent;
      const bool attempt_subset = has_sent || k < maxc;
      const int64_t target =
          has_sent ? static_cast<int64_t>(kMfSent)
                   : (attempt_subset
                          ? (k + static_cast<int64_t>(maxc)) / 2
                          : 0);

      auto place_first_with = [&](int32_t value) {
        int32_t best = kBig;
        for (int64_t c = 0; c < nc; ++c) {
          if (cs.cls_caps[c] != value || eff_live(c) <= 0) continue;
          for (const int32_t m : cs.classes[c].members) {
            if (cs.node_cls[m] == static_cast<int32_t>(c) && m != didx) {
              best = std::min(best, m);
              break;
            }
          }
        }
        for (int64_t j = 0; j < nov; ++j) {
          if (cs.ov_caps[j] == value && cs.ov_nodes[j] != didx) {
            best = std::min(best, cs.ov_nodes[j]);
            break;
          }
        }
        if (dval == value) best = std::min(best, didx);
        segs.emplace_back(best, static_cast<int64_t>(k));
      };

      // value-ordered drain over the class-structured capacity multiset
      auto drain = [&](int64_t bound) -> bool {
        std::map<int32_t, ClsDrainVal> vals;
        for (int64_t c = 0; c < nc; ++c) {
          const int32_t v = cs.cls_caps[c];
          if (v > 0 && v < bound && eff_live(c) > 0) {
            vals[v].cls.push_back({static_cast<int32_t>(c), 0, kBig});
          }
        }
        for (int64_t j = 0; j < nov; ++j) {
          const int32_t v = cs.ov_caps[j];
          if (v > 0 && v < bound && cs.ov_nodes[j] != didx) {
            vals[v].ov.push_back(cs.ov_nodes[j]);
          }
        }
        if (dval > 0 && dval < bound) vals[dval].has_didx = true;
        int64_t rem = k;
        while (true) {
          if (vals.empty()) return false;
          auto last = std::prev(vals.end());
          const int32_t maxv = last->first;
          if (rem <= maxv) {
            // instant fit: smallest unconsumed value ≥ rem, earliest
            // node among its remaining holders
            auto it = vals.lower_bound(static_cast<int32_t>(rem));
            const int32_t node = cls_drain_head(cs, it->second, didx);
            segs.emplace_back(node, rem);
            return true;
          }
          ClsDrainVal& dv = last->second;
          while (rem >= maxv) {
            const int32_t node = cls_drain_head(cs, dv, didx);
            if (node < 0) break;
            cls_drain_advance(cs, dv, node, didx);
            segs.emplace_back(node, static_cast<int64_t>(maxv));
            rem -= maxv;
          }
          if (rem == 0) return true;
          if (cls_drain_exhausted(cs, dv, didx)) vals.erase(last);
        }
      };

      const bool have_ge = min_ge != kBig && min_ge >= k;
      if (attempt_subset && have_ge && min_ge < target) {
        place_first_with(min_ge);
        placed_any = true;
      } else if (attempt_subset && min_pos != kBig && min_pos < target &&
                 drain(std::min<int64_t>(target, kBig))) {
        placed_any = true;
      } else {
        segs.clear();
        if (have_ge) {
          place_first_with(min_ge);
          placed_any = true;
        } else {
          placed_any = drain(static_cast<int64_t>(kBig));
        }
      }
    }
  }

  bool driver_hosts_exec = false;
  cs.newly.clear();
  if (placed_any) {
    for (const auto& seg : segs) {
      const int32_t i = seg.first;
      if (i == didx) driver_hosts_exec = true;
      a0[i] = wrap_sub(a0[i], e[0]);
      a1[i] = wrap_sub(a1[i], e[1]);
      a2[i] = wrap_sub(a2[i], e[2]);
      cs.newly.push_back(i);
    }
  } else {
    segs.clear();
  }
  if (!driver_hosts_exec) {
    a0[didx] = wrap_sub(a0[didx], d[0]);
    a1[didx] = wrap_sub(a1[didx], d[1]);
    a2[didx] = wrap_sub(a2[didx], d[2]);
    cs.newly.push_back(didx);
  }
  class_absorb(cs, a0, a1, a2, exec_ok);
  return didx;
}

// ---------------------------------------------------------------------------
// Decision-provenance explainer (ops side: provenance/explain.py).
//
// A refused driver's verdict is a bare infeasible bit; the explainer
// recovers the WHY: which dimension is short and by how much (the
// shortfall vector), which node comes closest to hosting the gang, and
// which earlier FIFO drivers consumed the capacity this app needed (the
// blocker set).  Runs only on demand — the hot solve paths never call
// any of this, and the StepUsage capture they share is nullptr there.
// ---------------------------------------------------------------------------

// One feasibility probe of an app against fixed planes, with the
// diagnostic decomposition: full clamped capacity total, per-dim-alone
// totals (dim j as the only constraint — the argmin is the tightest
// dimension), the best single node, and the count of driver candidates
// whose availability covers the driver row.  Feasibility reproduces
// step_app_plain's rule exactly (min-frag feasibility equals tightly's:
// the drain is work-conserving), so a probe verdict always matches the
// solver's verdict at the same planes.
struct ExplainProbe {
  int64_t dim_total[kDims] = {0, 0, 0};
  int64_t cap_total = 0;
  int32_t max_cap = 0;
  int32_t max_node = -1;
  int64_t driver_fit = 0;
  bool feasible = false;
};

void explain_probe(const int32_t* a0, const int32_t* a1, const int32_t* a2,
                   const uint8_t* eok, int64_t nb,
                   const std::vector<int32_t>& cand, const int32_t* d,
                   const int32_t* e, int32_t k,
                   std::vector<int32_t>& cap_ws, ExplainProbe* out) {
  cap_ws.resize(nb);
  int32_t* cap = cap_ws.data();
  const int64_t total = cap_pass_all(a0, a1, a2, eok, nb, e, k, cap);
  out->cap_total = total;
  const int32_t* planes[kDims] = {a0, a1, a2};
  for (int j = 0; j < kDims; ++j) {
    int64_t tj = 0;
    const int32_t* a = planes[j];
    if (e[j] == 0) {
      // a zero-requirement dim bounds nothing unless overdrawn: per
      // node it contributes the full clamp k when non-negative
      for (int64_t i = 0; i < nb; ++i) {
        if (eok[i] && a[i] >= 0) tj += k;
      }
    } else {
      const int32_t den = std::max(e[j], 1);
      for (int64_t i = 0; i < nb; ++i) {
        if (!eok[i] || a[i] <= 0) continue;
        tj += std::min<int64_t>(a[i] / den, k);
      }
    }
    out->dim_total[j] = tj;
  }
  int32_t maxc = 0;
  int64_t maxi = -1;
  for (int64_t i = 0; i < nb; ++i) {
    if (cap[i] > maxc) {
      maxc = cap[i];
      maxi = i;
    }
  }
  out->max_cap = maxc;
  out->max_node = static_cast<int32_t>(maxi);
  int64_t dfit = 0;
  bool feas = false;
  for (int32_t i : cand) {
    const int32_t a[kDims] = {a0[i], a1[i], a2[i]};
    if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
    ++dfit;
    if (!feas && total >= k) {
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      const int32_t cwd = eok[i] ? clamped_cap(am, e, k) : 0;
      if (total - cap[i] + cwd >= k) feas = true;
    }
  }
  out->driver_fit = dfit;
  out->feasible = feas;
}

// ---------------------------------------------------------------------------
// Exact packing-efficiency math (efficiency.go:80-105 via
// ops/fifo_solver.efficiencies_from_rows): float64 ops in the same IEEE
// order as the numpy columns, so zone scores are bit-identical to the
// solver's host lane.
// ---------------------------------------------------------------------------

inline int64_t ceil_div64(int64_t a, int64_t b) {  // b > 0
  return a >= 0 ? (a + b - 1) / b : -((-a) / b);
}

// int64 wrap arithmetic matching numpy's (signed overflow is UB in C++,
// defined mod 2^64 via unsigned)
inline int64_t wrap_addsub64(int64_t s, int64_t sub, int64_t add) {
  return static_cast<int64_t>(static_cast<uint64_t>(s) -
                              static_cast<uint64_t>(sub) +
                              static_cast<uint64_t>(add));
}

// max(gpu, cpu, memory) of one node's reserved/schedulable ratios.
// s* are base-unit schedulable rows (milli-cpu, bytes, milli-gpu);
// r* the reserved numerators (same units).
inline double max_eff(int64_t s0, int64_t s1, int64_t s2, int64_t r0,
                      int64_t r1, int64_t r2) {
  const int64_t den_c = std::max<int64_t>(ceil_div64(s0, 1000), 1);
  const double cpu =
      static_cast<double>(ceil_div64(r0, 1000)) / static_cast<double>(den_c);
  const double mem = static_cast<double>(r1) /
                     static_cast<double>(std::max<int64_t>(s1, 1));
  const int64_t s_gpu = ceil_div64(s2, 1000);
  double gpu = 0.0;
  if (s_gpu != 0) {
    gpu = static_cast<double>(ceil_div64(r2, 1000)) /
          static_cast<double>(std::max<int64_t>(s_gpu, 1));
  }
  return std::max(gpu, std::max(cpu, mem));
}

}  // namespace

extern "C" {

// Whole-FIFO-queue solve (batch_solver.solve_queue semantics,
// with_placements=False): scan apps in order carrying availability.
//   avail_io      [nb*3] int32 row-major — updated in place to the
//                 post-queue availability
//   driver_rank   [nb] int32 (kBig = not a driver candidate)
//   exec_ok       [nb] uint8
//   drivers/executors [na*3] int32, counts [na] int32, app_valid [na] u8
//   evenly        0 = tightly-pack fill, 1 = distribute-evenly mask
//   out_feasible  [na] uint8
//   out_driver_idx[na] int32 (= nb when infeasible)
// Scratch buffers are internal; returns 1 (always succeeds).
int fifo_solve_queue(int64_t nb, int64_t na, int32_t* avail_io,
                     const int32_t* driver_rank, const uint8_t* exec_ok,
                     const int32_t* drivers, const int32_t* executors,
                     const int32_t* counts, const uint8_t* app_valid,
                     int evenly, uint8_t* out_feasible,
                     int32_t* out_driver_idx) {
  // rank-sorted driver candidates, built once (ranks are unique);
  // availability as column planes for the SIMD capacity pass, written
  // back to the row-major buffer at the end.  The per-app step itself
  // is shared with the persistent session (step_app_plain): capacity
  // pass, first-rank driver probe whose total < k early-out is exact
  // (for fitting nodes avail−driver stays in [0, avail], so capacity
  // can only shrink), and the sparkpods.go:139-146 subtraction quirk.
  std::vector<int32_t> cand = build_cand(driver_rank, nb);
  std::vector<int32_t> a0, a1, a2;
  split_planes(avail_io, nb, a0, a1, a2);
  QueueScratch ws;
  ws.cap.resize(nb);

  for (int64_t ai = 0; ai < na; ++ai) {
    const int32_t* d = drivers + ai * kDims;
    const int32_t* e = executors + ai * kDims;
    const int32_t k = counts[ai];
    out_feasible[ai] = 0;
    out_driver_idx[ai] = static_cast<int32_t>(nb);
    if (!app_valid[ai]) continue;
    int32_t didx = step_app_plain(a0.data(), a1.data(), a2.data(), exec_ok,
                                  nb, cand, d, e, k, evenly, ws, nullptr);
    if (didx < 0) continue;
    out_feasible[ai] = 1;
    out_driver_idx[ai] = didx;
  }
  join_planes(a0, a1, a2, nb, avail_io);
  return 1;
}

// Whole-FIFO-queue solve under the minimal-fragmentation policy
// (batch_solver.solve_queue_min_frag semantics, with_placements=False):
// feasibility + driver choice equal tightly-pack's (the drain is work-
// conserving); the carried usage subtraction comes from the min-frag
// drain counts.  Caller must hold the MF sentinel guard
// (batch_solver.mf_sentinel_safe) exactly like the device lanes.
int fifo_solve_queue_minfrag(int64_t nb, int64_t na, int32_t* avail_io,
                             const int32_t* driver_rank,
                             const uint8_t* exec_ok, const int32_t* drivers,
                             const int32_t* executors, const int32_t* counts,
                             const uint8_t* app_valid, uint8_t* out_feasible,
                             int32_t* out_driver_idx) {
  // per-app step shared with the persistent session (step_app_minfrag):
  // one fused pass yields both the UNCLAMPED min-frag capacities and
  // the tightly feasibility total, the driver-node capacity is fixed up
  // after the choice (batch_solver.min_frag_step_counts), and the
  // carried subtraction comes from the drain segments.
  std::vector<int32_t> cand = build_cand(driver_rank, nb);
  std::vector<int32_t> a0, a1, a2;
  split_planes(avail_io, nb, a0, a1, a2);
  QueueScratch ws;
  ws.mf_caps.resize(nb);

  for (int64_t ai = 0; ai < na; ++ai) {
    const int32_t* d = drivers + ai * kDims;
    const int32_t* e = executors + ai * kDims;
    const int32_t k = counts[ai];
    out_feasible[ai] = 0;
    out_driver_idx[ai] = static_cast<int32_t>(nb);
    if (!app_valid[ai]) continue;
    int32_t didx = step_app_minfrag(a0.data(), a1.data(), a2.data(), exec_ok,
                                    nb, cand, d, e, k, ws, nullptr);
    if (didx < 0) continue;
    out_feasible[ai] = 1;
    out_driver_idx[ai] = didx;
  }
  join_planes(a0, a1, a2, nb, avail_io);
  return 1;
}

// Whole-FIFO-queue solve over node equivalence classes (ROADMAP 2):
// byte-identical verdicts and post-queue availability to
// fifo_solve_queue / fifo_solve_queue_minfrag at the same inputs, with
// the per-app cost O(classes + diverged overlay) instead of O(nodes).
//   apps8    [na][8] packed rows: d0 d1 d2 e0 e1 e2 count valid
//   policy   0 tightly-pack, 1 distribute-evenly, 2 min-frag
//   out_stats (nullable) [4] int64 compression evidence:
//     [0] classes at the initial partition   [1] partition rebuilds
//     [2] overlay peak size                  [3] classes at the last rebuild
// Returns 1 (always succeeds).
int fifo_solve_queue_classes(int64_t nb, int64_t na, int32_t* avail_io,
                             const int32_t* driver_rank,
                             const uint8_t* exec_ok, const int32_t* apps8,
                             int policy, uint8_t* out_feasible,
                             int32_t* out_didx, int64_t* out_stats) {
  std::vector<int32_t> cand = build_cand(driver_rank, nb);
  std::vector<int32_t> a0, a1, a2;
  split_planes(avail_io, nb, a0, a1, a2);
  MfSegs segs;
  ClassSolver cs;
  class_rebuild(cs, a0.data(), a1.data(), a2.data(), exec_ok, nb);
  const int64_t classes_initial = cs.classes_last;
  for (int64_t ai = 0; ai < na; ++ai) {
    const int32_t* row = apps8 + ai * 8;
    const int32_t* d = row;
    const int32_t* e = row + 3;
    const int32_t k = row[6];
    out_feasible[ai] = 0;
    out_didx[ai] = static_cast<int32_t>(nb);
    if (!row[7]) continue;
    int32_t di;
    if (policy == 2) {
      di = step_app_minfrag_classes(cs, a0.data(), a1.data(), a2.data(),
                                    exec_ok, nb, cand, d, e, k, segs);
    } else {
      di = step_app_plain_classes(cs, a0.data(), a1.data(), a2.data(),
                                  exec_ok, nb, cand, d, e, k, policy == 1);
    }
    if (di >= 0) {
      out_feasible[ai] = 1;
      out_didx[ai] = di;
    }
  }
  join_planes(a0, a1, a2, nb, avail_io);
  if (out_stats != nullptr) {
    out_stats[0] = classes_initial;
    out_stats[1] = cs.rebuilds;
    out_stats[2] = cs.ov_peak;
    out_stats[3] = cs.classes_last;
  }
  return 1;
}

// Whole-FIFO-queue solve for the single-AZ policies
// (single_az.go:23-97 × resource.go:224-262): per app, per-zone
// tightly-pack (or min-frag) solves with the zone chosen by EXACT
// float64 average packing efficiency — the same IEEE operation sequence
// as the solver's host lane (pack_one → _choose_best_result), so no
// fixed-point uncertainty valve is needed.
//   zone_id      [nb] int32 — disjoint candidate-zone index per node
//                (-1 = in no candidate zone)
//   sched_base   [nb*3] int64 — base-unit schedulable rows
//   scale        [3] int64 — tensorize scale vector
//   az_aware     adds the cross-zone tightly-pack fallback (zone = nz)
//   minfrag      single-az-minimal-fragmentation inner placements
//   strict       reference no-write-back quirk: zone scores see only the
//                driver's reservation
//   out_zone     [na] int32 — chosen zone; nz = cross-zone; -1 = none
int fifo_solve_queue_single_az(
    int64_t nb, int64_t na, int64_t nz, int32_t* avail_io,
    const int32_t* driver_rank, const uint8_t* exec_ok,
    const int32_t* zone_id, const int32_t* drivers, const int32_t* executors,
    const int32_t* counts, const uint8_t* app_valid,
    const int64_t* sched_base, const int64_t* scale, int az_aware,
    int minfrag, int strict, uint8_t* out_feasible, int32_t* out_zone,
    int32_t* out_driver_idx) {
  std::vector<int32_t> cand;
  cand.reserve(nb);
  for (int64_t i = 0; i < nb; ++i) {
    if (driver_rank[i] < kBig) cand.push_back(static_cast<int32_t>(i));
  }
  std::sort(cand.begin(), cand.end(), [&](int32_t x, int32_t y) {
    return driver_rank[x] < driver_rank[y];
  });

  std::vector<int32_t> a0(nb), a1(nb), a2(nb), cap(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = avail_io[i * kDims + 0];
    a1[i] = avail_io[i * kDims + 1];
    a2[i] = avail_io[i * kDims + 2];
  }

  std::vector<int64_t> total_z(std::max<int64_t>(nz, 1));
  std::vector<int32_t> didx_z(std::max<int64_t>(nz, 1));
  std::vector<int32_t> capd_z(std::max<int64_t>(nz, 1));
  std::vector<MfSegs> segs_z(std::max<int64_t>(nz, 1));
  std::vector<int32_t> mf_caps(nb);
  MfScratch mf_ws;
  // per-zone eligibility bytes: lets the min-frag capacity pass run
  // vectorized per zone instead of a branchy zone_id test per node
  std::vector<std::vector<uint8_t>> zone_elig;
  if (minfrag) {
    zone_elig.assign(std::max<int64_t>(nz, 1), std::vector<uint8_t>(nb, 0));
    for (int64_t i = 0; i < nb; ++i) {
      const int32_t z = zone_id[i];
      if (z >= 0 && z < nz && exec_ok[i]) zone_elig[z][i] = 1;
    }
  }

  // reserved/schedulable ratio of one node under this app's packing
  // (eff_count executors + the driver when on it), exact float64
  auto node_max_eff = [&](int64_t i, int64_t eff_count, const int32_t* d,
                          const int32_t* e, bool is_driver) {
    int64_t r[kDims];
    for (int j = 0; j < kDims; ++j) {
      const int64_t res =
          eff_count * e[j] + (is_driver ? static_cast<int64_t>(d[j]) : 0);
      const int64_t avail_j =
          static_cast<int64_t>((j == 0 ? a0 : j == 1 ? a1 : a2)[i]);
      r[j] = wrap_addsub64(
          sched_base[i * kDims + j],
          static_cast<int64_t>(
              static_cast<uint64_t>(avail_j) *
              static_cast<uint64_t>(scale[j])),
          static_cast<int64_t>(
              static_cast<uint64_t>(res) * static_cast<uint64_t>(scale[j])));
    }
    return max_eff(sched_base[i * kDims + 0], sched_base[i * kDims + 1],
                   sched_base[i * kDims + 2], r[0], r[1], r[2]);
  };

  for (int64_t ai = 0; ai < na; ++ai) {
    const int32_t* d = drivers + ai * kDims;
    const int32_t* e = executors + ai * kDims;
    const int32_t k = counts[ai];
    out_feasible[ai] = 0;
    out_zone[ai] = -1;
    out_driver_idx[ai] = static_cast<int32_t>(nb);
    if (!app_valid[ai]) continue;

    cap_pass_all(a0.data(), a1.data(), a2.data(), exec_ok, nb, e, k,
                 cap.data());
    std::fill(total_z.begin(), total_z.end(), 0);
    for (int64_t i = 0; i < nb; ++i) {
      const int32_t z = zone_id[i];
      if (z >= 0 && z < nz) total_z[z] += cap[i];
    }

    // one rank-ordered walk finds every zone's first feasible driver
    std::fill(didx_z.begin(), didx_z.end(), -1);
    int64_t found = 0;
    for (int32_t i : cand) {
      if (found == nz) break;
      const int32_t z = zone_id[i];
      if (z < 0 || z >= nz || didx_z[z] >= 0) continue;
      int32_t a[kDims] = {a0[i], a1[i], a2[i]};
      if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
      int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
      if (total_z[z] - cap[i] + cwd >= k) {
        didx_z[z] = i;
        capd_z[z] = cwd;
        ++found;
      }
    }

    // per feasible zone: placement segments + exact zone score
    int32_t best_zone = -1;
    double best_avg = 0.0;
    for (int64_t z = 0; z < nz; ++z) {
      const int32_t dz = didx_z[z];
      if (dz < 0) continue;
      MfSegs& segs = segs_z[z];
      segs.clear();
      bool ok = true;
      if (minfrag) {
        // drain over UNCLAMPED zone capacities (vectorized pass over the
        // per-zone eligibility bytes), driver subtracted on its node
        mf_cap_pass_all(a0.data(), a1.data(), a2.data(),
                        zone_elig[z].data(), nb, e, k, mf_caps.data());
        if (zone_elig[z][dz]) {
          int32_t av[kDims];
          for (int j = 0; j < kDims; ++j)
            av[j] = wrap_sub((j == 0 ? a0 : j == 1 ? a1 : a2)[dz], d[j]);
          mf_caps[dz] = mf_cap_one(av[0], av[1], av[2], e);
        }
        if (k > 0)
          ok = mf_assign(mf_caps, k, mf_extremes(mf_caps, k, mf_ws.copy),
                         mf_ws, segs);
      } else if (k > 0) {
        // tightly-pack greedy fill in node order within the zone
        int64_t cum = 0;
        for (int64_t i = 0; i < nb && cum < k; ++i) {
          if (zone_id[i] != z) continue;
          const int64_t c = (i == dz) ? capd_z[z] : cap[i];
          if (c <= 0) continue;
          const int64_t take = std::min<int64_t>(c, k - cum);
          segs.emplace_back(static_cast<int32_t>(i), take);
          cum += take;
        }
        ok = cum == k;  // guaranteed by the driver-choice condition
      }
      if (!ok) {
        didx_z[z] = -1;
        continue;
      }
      // occurrence-ordered float64 sum of per-node max efficiencies
      // ([driver] + executor placements, single_az.go:75-97).  Under
      // strict min-frag parity the reservation side sees only the
      // driver (the reference's no-write-back quirk); occurrences still
      // weight every placement.
      const bool eff_zero = minfrag && strict;
      double max_sum = 0.0;
      {
        int64_t eff_driver = 0;
        if (!eff_zero) {
          for (const auto& seg : segs) {
            if (seg.first == dz) eff_driver = seg.second;
          }
        }
        max_sum += node_max_eff(dz, eff_driver, d, e, true);
      }
      for (const auto& seg : segs) {
        const int64_t eff_count = eff_zero ? 0 : seg.second;
        const double v =
            node_max_eff(seg.first, eff_count, d, e, seg.first == dz);
        for (int64_t c = 0; c < seg.second; ++c) max_sum += v;
      }
      const double avg =
          max_sum / static_cast<double>(static_cast<int64_t>(k) + 1);
      if (best_avg < avg) {  // strict improvement, zone order
        best_avg = avg;
        best_zone = static_cast<int32_t>(z);
      }
    }

    int32_t chosen_didx = -1;
    const MfSegs* chosen_segs = nullptr;
    MfSegs cross_segs;
    if (best_zone >= 0) {
      chosen_didx = didx_z[best_zone];
      chosen_segs = &segs_z[best_zone];
    } else if (az_aware) {
      // cross-zone tightly-pack fallback (az_aware_pack_tightly.go:27-38)
      int64_t total = 0;
      for (int64_t i = 0; i < nb; ++i) total += cap[i];
      int32_t didx = -1, capd = 0;
      if (total >= k) {
        for (int32_t i : cand) {
          int32_t a[kDims] = {a0[i], a1[i], a2[i]};
          if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
          int32_t am[kDims];
          for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
          int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
          if (total - cap[i] + cwd >= k) {
            didx = i;
            capd = cwd;
            break;
          }
        }
      }
      if (didx >= 0) {
        int64_t cum = 0;
        for (int64_t i = 0; i < nb && cum < k; ++i) {
          const int64_t c = (i == didx) ? capd : cap[i];
          if (c <= 0) continue;
          const int64_t take = std::min<int64_t>(c, k - cum);
          cross_segs.emplace_back(static_cast<int32_t>(i), take);
          cum += take;
        }
        chosen_didx = didx;
        chosen_segs = &cross_segs;
        best_zone = static_cast<int32_t>(nz);
      }
    }
    if (chosen_didx < 0) continue;

    out_feasible[ai] = 1;
    out_zone[ai] = best_zone;
    out_driver_idx[ai] = chosen_didx;

    bool driver_hosts_exec = false;
    for (const auto& seg : *chosen_segs) {
      const int32_t i = seg.first;
      if (i == chosen_didx) driver_hosts_exec = true;
      a0[i] = wrap_sub(a0[i], e[0]);
      a1[i] = wrap_sub(a1[i], e[1]);
      a2[i] = wrap_sub(a2[i], e[2]);
    }
    if (!driver_hosts_exec) {
      a0[chosen_didx] = wrap_sub(a0[chosen_didx], d[0]);
      a1[chosen_didx] = wrap_sub(a1[chosen_didx], d[1]);
      a2[chosen_didx] = wrap_sub(a2[chosen_didx], d[2]);
    }
  }
  for (int64_t i = 0; i < nb; ++i) {
    avail_io[i * kDims + 0] = a0[i];
    avail_io[i * kDims + 1] = a1[i];
    avail_io[i * kDims + 2] = a2[i];
  }
  return 1;
}

// Single-app solve against a fixed availability (batch_solver.solve_app
// semantics): fills out_exec_counts [nb] with the tightly-pack fill
// counts and out_caps [nb] with the post-driver-placement capacities
// (AppSolve.exec_capacity — the distribute-evenly decode consumes
// these; both zeroed when infeasible).  Availability is NOT mutated.
int fifo_solve_app(int64_t nb, const int32_t* avail,
                   const int32_t* driver_rank, const uint8_t* exec_ok,
                   const int32_t* driver, const int32_t* executor,
                   int32_t k, uint8_t* out_feasible, int32_t* out_driver_idx,
                   int32_t* out_exec_counts, int32_t* out_caps) {
  *out_feasible = 0;
  *out_driver_idx = static_cast<int32_t>(nb);
  for (int64_t i = 0; i < nb; ++i) out_exec_counts[i] = 0;
  for (int64_t i = 0; i < nb; ++i) out_caps[i] = 0;

  std::vector<int32_t> cap(nb);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? clamped_cap(avail + i * kDims, executor, k) : 0;
    cap[i] = c;
    total += c;
  }
  int32_t best_rank = kBig;
  int32_t didx = -1;
  int32_t capd = 0;
  if (total >= k) {
    for (int64_t i = 0; i < nb; ++i) {
      if (driver_rank[i] >= best_rank) continue;
      const int32_t* a = avail + i * kDims;
      if (a[0] < driver[0] || a[1] < driver[1] || a[2] < driver[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], driver[j]);
      int32_t cwd = exec_ok[i] ? clamped_cap(am, executor, k) : 0;
      if (total - cap[i] + cwd >= k) {
        best_rank = driver_rank[i];
        didx = static_cast<int32_t>(i);
        capd = cwd;
      }
    }
  }
  if (didx < 0) return 1;
  *out_feasible = 1;
  *out_driver_idx = didx;
  cap[didx] = capd;
  int64_t cum = 0;
  for (int64_t i = 0; i < nb; ++i) {
    out_caps[i] = cap[i];
    if (cum < k) {
      int64_t take = std::min<int64_t>(cap[i], k - cum);
      if (take > 0) {
        out_exec_counts[i] = static_cast<int32_t>(take);
        cum += take;
      }
    }
  }
  return 1;
}

// ---------------------------------------------------------------------------
// Persistent solver session (ops/deltasolve.py) — the warm path of the
// incremental delta-solve engine.
//
// A session pins one (cluster basis, policy) problem in native memory:
// the scaled availability planes at queue position 0, the rank-sorted
// driver-candidate list (sorted ONCE per basis instead of once per
// request), the queue rows it last solved with their per-position
// verdicts, and prefix checkpoints of the carried availability every
// `stride` positions plus the final tail.  A warm solve self-verifies
// the queue prefix byte-for-byte against the cached rows (the Python
// caller's id-based bookkeeping is an optimization, never a correctness
// input), restores the nearest checkpoint at or below the first changed
// position, and re-runs only the suffix — O(changed suffix × nodes)
// instead of O(queue × nodes).
//
// Checkpoint memory is bounded: at most kMaxCheckpoints live at once;
// when the queue grows past stride × kMaxCheckpoints the stride doubles
// and odd checkpoints are dropped (positions at even multiples of the
// old stride are exactly the multiples of the new one), so resume
// granularity degrades gracefully instead of memory growing with the
// queue.
// ---------------------------------------------------------------------------

namespace {
constexpr int64_t kMaxCheckpoints = 24;
}

struct FifoSession {
  int64_t nb = 0;
  int policy = 0;  // 0 tightly-pack, 1 distribute-evenly, 2 min-frag
  int64_t stride = 64;
  std::vector<int32_t> basis0, basis1, basis2;  // planes at position 0
  std::vector<uint8_t> eok;
  std::vector<int32_t> cand;  // rank-sorted driver candidates
  // last-solved queue: packed rows [na][8] = d0 d1 d2 e0 e1 e2 count
  // valid, plus the per-position verdicts
  std::vector<int32_t> apps;
  std::vector<uint8_t> feas;
  std::vector<int32_t> didx;
  int64_t na = 0;
  // chk*[j] = planes BEFORE the app at position (j+1)*stride
  std::vector<std::vector<int32_t>> chk0, chk1, chk2;
  // planes after all `na` cached apps (the "checkpoint at position na")
  std::vector<int32_t> tail0, tail1, tail2;
  std::vector<int32_t> a0, a1, a2;  // working planes
  QueueScratch ws;
  SweepPool* pool = nullptr;
  // class-compressed stepping (opt-in): the partition mirrors the
  // working planes at queue position cls_pos (-1 = stale, rebuild
  // before stepping).  A warm full-prefix resume (r == na) keeps the
  // partition synced at the tail, so the steady state never rebuilds.
  int use_classes = 0;
  int64_t cls_pos = -1;
  ClassSolver cls;
  ~FifoSession() { delete pool; }
};

extern "C" void* fifo_sess_create() {
  return new (std::nothrow) FifoSession();
}

extern "C" void fifo_sess_destroy(void* handle) {
  delete static_cast<FifoSession*>(handle);
}

// (Re)load the session basis: scaled availability rows [nb,3] at queue
// position 0, driver ranks, executor eligibility, policy, checkpoint
// stride, worker count for the sharded cold pass (engages only when
// n_threads > 1 and nb >= min_pool_nodes).  Drops all cached queue
// state.  Returns 1 on success.
extern "C" int fifo_sess_load(void* handle, int64_t nb,
                              const int32_t* avail_rows,
                              const int32_t* driver_rank,
                              const uint8_t* exec_ok, int policy,
                              int64_t stride, int n_threads,
                              int64_t min_pool_nodes) {
  FifoSession* s = static_cast<FifoSession*>(handle);
  if (s == nullptr || nb <= 0 || stride <= 0) return 0;
  s->nb = nb;
  s->policy = policy;
  s->stride = stride;
  split_planes(avail_rows, nb, s->basis0, s->basis1, s->basis2);
  s->eok.assign(exec_ok, exec_ok + nb);
  s->cand = build_cand(driver_rank, nb);
  s->apps.clear();
  s->feas.clear();
  s->didx.clear();
  s->na = 0;
  s->chk0.clear();
  s->chk1.clear();
  s->chk2.clear();
  s->tail0 = s->basis0;
  s->tail1 = s->basis1;
  s->tail2 = s->basis2;
  s->a0.resize(nb);
  s->a1.resize(nb);
  s->a2.resize(nb);
  s->ws.cap.resize(nb);
  s->ws.mf_caps.resize(nb);
  s->cls_pos = -1;
  int want = std::min(n_threads, kMaxPoolThreads);
  if (want <= 1 || nb < min_pool_nodes) {
    delete s->pool;
    s->pool = nullptr;
  } else if (s->pool == nullptr || s->pool->workers() != want) {
    delete s->pool;
    s->pool = new (std::nothrow) SweepPool(want);
  }
  return 1;
}

// Solve the queue `apps8` ([na][8] packed rows, same scaled units as
// the loaded basis) against the session basis, resuming from the
// nearest prefix checkpoint.  Writes per-position verdicts and the
// post-queue availability rows.  Returns the resume position (0 = full
// cold solve, na = everything served from cache), or -2 when the
// session has no basis.
extern "C" int64_t fifo_sess_solve(void* handle, int64_t na,
                                   const int32_t* apps8, uint8_t* out_feas,
                                   int32_t* out_didx,
                                   int32_t* out_avail_rows) {
  FifoSession* s = static_cast<FifoSession*>(handle);
  if (s == nullptr || s->nb == 0 || na < 0) return -2;
  const int64_t nb = s->nb;

  // 1. first position whose packed row differs from the cached run —
  // blocked memcmp then a row scan, so the common all-equal prefix
  // costs one pass of memcmp bandwidth (~us at 1k apps)
  const int64_t lim = std::min(na, s->na);
  int64_t diff = lim;
  {
    const int32_t* cached = s->apps.data();
    constexpr int64_t B = 256;
    int64_t i = 0;
    while (i < lim) {
      const int64_t hi = std::min(lim, i + B);
      if (std::memcmp(apps8 + i * 8, cached + i * 8,
                      static_cast<size_t>(hi - i) * 8 * sizeof(int32_t)) ==
          0) {
        i = hi;
        continue;
      }
      while (i < hi && std::memcmp(apps8 + i * 8, cached + i * 8,
                                   8 * sizeof(int32_t)) == 0) {
        ++i;
      }
      break;
    }
    diff = i;
  }

  // 2. stride doubling keeps the checkpoint set bounded as na grows
  while (na / s->stride > kMaxCheckpoints) {
    const int64_t keep = static_cast<int64_t>(s->chk0.size()) / 2;
    for (int64_t j = 0; j < keep; ++j) {
      // old index 2j+1 holds position (2j+2)·stride = (j+1)·(2·stride)
      s->chk0[j] = std::move(s->chk0[2 * j + 1]);
      s->chk1[j] = std::move(s->chk1[2 * j + 1]);
      s->chk2[j] = std::move(s->chk2[2 * j + 1]);
    }
    s->chk0.resize(keep);
    s->chk1.resize(keep);
    s->chk2.resize(keep);
    s->stride *= 2;
  }

  // 3. resume position: the largest checkpointed position ≤ diff (the
  // tail counts as the checkpoint at position s->na)
  int64_t r;
  if (diff >= s->na) {
    r = s->na;
  } else {
    int64_t j = diff / s->stride;
    if (j > static_cast<int64_t>(s->chk0.size())) {
      j = static_cast<int64_t>(s->chk0.size());
    }
    r = j * s->stride;
  }

  // 4. restore working planes from that checkpoint
  if (r == s->na) {
    s->a0 = s->tail0;
    s->a1 = s->tail1;
    s->a2 = s->tail2;
  } else if (r == 0) {
    s->a0 = s->basis0;
    s->a1 = s->basis1;
    s->a2 = s->basis2;
  } else {
    const int64_t j = r / s->stride - 1;
    s->a0 = s->chk0[j];
    s->a1 = s->chk1[j];
    s->a2 = s->chk2[j];
  }

  // 5. checkpoints past the resume point describe a superseded suffix
  const int64_t keep_chk = r / s->stride;
  if (static_cast<int64_t>(s->chk0.size()) > keep_chk) {
    s->chk0.resize(keep_chk);
    s->chk1.resize(keep_chk);
    s->chk2.resize(keep_chk);
  }

  // 6. adopt the new queue rows + verdict storage (prefix verdicts for
  // [0, r) stay valid by construction)
  s->apps.assign(apps8, apps8 + na * 8);
  s->feas.resize(na);
  s->didx.resize(na);

  // 7. solve the suffix, dropping fresh checkpoints as positions pass
  int32_t* a0 = s->a0.data();
  int32_t* a1 = s->a1.data();
  int32_t* a2 = s->a2.data();
  const uint8_t* eok = s->eok.data();
  // class mode: the partition must mirror the restored planes.  It does
  // iff it was left at exactly this queue position (the warm tail
  // resume); any other restore point rebuilds it in one O(nb) pass.
  if (s->use_classes && s->cls_pos != r && r < na) {
    class_rebuild(s->cls, a0, a1, a2, eok, nb);
  }
  for (int64_t i = r; i < na; ++i) {
    if (i > 0 && i % s->stride == 0 &&
        static_cast<int64_t>(s->chk0.size()) == i / s->stride - 1) {
      s->chk0.push_back(s->a0);
      s->chk1.push_back(s->a1);
      s->chk2.push_back(s->a2);
    }
    const int32_t* row = s->apps.data() + i * 8;
    const int32_t* d = row;
    const int32_t* e = row + 3;
    const int32_t k = row[6];
    s->feas[i] = 0;
    s->didx[i] = static_cast<int32_t>(nb);
    if (!row[7]) continue;
    int32_t di;
    if (s->use_classes) {
      if (s->policy == 2) {
        di = step_app_minfrag_classes(s->cls, a0, a1, a2, eok, nb, s->cand,
                                      d, e, k, s->ws.segs);
      } else {
        di = step_app_plain_classes(s->cls, a0, a1, a2, eok, nb, s->cand, d,
                                    e, k, s->policy == 1);
      }
    } else if (s->policy == 2) {
      di = step_app_minfrag(a0, a1, a2, eok, nb, s->cand, d, e, k, s->ws,
                            s->pool);
    } else {
      di = step_app_plain(a0, a1, a2, eok, nb, s->cand, d, e, k,
                          s->policy == 1, s->ws, s->pool);
    }
    if (di >= 0) {
      s->feas[i] = 1;
      s->didx[i] = di;
    }
  }

  // 8. tail + outputs
  s->tail0 = s->a0;
  s->tail1 = s->a1;
  s->tail2 = s->a2;
  s->na = na;
  if (s->use_classes) {
    // partition mirrors the new tail unless the queue was truncated to
    // a checkpoint with nothing to step (no rebuild ran there)
    s->cls_pos = (r < na || s->cls_pos == r) ? na : -1;
  }
  if (na > 0) {
    std::memcpy(out_feas, s->feas.data(), static_cast<size_t>(na));
    std::memcpy(out_didx, s->didx.data(),
                static_cast<size_t>(na) * sizeof(int32_t));
  }
  join_planes(s->a0, s->a1, s->a2, nb, out_avail_rows);
  return r;
}

// Toggle class-compressed stepping for the session (ROADMAP 2).  The
// partition is built lazily at the next solve; verdicts and planes are
// byte-identical either way, so this is purely a performance mode.
extern "C" void fifo_sess_set_classes(void* handle, int enable) {
  FifoSession* s = static_cast<FifoSession*>(handle);
  if (s == nullptr) return;
  s->use_classes = enable != 0;
  s->cls_pos = -1;
}

// Compression evidence of the session's class partition: [0] class
// count at the last rebuild, [1] cumulative rebuilds, [2] overlay peak,
// [3] current overlay size.  Zeros until class mode has stepped.
extern "C" void fifo_sess_class_stats(void* handle, int64_t* out4) {
  FifoSession* s = static_cast<FifoSession*>(handle);
  if (s == nullptr || out4 == nullptr) return;
  out4[0] = s->cls.classes_last;
  out4[1] = s->cls.rebuilds;
  out4[2] = s->cls.ov_peak;
  out4[3] = static_cast<int64_t>(s->cls.ov_nodes.size());
}

// Resident bytes of the session's buffers (basis + checkpoints + tail +
// working planes + queue cache) — the soak's bounded-memory assertion
// reads this through the engine.
extern "C" int64_t fifo_sess_mem_bytes(void* handle) {
  FifoSession* s = static_cast<FifoSession*>(handle);
  if (s == nullptr) return 0;
  auto vb = [](const std::vector<int32_t>& v) {
    return static_cast<int64_t>(v.capacity()) * sizeof(int32_t);
  };
  int64_t total = vb(s->basis0) + vb(s->basis1) + vb(s->basis2) +
                  vb(s->tail0) + vb(s->tail1) + vb(s->tail2) + vb(s->a0) +
                  vb(s->a1) + vb(s->a2) + vb(s->cand) + vb(s->apps) +
                  vb(s->didx) + vb(s->ws.cap) + vb(s->ws.mf_caps) +
                  static_cast<int64_t>(s->eok.capacity()) +
                  static_cast<int64_t>(s->feas.capacity());
  for (const auto& c : s->chk0) total += vb(c);
  for (const auto& c : s->chk1) total += vb(c);
  for (const auto& c : s->chk2) total += vb(c);
  return total;
}

// Explain one queue position's verdict (provenance/explain.py): replay
// the queue from the given basis with the policy-correct step function,
// probing the target app's feasibility along the way, and report
//
//   out_info[0]  flip — the queue position whose (feasible) step turned
//                the target infeasible; -1 = target feasible at its own
//                position; -2 = infeasible even against the empty basis
//                (the cluster is undersized, no earlier driver to blame)
//   out_info[1]  target feasible at its own position (0/1)
//   out_info[2]  clamped capacity total at the target position
//   out_info[3..5]  per-dim-alone capacity totals (tightest = argmin)
//   out_info[6]  best single-node capacity,  out_info[7] its index
//   out_info[8]  driver candidates whose availability covers the driver
//   out_info[9]  tightest dimension (-1 = capacity fine, driver-blocked)
//   out_info[10] shortfall in executor units (k − capacity total)
//   out_info[11] blocker count
//   out_blockers [na] u8 — the blocker set: walking back from the flip
//                position, the feasible earlier drivers whose recorded
//                consumption in the tightest dimension covers the
//                resource shortfall (the preemption-vocabulary victim
//                candidates); the flip driver is always included
//
// Feasibility is monotone along the queue (steps only subtract), so
// probing stops at the first flip.  Cost: ≤ 2 cold solves worth of
// passes — explain is an on-demand diagnostic, never a hot path.
int fifo_explain_queue(int64_t nb, int64_t na, const int32_t* avail_rows,
                       const int32_t* driver_rank, const uint8_t* exec_ok,
                       const int32_t* apps8, int policy, int64_t target,
                       uint8_t* out_blockers, int64_t* out_info) {
  if (nb <= 0 || na <= 0 || target < 0 || target >= na) return 0;
  std::vector<int32_t> cand = build_cand(driver_rank, nb);
  std::vector<int32_t> a0, a1, a2;
  split_planes(avail_rows, nb, a0, a1, a2);
  QueueScratch ws;
  ws.cap.resize(nb);
  ws.mf_caps.resize(nb);
  std::vector<int32_t> probe_ws;
  for (int64_t i = 0; i < na; ++i) out_blockers[i] = 0;

  const int32_t* trow = apps8 + target * 8;
  const int32_t* td = trow;
  const int32_t* te = trow + 3;
  const int32_t tk = trow[6];

  ExplainProbe probe;
  explain_probe(a0.data(), a1.data(), a2.data(), exec_ok, nb, cand, td, te,
                tk, probe_ws, &probe);
  int64_t flip = -1;
  bool still_feasible = probe.feasible;
  if (!still_feasible) flip = -2;

  std::vector<std::array<int64_t, kDims>> used(
      target, std::array<int64_t, kDims>{0, 0, 0});
  std::vector<uint8_t> step_feas(target, 0);

  for (int64_t i = 0; i < target; ++i) {
    const int32_t* row = apps8 + i * 8;
    if (!row[7]) continue;
    StepUsage su;
    int32_t di;
    if (policy == 2) {
      di = step_app_minfrag(a0.data(), a1.data(), a2.data(), exec_ok, nb,
                            cand, row, row + 3, row[6], ws, nullptr, &su);
    } else {
      di = step_app_plain(a0.data(), a1.data(), a2.data(), exec_ok, nb, cand,
                          row, row + 3, row[6], policy == 1, ws, nullptr,
                          &su);
    }
    if (di < 0) continue;
    step_feas[i] = 1;
    for (int j = 0; j < kDims; ++j) {
      used[i][j] = static_cast<int64_t>(su.hosting_nodes) * row[3 + j] +
                   (su.driver_row_applied ? static_cast<int64_t>(row[j]) : 0);
    }
    if (still_feasible) {
      ExplainProbe after;
      explain_probe(a0.data(), a1.data(), a2.data(), exec_ok, nb, cand, td,
                    te, tk, probe_ws, &after);
      if (!after.feasible) {
        still_feasible = false;
        flip = i;
      }
    }
  }

  // the verdict the operator saw: the target against its own position
  explain_probe(a0.data(), a1.data(), a2.data(), exec_ok, nb, cand, td, te,
                tk, probe_ws, &probe);

  int64_t tightest = -1;
  int64_t shortfall = 0;
  if (!probe.feasible && probe.cap_total < tk) {
    for (int j = 0; j < kDims; ++j) {
      if (te[j] == 0) continue;
      if (tightest < 0 || probe.dim_total[j] < probe.dim_total[tightest]) {
        tightest = j;
      }
    }
    shortfall = tk - probe.cap_total;
  }

  int64_t blocker_count = 0;
  if (!probe.feasible && flip >= 0) {
    const int64_t need =
        (tightest >= 0) ? shortfall * static_cast<int64_t>(te[tightest]) : 0;
    int64_t freed = 0;
    for (int64_t i = flip; i >= 0; --i) {
      if (!step_feas[i]) continue;
      out_blockers[i] = 1;
      ++blocker_count;
      if (tightest < 0) break;  // driver-blocked: the flip driver alone
      freed += used[i][tightest];
      if (freed >= need) break;
    }
  }

  out_info[0] = flip;
  out_info[1] = probe.feasible ? 1 : 0;
  out_info[2] = probe.cap_total;
  out_info[3] = probe.dim_total[0];
  out_info[4] = probe.dim_total[1];
  out_info[5] = probe.dim_total[2];
  out_info[6] = probe.max_cap;
  out_info[7] = probe.max_node;
  out_info[8] = probe.driver_fit;
  out_info[9] = tightest;
  out_info[10] = shortfall;
  out_info[11] = blocker_count;
  return 1;
}

// ---------------------------------------------------------------------------
// Capacity-observatory probes (ops side: capacity/probe.py).
//
// What-if analytics against a FIXED availability basis: the largest
// gang of a given (driver, executor) shape the solver would admit, and
// a per-dimension fragmentation report.  Read-only — the planes are
// never mutated, and nothing here runs on a scheduling hot path.
// ---------------------------------------------------------------------------

// Batched headroom probe: for each shape s (rows [s*6..s*6+2] driver,
// [s*6+3..s*6+5] executor, same scaled units as avail_rows), the
// largest k in [0, k_max] for which the FIFO step at queue position 0
// would admit a gang of k executors — exactly step_app_plain's
// feasibility rule (shared by distribute-evenly, and by min-frag whose
// drain is work-conserving, so one probe covers all three policies).
//
// Feasibility is monotone in k: per node min(c,k)·(k+1) ≥ min(c,k+1)·k,
// so Σ min(c_i,k+1) ≥ k+1 implies Σ min(c_i,k) ≥ k, and the same
// scaling applies to the with-driver total of the k+1 witness
// candidate.  Bisection therefore needs O(log k_max) feasibility
// evaluations; the UNCLAMPED per-node capacities are computed once per
// shape (they are k-independent), so each evaluation is one clamp-sum
// sweep plus the driver-candidate walk.
//
// Outputs per shape:
//   out_headroom[s]    largest admissible k (0 = not even one executor,
//                      or no node covers the driver row)
//   out_usable[s*3+j]  Σ_i clamp(c_i, 0, k_max) · e_j — scaled units of
//                      dimension j actually reachable by executors of
//                      this shape (vs. raw free: the fragmentation gap)
//   out_probes[s]      feasibility evaluations spent (bisection depth)
int fifo_probe_headroom(int64_t nb, const int32_t* avail_rows,
                        const int32_t* driver_rank, const uint8_t* exec_ok,
                        int64_t nshapes, const int32_t* shapes,
                        int32_t k_max, int64_t* out_headroom,
                        int64_t* out_usable, int64_t* out_probes) {
  if (nb <= 0 || nshapes <= 0 || k_max <= 0) return 0;
  std::vector<int32_t> cand = build_cand(driver_rank, nb);
  std::vector<int32_t> a0, a1, a2;
  split_planes(avail_rows, nb, a0, a1, a2);
  std::vector<int32_t> caps(nb);

  for (int64_t s = 0; s < nshapes; ++s) {
    const int32_t* d = shapes + s * 6;
    const int32_t* e = shapes + s * 6 + 3;
    // unclamped exact-floor capacities (≤ 0 = ineligible), shared by
    // every feasibility evaluation of this shape
    cap_sweeps(a0.data(), a1.data(), a2.data(), nb, e, kMfSent, caps.data());
    for (int64_t i = 0; i < nb; ++i) {
      if (!exec_ok[i]) caps[i] = 0;
    }

    int64_t total_kmax = 0;
    for (int64_t i = 0; i < nb; ++i) {
      total_kmax += std::clamp<int32_t>(caps[i], 0, k_max);
    }
    for (int j = 0; j < kDims; ++j) {
      out_usable[s * 3 + j] = total_kmax * static_cast<int64_t>(e[j]);
    }

    int64_t probes = 0;
    auto feasible = [&](int32_t k) -> bool {
      ++probes;
      int64_t total = 0;
      for (int64_t i = 0; i < nb; ++i) {
        total += std::clamp<int32_t>(caps[i], 0, k);
      }
      if (total < k) return false;
      for (int32_t i : cand) {
        const int32_t a[kDims] = {a0[i], a1[i], a2[i]};
        if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
        int32_t am[kDims];
        for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
        const int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
        if (total - std::clamp<int32_t>(caps[i], 0, k) + cwd >= k) {
          return true;
        }
      }
      return false;
    };

    int64_t headroom = 0;
    int64_t hi = std::min<int64_t>(k_max, total_kmax);
    if (hi >= 1) {
      if (feasible(static_cast<int32_t>(hi))) {
        headroom = hi;
      } else if (feasible(1)) {
        // invariant: lo feasible, hi infeasible
        int64_t lo = 1;
        while (hi - lo > 1) {
          const int64_t mid = lo + (hi - lo) / 2;
          if (feasible(static_cast<int32_t>(mid))) {
            lo = mid;
          } else {
            hi = mid;
          }
        }
        headroom = lo;
      }
    }
    out_headroom[s] = headroom;
    out_probes[s] = probes;
  }
  return 1;
}

// One-sweep per-dimension fragmentation report over the eligible
// (exec_ok) rows:
//   out[j*4+0] total free      Σ max(avail_ij, 0)
//   out[j*4+1] largest chunk   max(avail_ij, 0) over single nodes
//   out[j*4+2] free nodes      count with avail_ij > 0
//   out[j*4+3] overdrawn nodes count with avail_ij < 0
// The fragmentation index (1 − largest/total) is computed by the
// Python caller, which also rescales to base units.
int fifo_frag_report(int64_t nb, const int32_t* avail_rows,
                     const uint8_t* exec_ok, int64_t* out12) {
  if (nb < 0) return 0;
  for (int j = 0; j < kDims * 4; ++j) out12[j] = 0;
  for (int64_t i = 0; i < nb; ++i) {
    if (!exec_ok[i]) continue;
    for (int j = 0; j < kDims; ++j) {
      const int64_t a = avail_rows[i * kDims + j];
      if (a > 0) {
        out12[j * 4 + 0] += a;
        if (a > out12[j * 4 + 1]) out12[j * 4 + 1] = a;
        ++out12[j * 4 + 2];
      } else if (a < 0) {
        ++out12[j * 4 + 3];
      }
    }
  }
  return 1;
}

// CPython-compatible float64 sum: the packing-efficiency gauge
// contract is bit-equality with the host lane's builtin sum().  Which
// algorithm that is depends on the interpreter: since Python 3.12 the
// float fast path is NEUMAIER-compensated summation; before that it is
// naive left-to-right addition.  Both are provided and the ctypes
// wrapper (native/fifo.py seq_sum_f64_native) picks by interpreter
// version, so the bit-equality contract holds on either.  The optimize
// attribute pins scalar in-order codegen (vectorizing would
// reassociate).
__attribute__((optimize("no-tree-vectorize", "no-unroll-loops")))
double seq_sum_f64(const double* v, int64_t n) {
  double s = 0.0, c = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    const double x = v[i];
    const double t = s + x;
    if (std::abs(s) >= std::abs(x)) {
      c += (s - t) + x;
    } else {
      c += (x - t) + s;
    }
    s = t;
  }
  return s + c;
}

// pre-3.12 builtin sum(): plain sequential IEEE addition
__attribute__((optimize("no-tree-vectorize", "no-unroll-loops")))
double seq_sum_f64_plain(const double* v, int64_t n) {
  double s = 0.0;
  for (int64_t i = 0; i < n; ++i) s += v[i];
  return s;
}

}  // extern "C"
