// Native FIFO queue gang solver — the host-CPU lane of the batch
// solver (ops/batch_solver.py::solve_queue), for deployments without a
// TPU and for the bench's CPU fallback.
//
// Replicates the device solver's decisions BIT-EXACTLY (same capacity
// rule as reference capacity.go:36-75 with the negative-availability
// short-circuit; same first-priority driver choice binpack.go:60-87;
// same usage-subtraction quirk sparkpods.go:139-146): the parity suite
// (tests/test_native_fifo.py) runs the randomized differential against
// solve_queue for both tightly-pack and distribute-evenly.
//
// Design notes for the one-core host this runs on:
//  - per app, per-node capacity needs a floor-division per nonzero
//    executor dimension; int32/int32 division done in double is exact
//    (|numerator| < 2^31 and numerator = q*den ⟹ representable; a
//    non-integer quotient is ≥ 1/den > ulp away from any integer since
//    num·den < 2^52) and, unlike integer division, vectorizes.
//  - driver choice walks a rank-sorted candidate list (built once per
//    queue: driver_rank is constant) and computes the with-driver
//    capacity lazily — almost always a handful of probes instead of a
//    second full N-vector pass.
//  - all int32 arithmetic wraps exactly like XLA's (unsigned ops).
//
// C ABI via ctypes (k8s_spark_scheduler_tpu/native/fifo.py).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

constexpr int kDims = 3;
constexpr int32_t kBig = 2147483647;  // batch_solver.BIG

inline int32_t wrap_sub(int32_t a, int32_t b) {
  return static_cast<int32_t>(static_cast<uint32_t>(a) -
                              static_cast<uint32_t>(b));
}

// Per-node executor capacity clamped to [0, k] (capacity.go:36-75 via
// batch_solver.node_capacity): zero-requirement dim is unbounded unless
// availability is negative; any value ≤ 0 clips to 0, so truncating
// division equals the device kernel's floor division after the clip.
inline int32_t clamped_cap(const int32_t* a, const int32_t* e, int32_t k) {
  int32_t cap = k;
  for (int j = 0; j < kDims; ++j) {
    int32_t c;
    if (e[j] == 0) {
      c = a[j] >= 0 ? kBig : 0;
    } else if (a[j] <= 0) {
      c = 0;
    } else {
      c = static_cast<int32_t>(static_cast<double>(a[j]) /
                               static_cast<double>(e[j]));
    }
    cap = std::min(cap, c);
  }
  return std::max(cap, 0);
}

// Branchless capacity pass over column planes, specialized per app on
// which executor dims are nonzero (the dim pattern is constant across
// the whole node axis, so hoisting it turns the inner loop into pure
// cvtdq2pd/divpd/cvttpd2dq + min/max SIMD).  Double division of int32
// by int32 is exact: an integer quotient is representable and hit
// exactly; a non-integer one sits ≥ 1/den > ulp(q) from any integer
// (num·den < 2^52).  Negative numerators give values ≤ 0 that the final
// [0, k] clamp zeroes, matching the device kernel's floor + clip.
template <bool E0, bool E1, bool E2>
int64_t cap_pass(const int32_t* a0, const int32_t* a1, const int32_t* a2,
                 const uint8_t* exec_ok, int64_t nb, double de0, double de1,
                 double de2, int32_t k, int32_t* cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = k;
    if (E0) c = std::min(c, static_cast<int32_t>(a0[i] / de0));
    if (E1) c = std::min(c, static_cast<int32_t>(a1[i] / de1));
    if (E2) c = std::min(c, static_cast<int32_t>(a2[i] / de2));
    // zero-requirement dims bound capacity only when already overdrawn
    if (!E0) c = a0[i] >= 0 ? c : 0;
    if (!E1) c = a1[i] >= 0 ? c : 0;
    if (!E2) c = a2[i] >= 0 ? c : 0;
    c = exec_ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

using CapPassFn = int64_t (*)(const int32_t*, const int32_t*, const int32_t*,
                              const uint8_t*, int64_t, double, double, double,
                              int32_t, int32_t*);

CapPassFn select_cap_pass(const int32_t* e) {
  static constexpr CapPassFn kTable[8] = {
      cap_pass<false, false, false>, cap_pass<false, false, true>,
      cap_pass<false, true, false>,  cap_pass<false, true, true>,
      cap_pass<true, false, false>,  cap_pass<true, false, true>,
      cap_pass<true, true, false>,   cap_pass<true, true, true>,
  };
  int idx = (e[0] != 0 ? 4 : 0) | (e[1] != 0 ? 2 : 0) | (e[2] != 0 ? 1 : 0);
  return kTable[idx];
}

}  // namespace

extern "C" {

// Whole-FIFO-queue solve (batch_solver.solve_queue semantics,
// with_placements=False): scan apps in order carrying availability.
//   avail_io      [nb*3] int32 row-major — updated in place to the
//                 post-queue availability
//   driver_rank   [nb] int32 (kBig = not a driver candidate)
//   exec_ok       [nb] uint8
//   drivers/executors [na*3] int32, counts [na] int32, app_valid [na] u8
//   evenly        0 = tightly-pack fill, 1 = distribute-evenly mask
//   out_feasible  [na] uint8
//   out_driver_idx[na] int32 (= nb when infeasible)
// Scratch buffers are internal; returns 1 (always succeeds).
int fifo_solve_queue(int64_t nb, int64_t na, int32_t* avail_io,
                     const int32_t* driver_rank, const uint8_t* exec_ok,
                     const int32_t* drivers, const int32_t* executors,
                     const int32_t* counts, const uint8_t* app_valid,
                     int evenly, uint8_t* out_feasible,
                     int32_t* out_driver_idx) {
  // rank-sorted driver candidates, built once (ranks are unique)
  std::vector<int32_t> cand;
  cand.reserve(nb);
  for (int64_t i = 0; i < nb; ++i) {
    if (driver_rank[i] < kBig) cand.push_back(static_cast<int32_t>(i));
  }
  std::sort(cand.begin(), cand.end(), [&](int32_t x, int32_t y) {
    return driver_rank[x] < driver_rank[y];
  });

  // availability as column planes for the SIMD capacity pass; written
  // back to the row-major buffer at the end
  std::vector<int32_t> a0(nb), a1(nb), a2(nb), cap(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = avail_io[i * kDims + 0];
    a1[i] = avail_io[i * kDims + 1];
    a2[i] = avail_io[i * kDims + 2];
  }

  for (int64_t ai = 0; ai < na; ++ai) {
    const int32_t* d = drivers + ai * kDims;
    const int32_t* e = executors + ai * kDims;
    const int32_t k = counts[ai];
    out_feasible[ai] = 0;
    out_driver_idx[ai] = static_cast<int32_t>(nb);
    if (!app_valid[ai]) continue;

    // pass 1: per-node capacity + total S (branchless, dim-specialized)
    const double de0 = e[0] ? e[0] : 1.0, de1 = e[1] ? e[1] : 1.0,
                 de2 = e[2] ? e[2] : 1.0;
    int64_t total = select_cap_pass(e)(a0.data(), a1.data(), a2.data(),
                                       exec_ok, nb, de0, de1, de2, k,
                                       cap.data());

    // driver choice: first rank-ordered candidate that fits and leaves
    // total capacity ≥ k with the driver subtracted from its node.
    // (For fitting nodes avail−driver stays in [0, avail], so capacity
    // can only shrink and total_d ≤ total — the total < k early-out is
    // exact.)
    int32_t didx = -1;
    int32_t capd = 0;
    if (total >= k) {
      for (int32_t i : cand) {
        int32_t a[kDims] = {a0[i], a1[i], a2[i]};
        if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
        int32_t am[kDims];
        for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], d[j]);
        int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
        if (total - cap[i] + cwd >= k) {
          didx = i;
          capd = cwd;
          break;
        }
      }
    }
    if (didx < 0) continue;

    out_feasible[ai] = 1;
    out_driver_idx[ai] = didx;

    // usage subtraction (sparkpods.go:139-146 quirk): ONE executor's
    // worth per hosting node; the driver row on its node unless that
    // node also hosts executors
    auto sub_exec = [&](int64_t i) {
      a0[i] = wrap_sub(a0[i], e[0]);
      a1[i] = wrap_sub(a1[i], e[1]);
      a2[i] = wrap_sub(a2[i], e[2]);
    };
    bool driver_hosts_exec = false;
    if (evenly) {
      // hosting nodes = first k capacity-bearing nodes in node order
      int32_t placed = 0;
      for (int64_t i = 0; i < nb && placed < k; ++i) {
        int32_t c = (i == didx) ? capd : cap[i];
        if (c <= 0) continue;
        ++placed;
        if (i == didx) driver_hosts_exec = true;
        sub_exec(i);
      }
    } else {
      // tightly-pack: greedy fill in node order until k executors sit
      int64_t cum = 0;
      for (int64_t i = 0; i < nb && cum < k; ++i) {
        int32_t c = (i == didx) ? capd : cap[i];
        if (c <= 0) continue;
        cum += c;
        if (i == didx) driver_hosts_exec = true;
        sub_exec(i);
      }
    }
    if (!driver_hosts_exec) {
      a0[didx] = wrap_sub(a0[didx], d[0]);
      a1[didx] = wrap_sub(a1[didx], d[1]);
      a2[didx] = wrap_sub(a2[didx], d[2]);
    }
  }
  for (int64_t i = 0; i < nb; ++i) {
    avail_io[i * kDims + 0] = a0[i];
    avail_io[i * kDims + 1] = a1[i];
    avail_io[i * kDims + 2] = a2[i];
  }
  return 1;
}

// Single-app solve against a fixed availability (batch_solver.solve_app
// semantics): fills out_exec_counts [nb] with the tightly-pack fill
// counts and out_caps [nb] with the post-driver-placement capacities
// (AppSolve.exec_capacity — the distribute-evenly decode consumes
// these; both zeroed when infeasible).  Availability is NOT mutated.
int fifo_solve_app(int64_t nb, const int32_t* avail,
                   const int32_t* driver_rank, const uint8_t* exec_ok,
                   const int32_t* driver, const int32_t* executor,
                   int32_t k, uint8_t* out_feasible, int32_t* out_driver_idx,
                   int32_t* out_exec_counts, int32_t* out_caps) {
  *out_feasible = 0;
  *out_driver_idx = static_cast<int32_t>(nb);
  for (int64_t i = 0; i < nb; ++i) out_exec_counts[i] = 0;
  for (int64_t i = 0; i < nb; ++i) out_caps[i] = 0;

  std::vector<int32_t> cap(nb);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? clamped_cap(avail + i * kDims, executor, k) : 0;
    cap[i] = c;
    total += c;
  }
  int32_t best_rank = kBig;
  int32_t didx = -1;
  int32_t capd = 0;
  if (total >= k) {
    for (int64_t i = 0; i < nb; ++i) {
      if (driver_rank[i] >= best_rank) continue;
      const int32_t* a = avail + i * kDims;
      if (a[0] < driver[0] || a[1] < driver[1] || a[2] < driver[2]) continue;
      int32_t am[kDims];
      for (int j = 0; j < kDims; ++j) am[j] = wrap_sub(a[j], driver[j]);
      int32_t cwd = exec_ok[i] ? clamped_cap(am, executor, k) : 0;
      if (total - cap[i] + cwd >= k) {
        best_rank = driver_rank[i];
        didx = static_cast<int32_t>(i);
        capd = cwd;
      }
    }
  }
  if (didx < 0) return 1;
  *out_feasible = 1;
  *out_driver_idx = didx;
  cap[didx] = capd;
  int64_t cum = 0;
  for (int64_t i = 0; i < nb; ++i) {
    out_caps[i] = cap[i];
    if (cum < k) {
      int64_t take = std::min<int64_t>(cap[i], k - cum);
      if (take > 0) {
        out_exec_counts[i] = static_cast<int32_t>(take);
        cum += take;
      }
    }
  }
  return 1;
}

}  // extern "C"
