// Native snapshot maintainer for the TPU gang scheduler.
//
// Holds the cluster availability tensor (nodes × {cpu milli, mem bytes,
// gpu milli} as int64) in native memory, applies reservation deltas
// incrementally, and produces the GCD-scaled int32 planes the device
// solver consumes — the steady-state alternative to re-marshalling the
// whole snapshot from Python objects on every Filter request (the role
// the reference's in-memory caches play for its Go hot path,
// internal/cache + lib/pkg/resources).
//
// C ABI, consumed from Python via ctypes.  All exactness rules match
// ops/tensorize.py: values beyond int32 after scaling → not ok, caller
// falls back to the host oracle.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int kDims = 3;
constexpr int64_t kInt32Safe = 2147483647LL;

int64_t gcd64(int64_t a, int64_t b) {
  if (a < 0) a = -a;
  if (b < 0) b = -b;
  while (b != 0) {
    int64_t t = a % b;
    a = b;
    b = t;
  }
  return a;
}

struct Snapshot {
  int64_t n_nodes = 0;
  // column-major per-dimension planes for cache-friendly per-dim scans
  std::vector<int64_t> avail[kDims];
};

}  // namespace

extern "C" {

void* snap_create(int64_t n_nodes) {
  Snapshot* s = new (std::nothrow) Snapshot();
  if (s == nullptr) return nullptr;
  s->n_nodes = n_nodes;
  for (int d = 0; d < kDims; ++d) s->avail[d].assign(n_nodes, 0);
  return s;
}

void snap_destroy(void* handle) { delete static_cast<Snapshot*>(handle); }

int64_t snap_size(void* handle) { return static_cast<Snapshot*>(handle)->n_nodes; }

// Bulk-load node availability (row-major [n, 3] int64).
int snap_load(void* handle, const int64_t* avail_rows, int64_t n) {
  Snapshot* s = static_cast<Snapshot*>(handle);
  if (n != s->n_nodes) return 0;
  for (int64_t i = 0; i < n; ++i) {
    for (int d = 0; d < kDims; ++d) s->avail[d][i] = avail_rows[i * kDims + d];
  }
  return 1;
}

// Apply reservation deltas: avail[idx] -= delta (row-major [count, 3]).
// Negative deltas release capacity.  Out-of-range indices are ignored
// (defensive: the control plane validates indices).
void snap_apply_deltas(void* handle, const int32_t* node_idx,
                       const int64_t* delta_rows, int64_t count) {
  Snapshot* s = static_cast<Snapshot*>(handle);
  for (int64_t i = 0; i < count; ++i) {
    int32_t idx = node_idx[i];
    if (idx < 0 || idx >= s->n_nodes) continue;
    for (int d = 0; d < kDims; ++d) s->avail[d][idx] -= delta_rows[i * kDims + d];
  }
}

// Read the raw availability back (row-major [n, 3] int64).
void snap_read(void* handle, int64_t* out_rows) {
  Snapshot* s = static_cast<Snapshot*>(handle);
  for (int64_t i = 0; i < s->n_nodes; ++i) {
    for (int d = 0; d < kDims; ++d) out_rows[i * kDims + d] = s->avail[d][i];
  }
}

// Compute the per-dimension GCD over availability plus demand rows
// (row-major [n_demands, 3]), then emit int32-scaled planes:
//   out_avail: [node_bucket, 3] row-major int32 (zero padded)
//   out_demands: [n_demands, 3] row-major int32
//   out_scale: [3] int64 divisors
// Returns 1 if everything fits int32 after scaling, else 0 (outputs
// are then undefined and the caller must use the exact host path).
int snap_scale_int32(void* handle, const int64_t* demand_rows, int64_t n_demands,
                     int64_t node_bucket, int32_t* out_avail,
                     int32_t* out_demands, int64_t* out_scale) {
  Snapshot* s = static_cast<Snapshot*>(handle);
  const int64_t n = s->n_nodes;
  if (node_bucket < n) return 0;

  for (int d = 0; d < kDims; ++d) {
    int64_t g = 0;
    const int64_t* col = s->avail[d].data();
    for (int64_t i = 0; i < n; ++i) g = gcd64(g, col[i]);
    for (int64_t j = 0; j < n_demands; ++j) g = gcd64(g, demand_rows[j * kDims + d]);
    if (g == 0) g = 1;
    out_scale[d] = g;

    for (int64_t i = 0; i < n; ++i) {
      int64_t v = col[i] / g;
      if (v > kInt32Safe || v < -kInt32Safe) return 0;
      out_avail[i * kDims + d] = static_cast<int32_t>(v);
    }
    for (int64_t i = n; i < node_bucket; ++i) out_avail[i * kDims + d] = 0;
    for (int64_t j = 0; j < n_demands; ++j) {
      int64_t v = demand_rows[j * kDims + d] / g;
      if (v > kInt32Safe || v < -kInt32Safe) return 0;
      out_demands[j * kDims + d] = static_cast<int32_t>(v);
    }
  }
  return 1;
}

}  // extern "C"

extern "C" {

// First differing row index between two row-major [n, 3] int64 buffers,
// or -1 when equal: the delta-solve engine's exact warm-basis check
// (ops/deltasolve.py) — one memcmp-bandwidth pass instead of a numpy
// elementwise compare + reduction, and the diff index comes for free
// for diagnostics.  Blocked so the common all-equal case never drops to
// the per-row scan.
int64_t snap_rows_diff(const int64_t* a, const int64_t* b, int64_t n) {
  constexpr int64_t kBlock = 512;
  int64_t i = 0;
  while (i < n) {
    const int64_t hi = i + kBlock < n ? i + kBlock : n;
    if (std::memcmp(a + i * kDims, b + i * kDims,
                    static_cast<size_t>(hi - i) * kDims * sizeof(int64_t)) ==
        0) {
      i = hi;
      continue;
    }
    for (; i < hi; ++i) {
      if (a[i * kDims] != b[i * kDims] ||
          a[i * kDims + 1] != b[i * kDims + 1] ||
          a[i * kDims + 2] != b[i * kDims + 2]) {
        return i;
      }
    }
  }
  return -1;
}

// Equivalence-class grouping of node rows (ROADMAP 2): assign each
// row-major [n, 3] int64 row (plus a per-row uint8 schedulability flag,
// nullable = all equal) a class id in first-occurrence order via one
// open-addressing hash pass.  The capacity observatory's per-class
// headroom/frag lanes and the class index's bulk rebuild use this to
// avoid a Python-level O(n) dict pass at 100k nodes.  Returns the class
// count (classes ≤ n always holds; out_class is [n] int32).
int64_t snap_group_rows(const int64_t* rows, const uint8_t* flags, int64_t n,
                        int32_t* out_class) {
  if (n <= 0) return 0;
  uint64_t want = 16;
  while (want < static_cast<uint64_t>(n) * 2) want <<= 1;
  std::vector<int32_t> table(want, -1);
  std::vector<int64_t> reps;  // class id -> first row index
  const uint64_t mask = want - 1;
  int64_t n_classes = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t* r = rows + i * kDims;
    const uint8_t f = flags != nullptr ? flags[i] : 0;
    uint64_t h = static_cast<uint64_t>(r[0]) * 0x9E3779B97F4A7C15ull;
    h = (h ^ static_cast<uint64_t>(r[1])) * 0x9E3779B97F4A7C15ull;
    h = (h ^ static_cast<uint64_t>(r[2])) * 0x9E3779B97F4A7C15ull;
    h = (h ^ f) * 0x9E3779B97F4A7C15ull;
    h ^= h >> 32;
    uint64_t slot = h & mask;
    int32_t id = -1;
    while (true) {
      const int32_t t = table[slot];
      if (t < 0) break;
      const int64_t* q = rows + reps[t] * kDims;
      const uint8_t qf = flags != nullptr ? flags[reps[t]] : 0;
      if (q[0] == r[0] && q[1] == r[1] && q[2] == r[2] && qf == f) {
        id = t;
        break;
      }
      slot = (slot + 1) & mask;
    }
    if (id < 0) {
      id = static_cast<int32_t>(n_classes++);
      reps.push_back(i);
      table[slot] = id;
    }
    out_class[i] = id;
  }
  return n_classes;
}

// Stateless one-shot scaling (no handle): the per-request marshal path.
// Same contract as snap_scale_int32 but reads availability directly from
// the caller's buffer (row-major [n, 3] int64).
int snap_scale_rows(const int64_t* avail_rows, int64_t n,
                    const int64_t* demand_rows, int64_t n_demands,
                    int64_t node_bucket, int32_t* out_avail,
                    int32_t* out_demands, int64_t* out_scale) {
  if (node_bucket < n) return 0;
  for (int d = 0; d < kDims; ++d) {
    int64_t g = 0;
    for (int64_t i = 0; i < n; ++i) g = gcd64(g, avail_rows[i * kDims + d]);
    for (int64_t j = 0; j < n_demands; ++j) g = gcd64(g, demand_rows[j * kDims + d]);
    if (g == 0) g = 1;
    out_scale[d] = g;
    for (int64_t i = 0; i < n; ++i) {
      int64_t v = avail_rows[i * kDims + d] / g;
      if (v > kInt32Safe || v < -kInt32Safe) return 0;
      out_avail[i * kDims + d] = static_cast<int32_t>(v);
    }
    for (int64_t i = n; i < node_bucket; ++i) out_avail[i * kDims + d] = 0;
    for (int64_t j = 0; j < n_demands; ++j) {
      int64_t v = demand_rows[j * kDims + d] / g;
      if (v > kInt32Safe || v < -kInt32Safe) return 0;
      out_demands[j * kDims + d] = static_cast<int32_t>(v);
    }
  }
  return 1;
}

}  // extern "C"
