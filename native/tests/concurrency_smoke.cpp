// Sanitizer smoke for the native layer's concurrency surface.
//
// Compiled together with fifo_solver.cpp and snapshot.cpp (they are
// plain translation units with extern "C" APIs) under either
//   -fsanitize=thread            (hack/sanitize.sh tsan)
//   -fsanitize=address,undefined (hack/sanitize.sh asan)
// and run to completion.  Any sanitizer report exits nonzero, so the CI
// lanes gate on a clean run.
//
// What it exercises, and why:
//  1. stateless queue solves from many threads over SHARED read-only
//     inputs — the pattern the ROADMAP-1 parallel admission pipeline
//     will run (concurrent Filter solves against one basis);
//  2. per-thread FifoSession instances whose SweepPool worker threads
//     (condvar-coordinated sharded capacity sweeps) run CONCURRENTLY
//     with each other — the only multi-threaded code inside the
//     extension today, previously unsanitized;
//  3. session load/solve/destroy churn across threads — the engine's
//     LRU eviction frees sessions on whatever thread drops the last
//     reference, so create/destroy must be clean off the owning thread;
//  4. warm-resume parity: every session solve is checked byte-for-byte
//     against the stateless cold solve, so the smoke is also a
//     correctness harness, not just a crash test;
//  5. the snapshot maintainer API (load/apply/read/scale/rows-diff)
//     under ASan/UBSan — single-threaded by contract, but every array
//     walk and allocation is bounds- and UB-checked.
//
// Deliberately NOT exercised: concurrent calls into ONE session — the
// binding documents sessions as not thread-safe (the engine serializes
// per-session access), so sanitizing that would "prove" a contract the
// code does not offer.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// extern "C" surface under test (mirrors the ctypes bindings in
// k8s_spark_scheduler_tpu/native/__init__.py and native/fifo.py)
// ---------------------------------------------------------------------------

extern "C" {
int fifo_solve_queue(int64_t nb, int64_t na, int32_t* avail_io,
                     const int32_t* driver_rank, const uint8_t* exec_ok,
                     const int32_t* drivers, const int32_t* executors,
                     const int32_t* counts, const uint8_t* app_valid,
                     int evenly, uint8_t* out_feas, int32_t* out_didx);
int fifo_solve_queue_minfrag(int64_t nb, int64_t na, int32_t* avail_io,
                             const int32_t* driver_rank,
                             const uint8_t* exec_ok, const int32_t* drivers,
                             const int32_t* executors, const int32_t* counts,
                             const uint8_t* app_valid, uint8_t* out_feas,
                             int32_t* out_didx);
void* fifo_sess_create();
void fifo_sess_destroy(void* handle);
int fifo_sess_load(void* handle, int64_t nb, const int32_t* avail_rows,
                   const int32_t* driver_rank, const uint8_t* exec_ok,
                   int policy, int64_t stride, int n_threads,
                   int64_t min_pool_nodes);
int64_t fifo_sess_solve(void* handle, int64_t na, const int32_t* apps8,
                        uint8_t* out_feas, int32_t* out_didx,
                        int32_t* out_avail_rows);
int64_t fifo_sess_mem_bytes(void* handle);
int fifo_explain_queue(int64_t nb, int64_t na, const int32_t* avail,
                       const int32_t* driver_rank, const uint8_t* exec_ok,
                       const int32_t* apps8, int policy, int64_t target,
                       uint8_t* out_blockers, int64_t* out_info);
int fifo_probe_headroom(int64_t nb, const int32_t* avail,
                        const int32_t* driver_rank, const uint8_t* exec_ok,
                        int64_t n_shapes, const int32_t* shapes,
                        int32_t k_max, int64_t* out_headroom,
                        int64_t* out_usable, int64_t* out_probes);
int fifo_frag_report(int64_t nb, const int32_t* avail, const uint8_t* exec_ok,
                     int64_t* out12);

void* snap_create(int64_t n_nodes);
void snap_destroy(void* handle);
int64_t snap_size(void* handle);
int snap_load(void* handle, const int64_t* rows, int64_t n);
void snap_apply_deltas(void* handle, const int32_t* idx, const int64_t* deltas,
                       int64_t n);
void snap_read(void* handle, int64_t* out);
int snap_scale_int32(void* handle, const int64_t* demands, int64_t n_demands,
                     int64_t node_bucket, int32_t* out_avail,
                     int32_t* out_demands, int64_t* out_scale);
int64_t snap_rows_diff(const int64_t* a, const int64_t* b, int64_t n);
}

// ---------------------------------------------------------------------------

namespace {

constexpr int64_t kNodes = 96;
constexpr int64_t kApps = 24;

struct Fixture {
  std::vector<int32_t> avail;        // [kNodes, 3]
  std::vector<int32_t> rank;         // [kNodes]
  std::vector<uint8_t> exec_ok;      // [kNodes]
  std::vector<int32_t> drivers;      // [kApps, 3]
  std::vector<int32_t> executors;    // [kApps, 3]
  std::vector<int32_t> counts;       // [kApps]
  std::vector<uint8_t> valid;        // [kApps]
  std::vector<int32_t> apps8;        // [kApps, 8] session packing

  Fixture() {
    avail.resize(kNodes * 3);
    rank.resize(kNodes);
    exec_ok.resize(kNodes);
    for (int64_t i = 0; i < kNodes; ++i) {
      // deterministic, mildly heterogeneous capacities
      avail[i * 3 + 0] = 16 + static_cast<int32_t>(i % 7) * 4;
      avail[i * 3 + 1] = 64 + static_cast<int32_t>(i % 5) * 16;
      avail[i * 3 + 2] = (i % 11 == 0) ? 8 : 0;
      rank[i] = static_cast<int32_t>((i * 37) % kNodes);
      exec_ok[i] = (i % 9 != 0) ? 1 : 0;
    }
    drivers.resize(kApps * 3);
    executors.resize(kApps * 3);
    counts.resize(kApps);
    valid.resize(kApps);
    apps8.resize(kApps * 8);
    for (int64_t a = 0; a < kApps; ++a) {
      drivers[a * 3 + 0] = 2 + static_cast<int32_t>(a % 3);
      drivers[a * 3 + 1] = 8;
      drivers[a * 3 + 2] = 0;
      executors[a * 3 + 0] = 4;
      executors[a * 3 + 1] = 16 + static_cast<int32_t>(a % 2) * 8;
      executors[a * 3 + 2] = 0;
      counts[a] = 1 + static_cast<int32_t>(a % 5);
      valid[a] = 1;
      for (int d = 0; d < 3; ++d) {
        apps8[a * 8 + d] = drivers[a * 3 + d];
        apps8[a * 8 + 3 + d] = executors[a * 3 + d];
      }
      apps8[a * 8 + 6] = counts[a];
      apps8[a * 8 + 7] = 1;
    }
  }
};

struct Verdict {
  std::vector<uint8_t> feas;
  std::vector<int32_t> didx;
  std::vector<int32_t> avail_after;
};

Verdict cold_solve(const Fixture& fx, int64_t na, int policy) {
  Verdict v;
  v.feas.assign(na, 0);
  v.didx.assign(na, 0);
  v.avail_after = fx.avail;  // mutated in place by the solver
  if (policy == 2) {
    fifo_solve_queue_minfrag(kNodes, na, v.avail_after.data(),
                             fx.rank.data(), fx.exec_ok.data(),
                             fx.drivers.data(), fx.executors.data(),
                             fx.counts.data(), fx.valid.data(),
                             v.feas.data(), v.didx.data());
  } else {
    fifo_solve_queue(kNodes, na, v.avail_after.data(), fx.rank.data(),
                     fx.exec_ok.data(), fx.drivers.data(),
                     fx.executors.data(), fx.counts.data(), fx.valid.data(),
                     policy == 1 ? 1 : 0, v.feas.data(), v.didx.data());
  }
  return v;
}

int failures = 0;

void check(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAIL: %s\n", what);
    ++failures;
  }
}

// 1 + 4: per-thread sessions with a forced SweepPool, warm resumes
// checked byte-for-byte against the stateless cold solver.
void session_worker(const Fixture* fx, int policy, int iters) {
  void* sess = fifo_sess_create();
  check(sess != nullptr, "fifo_sess_create");
  // min_pool_nodes=1 forces the condvar pool on at this node count
  check(fifo_sess_load(sess, kNodes, fx->avail.data(), fx->rank.data(),
                       fx->exec_ok.data(), policy, /*stride=*/4,
                       /*n_threads=*/4, /*min_pool_nodes=*/1) == 1,
        "fifo_sess_load");
  for (int it = 0; it < iters; ++it) {
    // vary the queue length so warm resumes hit different checkpoints
    int64_t na = 1 + (it * 7) % kApps;
    std::vector<uint8_t> feas(na, 0);
    std::vector<int32_t> didx(na, 0);
    std::vector<int32_t> after(kNodes * 3, 0);
    int64_t resume = fifo_sess_solve(sess, na, fx->apps8.data(), feas.data(),
                                     didx.data(), after.data());
    check(resume >= 0, "fifo_sess_solve resume");
    Verdict cold = cold_solve(*fx, na, policy);
    check(std::memcmp(feas.data(), cold.feas.data(), na) == 0,
          "warm/cold feasibility parity");
    check(std::memcmp(didx.data(), cold.didx.data(), na * 4) == 0,
          "warm/cold driver-index parity");
    check(std::memcmp(after.data(), cold.avail_after.data(),
                      kNodes * 3 * 4) == 0,
          "warm/cold avail-after parity");
  }
  check(fifo_sess_mem_bytes(sess) > 0, "fifo_sess_mem_bytes");
  fifo_sess_destroy(sess);
}

// 2: stateless solves from many threads over shared read-only inputs.
void stateless_worker(const Fixture* fx, const Verdict* expect, int iters) {
  for (int it = 0; it < iters; ++it) {
    Verdict v = cold_solve(*fx, kApps, 0);
    check(v.feas == expect->feas, "stateless repeat feasibility");
    check(v.didx == expect->didx, "stateless repeat driver indices");
  }
}

// 3: create/load/destroy churn across threads.
void churn_worker(const Fixture* fx, int iters) {
  for (int it = 0; it < iters; ++it) {
    void* sess = fifo_sess_create();
    check(sess != nullptr, "churn create");
    check(fifo_sess_load(sess, kNodes, fx->avail.data(), fx->rank.data(),
                         fx->exec_ok.data(), /*policy=*/it % 2, /*stride=*/8,
                         /*n_threads=*/2, /*min_pool_nodes=*/1) == 1,
          "churn load");
    std::vector<uint8_t> feas(kApps, 0);
    std::vector<int32_t> didx(kApps, 0);
    std::vector<int32_t> after(kNodes * 3, 0);
    check(fifo_sess_solve(sess, kApps, fx->apps8.data(), feas.data(),
                          didx.data(), after.data()) >= 0,
          "churn solve");
    fifo_sess_destroy(sess);
  }
}

void exercise_snapshot_api() {
  std::vector<int64_t> rows(kNodes * 3);
  for (int64_t i = 0; i < kNodes; ++i) {
    rows[i * 3 + 0] = 16000 + (i % 7) * 4000;
    rows[i * 3 + 1] = (int64_t{64} << 30) + (i % 5) * (int64_t{16} << 30);
    rows[i * 3 + 2] = (i % 11 == 0) ? 8000 : 0;
  }
  void* snap = snap_create(kNodes);
  check(snap != nullptr, "snap_create");
  check(snap_load(snap, rows.data(), kNodes) == 1, "snap_load");
  check(snap_size(snap) == kNodes, "snap_size");
  // delta rows are [count, 3]; rows 1+2 cancel out on node 5, row 3
  // targets an out-of-range index and must be ignored
  std::vector<int32_t> idx = {0, 5, 5, static_cast<int32_t>(kNodes)};
  std::vector<int64_t> deltas = {
      1000, 0,     0,   // node 0: -1000 cpu
      2000, 1 << 20, 0, // node 5: reserve …
      -2000, -(1 << 20), 0,  // … and release (cancels)
      77,   99,    11,  // ignored (index out of range)
  };
  snap_apply_deltas(snap, idx.data(), deltas.data(),
                    static_cast<int64_t>(idx.size()));
  std::vector<int64_t> out(kNodes * 3, 0);
  snap_read(snap, out.data());
  check(out[0] == rows[0] - 1000, "snap delta applied");
  check(out[5 * 3] == rows[5 * 3], "snap cancelled delta");
  check(snap_rows_diff(rows.data(), rows.data(), kNodes) == -1,
        "snap_rows_diff equal");
  check(snap_rows_diff(rows.data(), out.data(), kNodes) == 0,
        "snap_rows_diff first-diff index");
  std::vector<int64_t> demands = {2000, int64_t{8} << 30, 0,
                                  4000, int64_t{16} << 30, 0};
  std::vector<int32_t> out_avail(kNodes * 3, 0);
  std::vector<int32_t> out_dem(2 * 3, 0);
  std::vector<int64_t> out_scale(3, 1);
  check(snap_scale_int32(snap, demands.data(), 2, kNodes, out_avail.data(),
                         out_dem.data(), out_scale.data()) == 1,
        "snap_scale_int32");
  snap_destroy(snap);
}

void exercise_diagnostics(const Fixture& fx) {
  std::vector<uint8_t> blockers(kApps, 0);
  std::vector<int64_t> info(12, 0);
  check(fifo_explain_queue(kNodes, kApps, fx.avail.data(), fx.rank.data(),
                           fx.exec_ok.data(), fx.apps8.data(), /*policy=*/0,
                           /*target=*/kApps - 1, blockers.data(),
                           info.data()) == 1,
        "fifo_explain_queue");
  std::vector<int32_t> shapes = {2, 8, 0, 4, 16, 0};
  std::vector<int64_t> headroom(1, 0), usable(3, 0), probes(1, 0);
  check(fifo_probe_headroom(kNodes, fx.avail.data(), fx.rank.data(),
                            fx.exec_ok.data(), 1, shapes.data(),
                            /*k_max=*/64, headroom.data(), usable.data(),
                            probes.data()) == 1,
        "fifo_probe_headroom");
  std::vector<int64_t> frag(12, 0);
  check(fifo_frag_report(kNodes, fx.avail.data(), fx.exec_ok.data(),
                         frag.data()) == 1,
        "fifo_frag_report");
}

}  // namespace

int main() {
  Fixture fx;

  // correctness anchor: the first app of the fixture must fit cold
  Verdict expect = cold_solve(fx, kApps, 0);
  check(expect.feas[0] == 1, "fixture head app feasible");

  // phase 1: concurrent stateless solves (shared inputs)
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < 4; ++t) {
      ts.emplace_back(stateless_worker, &fx, &expect, 25);
    }
    for (auto& t : ts) t.join();
  }

  // phase 2: concurrent sessions, each with its own 4-worker SweepPool,
  // across all three policies, warm≡cold checked per solve
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < 3; ++t) {
      ts.emplace_back(session_worker, &fx, /*policy=*/t, 20);
    }
    for (auto& t : ts) t.join();
  }

  // phase 3: create/load/solve/destroy churn
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < 4; ++t) {
      ts.emplace_back(churn_worker, &fx, 10);
    }
    for (auto& t : ts) t.join();
  }

  // phase 4: snapshot + diagnostics APIs (ASan/UBSan value)
  exercise_snapshot_api();
  exercise_diagnostics(fx);

  if (failures != 0) {
    std::fprintf(stderr, "concurrency_smoke: %d failure(s)\n", failures);
    return 1;
  }
  std::printf("concurrency_smoke: OK\n");
  return 0;
}
