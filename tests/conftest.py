"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The container's sitecustomize registers the axon TPU tunnel and imports
jax before any test code runs, so setting JAX_PLATFORMS here is too
late — instead update the live config.  XLA_FLAGS still works because
the CPU backend initializes lazily on first device use.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices exactly as the driver's
``dryrun_multichip`` does.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
