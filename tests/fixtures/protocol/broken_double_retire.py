"""Broken twin of the commit-fifo scenario's request(): the abort path
retires inside the try AND again in the finally — the second retire
releases someone else's commit turn.  PC002 fixture."""


class BrokenRequest:
    def request(self, st, abort):
        ticket = st.gate.ticket()
        try:
            if abort:
                st.gate.retire(ticket, False)
                return
            st.gate.await_turn(ticket)
        finally:
            st.gate.retire(ticket, True)
