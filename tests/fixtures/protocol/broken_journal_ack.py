"""Broken twin of the write-back worker: ``finally: ack`` acknowledges
the intent even when the kube write raised — the intent is lost AND the
write never happened (breaks the I-P4/J1 exactly-once contract).
PC004 fixture."""


class BrokenWorker:
    def run_one(self, r):
        self._journal.record("create", r.kind, r.ns, r.name, r.obj)
        try:
            self._client.create(r.kind, r.ns, r.obj)
        finally:
            self._journal.ack("create", r.ns, r.name)
