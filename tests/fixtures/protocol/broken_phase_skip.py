"""Broken twin of the extender's phase ladder: the binpack boundary is
crossed without re-arming the deadline check — an expired request burns
the solver's budget before failing.  PC006 fixture."""


class BrokenExtender:
    def select(self, ctx):
        self._check_deadline("fifo-gate")
        fitted = self._try_device_fifo(ctx)
        if fitted is None:
            fitted = self._fit_earlier_drivers(ctx)
        with self._tracer.span("binpack"):
            plan = self.binpacker.binpack(ctx)
        self._check_deadline("reservation-writeback")
        self._rrm.create_reservations(plan)
        return plan
