"""Broken twin of a handler that opens a span and a lock manually:
early returns skip the close.  PC005 fixture."""


class BrokenHandler:
    def handle(self, req):
        span = self._tracer.span("request")
        span.__enter__()
        if req.bad:
            return None
        result = self._process(req)
        span.__exit__(None, None, None)
        return result

    def try_lock(self):
        self._stats_lock.acquire()
        if self._busy:
            return False
        self._stats_lock.release()
        return True
