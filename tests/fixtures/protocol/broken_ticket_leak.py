"""Broken twin of ConcurrentAdmissionEngine.predicate (pre-PR19 shape):
``finish`` raising inside the finally skips the retire, leaking the
FIFO ticket and stalling the commit line forever.  PC001 fixture."""


class BrokenPredicate:
    def predicate(self, args):
        ticket = self.gate.ticket()
        committed = False
        try:
            verdict = self.speculator.speculate(ticket, args)
            result = self.commit(args, verdict)
            committed = True
            return result
        finally:
            self.speculator.finish(ticket)
            self.gate.retire(ticket, committed)
