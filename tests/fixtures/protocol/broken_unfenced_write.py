"""Broken twin of PreemptionCoordinator.recover (pre-PR19): replaying
evict intents executes pod deletes with no fence check — a deposed
replica could still write.  PC003 fixture."""
# schedlint: entrypoints=BrokenCoordinator.recover


class BrokenCoordinator:
    def commit(self, plan):
        gate = self.fence_gate
        if gate is not None:
            gate.check("preempt.commit")
        for victim in plan.victims:
            self._execute(victim.ns, victim.app_id)

    def _execute(self, ns, app_id):
        self._api.delete("Pod", ns, app_id)

    def recover(self):
        for intent in self._journal.pending():
            self._execute(intent["ns"], intent["name"])
